"""Offline request-trace report over a flushed JSONL event log.

Runs the same assembly as ``Telemetry.request_traces()``
(``obs/tracing.py``) against a log file on disk — no live process
needed. Prints the per-request latency decomposition table (queue /
prefill / decode / sync / failover columns summing exactly to
end-to-end latency), the per-tenant-class rollup, and — given
``--slo`` targets — the SLO-miss attribution report ("interactive p99
TTFT miss = 78% class-queue wait"). Optionally exports the stitched
Chrome trace (request segments only: spans live in the recorder, not
the event log).

Usage:
    python tools/trace_report.py logs/serve.jsonl
    python tools/trace_report.py logs/serve.jsonl --slo interactive=4.0 \\
        --slo batch=50 --trace-out trace.json --json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

from ray_lightning_tpu.obs import tracing  # noqa: E402


def _parse_slo(pairs):
    slo = {}
    for pair in pairs or []:
        try:
            tenant, _, value = pair.partition("=")
            slo[tenant] = float(value)
        except ValueError:
            raise SystemExit(
                f"--slo expects class=target (e.g. interactive=4.0), "
                f"got {pair!r}")
    return slo


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-request latency decomposition + SLO-miss "
                    "attribution over a flushed obs JSONL log")
    ap.add_argument("jsonl", help="event log written by "
                                  "Telemetry(jsonl_path=...) + flush()")
    ap.add_argument("--slo", action="append", metavar="CLASS=TARGET",
                    help="TTFT SLO target per tenant class (client "
                         "clock units); repeatable")
    ap.add_argument("--trace-out", metavar="PATH",
                    help="also export the stitched Chrome trace "
                         "(request segments; load in Perfetto)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output: one JSON document "
                         "instead of tables")
    args = ap.parse_args(argv)

    events = tracing.load_jsonl_events(args.jsonl)
    traces = tracing.assemble_request_traces(events)
    slo = _parse_slo(args.slo)

    if args.trace_out:
        # offline stitching has no SpanRecorder: a stand-in telemetry
        # with no spans and the tick clock keeps the export pure-event
        class _NoSpans:
            @staticmethod
            def spans():
                return []

        class _Offline:
            clock = None
            spans = _NoSpans()

        tracing.export_fleet_chrome_trace(args.trace_out, _Offline(),
                                          traces)

    if args.json:
        doc = {
            "requests": tracing.decomposition_rows(traces),
            "tenants": tracing.tenant_rollup(traces),
        }
        if slo:
            doc["slo"] = tracing.slo_miss_attribution(traces, slo)
        print(json.dumps(doc, sort_keys=True, default=str))
        return 0

    if not traces:
        print(f"no request traces in {args.jsonl} "
              f"({len(events)} events)")
        return 0
    print(tracing.format_decomposition(traces))
    if slo:
        print()
        print("SLO-miss attribution (pre-first-token time of missed "
              "requests):")
        print(tracing.format_slo_report(traces, slo))
    if args.trace_out:
        print(f"\nChrome trace written to {args.trace_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
