"""Offline report over a serve write-ahead journal (WAL) file.

Folds a journal written by ``ray_lightning_tpu.serve.Journal`` with the
same reader a warm restart uses (``read_journal``) and prints what a
restart would see: admitted / retired / unretired counts, the finish-
reason breakdown, and — per unretired request — the journaled token
frontier a restore would replay from. Damage is diagnosed honestly:
a torn final record (the interrupted append a driver kill leaves) is
reported and tolerated; mid-file damage or a newer-schema journal is
reported as corrupt with the reader's verdict, and the tool exits
nonzero.

Usage:
    python tools/journal_report.py /path/to/serve.wal
    python tools/journal_report.py /path/to/serve.wal --json
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

from ray_lightning_tpu.serve.journal import (JournalCorrupt,  # noqa: E402
                                             read_journal)


def _pending_rows(state):
    rows = []
    for req, toks in state.pending():
        rows.append({
            "id": req.id,
            "prompt_len": len(req.prompt),
            "frontier": len(toks),
            "max_new_tokens": req.max_new_tokens,
            "greedy": not req.temperature,
            "tenant": req.tenant,
            "adapter": req.adapter,
            "first_token_seen": req.first_token_time is not None,
        })
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="admitted/retired/unretired story of a serve "
                    "write-ahead journal, with torn-tail diagnosis")
    ap.add_argument("journal", help="WAL file written by "
                                    "ServeClient(journal=Journal(...)) "
                                    "or ReplicaFleet(journal=...)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output: one JSON document "
                         "instead of tables")
    args = ap.parse_args(argv)

    try:
        state = read_journal(args.journal)
    except FileNotFoundError:
        print(f"no journal at {args.journal}", file=sys.stderr)
        return 1
    except JournalCorrupt as exc:
        if args.json:
            print(json.dumps({"corrupt": True, "detail": str(exc)}))
        else:
            print(f"CORRUPT: {exc}", file=sys.stderr)
            print("(a torn FINAL record is tolerated; this journal is "
                  "damaged earlier than the tail, so a warm restart "
                  "would refuse it too)", file=sys.stderr)
        return 1

    pending = _pending_rows(state)
    reasons = collections.Counter(state.retired.values())
    doc = {
        "path": state.path,
        "generation": state.generation,
        "schema_version": state.schema_version,
        "records": state.records,
        "torn_tail": state.torn_tail,
        "duplicate_retires": state.duplicate_retires,
        "admitted": len(state.admitted),
        "retired": len(state.retired),
        "unretired": len(pending),
        "finish_reasons": dict(sorted(reasons.items())),
        "next_request_id": state.next_request_id,
        "pending": pending,
    }
    if args.json:
        print(json.dumps(doc, sort_keys=True))
        return 0

    print(f"journal {state.path}: generation {state.generation}, "
          f"schema v{state.schema_version}, {state.records} records")
    print(f"  admitted {len(state.admitted)}, retired "
          f"{len(state.retired)}"
          + (" ({})".format(", ".join(f"{n} {r}" for r, n
                                      in sorted(reasons.items())))
             if reasons else "")
          + f", unretired {len(pending)}")
    print(f"  next_request_id {state.next_request_id}")
    if state.torn_tail:
        print("  torn tail: the final record is half-written — the "
              "append a driver kill interrupted. Dropped by the "
              "reader; everything above it is intact.")
    if state.duplicate_retires:
        print(f"  WARNING: {state.duplicate_retires} duplicate retire "
              "record(s) — the writer dedupes these, so this journal "
              "was not written by a single healthy Journal instance")
    if pending:
        print("\nunretired requests (what a warm restart replays):")
        print("  id  prompt  frontier  budget  sampling  tenant"
              "          adapter         first_token")
        for row in pending:
            print(f"  {row['id']:>2d}  {row['prompt_len']:>6d}  "
                  f"{row['frontier']:>8d}  {row['max_new_tokens']:>6d}"
                  f"  {'greedy' if row['greedy'] else 'sampled':>8s}"
                  f"  {row['tenant'] or '-':<14s}"
                  f"  {row['adapter'] or '-':<14s}"
                  f"  {'seen' if row['first_token_seen'] else '-'}")
    else:
        print("\nno unretired requests: a warm restart replays "
              "nothing (clean shutdown or fully drained run)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
