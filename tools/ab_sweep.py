"""Interleaved A/B sweep harness for model-zoo levers on the real chip.

The measurement discipline proven in round 4 on GPT-2 (docs/performance.md
"Measurement integrity"), packaged: candidate configs are measured in
alternating full passes within ONE session — A/B/A/B… — so the axon
tunnel's session jitter hits every candidate equally and the RATIO between
bests is trustworthy even when absolute rates drift.

Round-5 use (VERDICT #6): sweep ``save_attn`` remat and the
``make_optimizer`` presets over the ViT and MoE-LM families; results in
docs/performance.md, winning defaults shipped in the examples.

Usage (real chip) — one sweep per model family in ``SWEEPS``:
    python tools/ab_sweep.py vit      # remat space + adafactor
    python tools/ab_sweep.py moe      # remat space + optimizer presets
    python tools/ab_sweep.py gpt2     # flagship remat space (drift check)
    python tools/ab_sweep.py bert     # save_attn vs dots_nb (drift check)
    python tools/ab_sweep.py seq2seq  # encoder-decoder remat space

Prints one JSON line per candidate: {"name", "samples_per_sec", "best_of"}
plus a final {"winner": ...} line with ratios vs the first (baseline)
candidate.
"""
from __future__ import annotations

import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(REPO, "bench.py"))
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _build_seq2seq_step(strategy, batch_size: int, src_len: int = 256,
                        tgt_len: int = 256, **cfg_overrides):
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_lightning_tpu.core.optim import make_optimizer
    from ray_lightning_tpu.models.seq2seq import Seq2SeqTransformer
    from ray_lightning_tpu.models.transformer import TransformerConfig

    opt_name = cfg_overrides.pop("optimizer", "adamw")
    cfg = TransformerConfig(vocab_size=50304, max_seq_len=max(src_len,
                                                              tgt_len),
                            d_model=512, n_heads=8, n_layers=6,
                            d_ff=2048, causal=True, dtype=jnp.bfloat16,
                            scan_layers=False, **cfg_overrides)
    model = Seq2SeqTransformer(cfg)
    tx = make_optimizer(opt_name, learning_rate=1e-3)
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(0, 50257, (batch_size, src_len)),
                      jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 50257, (batch_size, tgt_len + 1)),
                      jnp.int32)

    def loss_fn(params, model_state, batch, rng):
        bsrc, btgt = batch
        logits = model.apply({"params": params}, bsrc, btgt[:, :-1])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, btgt[:, 1:]).mean()
        return loss, ({}, model_state)

    # init with 1-example shapes, measure at full batch
    return bench._assemble_step(
        strategy, _Seq2SeqInitAdapter(model), tx, loss_fn,
        (src[:1], tgt[:1]), (src, tgt))


class _Seq2SeqInitAdapter:
    """Adapts the two-input seq2seq model to _assemble_step's
    single-init-batch contract (init_batch arrives as an (src, tgt)
    tuple; flax wants them positional). Only ``init`` is consumed —
    the loss_fn closes over the real model for apply."""

    def __init__(self, model):
        self._model = model

    def init(self, rng, init_batch):
        src, tgt = init_batch
        return self._model.init(rng, src, tgt[:, :-1])


SWEEPS = {
    "seq2seq": {
        # encoder-decoder family: remat-space drift check (cross-attention
        # adds a third dot per decoder block — attention flop share is
        # higher than BERT's at the same T)
        "build": _build_seq2seq_step,
        "batch_size": 16,
        "candidates": [
            ("no_remat", {}),
            ("remat_dots_nb", {"remat": True,
                               "remat_policy":
                                   "dots_with_no_batch_dims"}),
            ("remat_save_attn", {"remat": True,
                                 "remat_policy":
                                     "dots_with_no_batch_dims_save_attn"}),
        ],
    },
    "vit": {
        "build": bench._build_vit_step,
        # 4 candidates' train states live simultaneously (interleaving
        # needs them all warm); bs 32 keeps the sum under the 16 GB chip
        "batch_size": 32,
        "candidates": [
            # explicit remat=False: vit_config ships remat+save_attn as
            # its default since this sweep measured the win, so an empty
            # override would silently measure the winner against itself
            ("no_remat", {"remat": False, "remat_policy": None}),
            ("remat_dots_nb", {"remat": True,
                               "remat_policy":
                                   "dots_with_no_batch_dims"}),
            ("remat_save_attn", {"remat": True,
                                 "remat_policy":
                                     "dots_with_no_batch_dims_save_attn"}),
            ("no_remat_adafactor", {"remat": False, "remat_policy": None,
                                    "optimizer": "adafactor"}),
        ],
    },
    "bert": {
        # same runtime-drift re-check as gpt2: bert shipped save_attn on
        # a +1.0-1.2% round-4 margin that the new compiler may have
        # reversed (it reversed gpt2-small's +9.6%)
        "build": lambda strategy, batch_size, **o: bench._build_bert_step(
            strategy, batch_size, 128, **o),
        "batch_size": 128,
        "candidates": [
            # explicit (not the builder default) so a future default flip
            # can't turn this into a self-comparison — same guard as vit
            ("save_attn", {"remat_policy":
                           "dots_with_no_batch_dims_save_attn"}),
            ("dots_nb", {"remat_policy": "dots_with_no_batch_dims"}),
        ],
    },
    "gpt2": {
        # flagship layout re-check under runtime/compiler drift: the
        # round-4 winner (save_attn) lost ~10% MFU across round-5
        # sessions while BERT gained — re-measure the remat space in one
        # session before attributing it to the environment
        "build": lambda strategy, batch_size, **o: bench._build_gpt2_step(
            strategy, batch_size, 512, size="small", **o),
        "batch_size": 8,
        "candidates": [
            ("save_attn", {"remat_policy":
                           "dots_with_no_batch_dims_save_attn"}),
            ("dots_nb", {"remat_policy": "dots_with_no_batch_dims"}),
            ("no_remat", {"remat_policy": "none"}),
            ("full_remat", {"remat_policy": "full"}),
        ],
    },
    "moe": {
        # bench's moe builder ships the sweep winner (adafactor) as its
        # default, so candidates name the optimizer EXPLICITLY — an empty
        # override would self-compare against the winner
        "build": bench._build_moe_step,
        "batch_size": 16,
        "candidates": [
            ("no_remat_adamw", {"optimizer": "adamw"}),
            ("remat_dots_nb_adamw", {"optimizer": "adamw", "remat": True,
                                     "remat_policy":
                                         "dots_with_no_batch_dims"}),
            ("remat_save_attn_adamw",
             {"optimizer": "adamw", "remat": True,
              "remat_policy": "dots_with_no_batch_dims_save_attn"}),
            ("no_remat_adafactor", {"optimizer": "adafactor"}),
        ],
    },
}


def run_sweep(which: str, pairs: int = 4) -> dict:
    import jax

    from ray_lightning_tpu import RayStrategy

    spec = SWEEPS[which]
    n_chips = len(jax.devices())
    strategy = RayStrategy(num_workers=n_chips, use_tpu=True)
    bs = spec["batch_size"]

    built = []
    for name, overrides in spec["candidates"]:
        try:
            step, state, batch = spec["build"](strategy, batch_size=bs,
                                               **dict(overrides))
            flops = bench._step_flops(step, state, batch)
            built.append((name, step, state, batch, flops))
        except Exception as exc:  # e.g. OOM at this layout: record, go on
            print(json.dumps({"name": name,
                              "error": f"{type(exc).__name__}: {exc}"}))
    chip_peak = bench._chip_peak_flops(jax.devices()[0])
    peak = chip_peak * n_chips if chip_peak else None

    best: dict = {}
    dead: set = set()
    for _ in range(pairs):  # interleave full passes across ALL candidates
        for name, step, state, batch, flops in built:
            if name in dead:
                continue
            try:
                out = bench._measure_rate(step, state, batch, bs, flops,
                                          peak)
            except Exception as exc:  # OOM at this layout: record, go on
                dead.add(name)
                print(json.dumps({"name": name,
                                  "error": f"{type(exc).__name__}: "
                                           f"{exc}"[:300]}))
                continue
            if name not in best or out["samples_per_sec"] > \
                    best[name]["samples_per_sec"]:
                best[name] = out
    if not best:
        print(json.dumps({"sweep": which, "batch_size": bs,
                          "error": "every candidate failed"}))
        return {}
    baseline = spec["candidates"][0][0]
    # a dead baseline (e.g. the memory-hungry no-remat candidate OOMs)
    # must not kill the report: fall back to the first surviving
    # candidate as the ratio base and say so
    if baseline not in best:
        baseline = next(n for n, *_ in built if n in best)
        print(json.dumps({"note": f"baseline dead; ratios vs {baseline}"}))
    report = {}
    for name, out in best.items():
        report[name] = {
            "samples_per_sec": round(out["samples_per_sec"], 2),
            "vs_baseline": round(out["samples_per_sec"]
                                 / best[baseline]["samples_per_sec"], 4),
        }
        print(json.dumps({"name": name, **report[name]}))
    winner = max(report, key=lambda k: report[k]["samples_per_sec"])
    print(json.dumps({"winner": winner, "sweep": which,
                      "batch_size": bs, "report": report}))
    return report


if __name__ == "__main__":
    run_sweep(sys.argv[1] if len(sys.argv) > 1 else "vit")
