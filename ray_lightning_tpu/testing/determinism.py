"""Determinism checking — the closest thing the reference has to a race
detector is its deterministic-seed plumbing (``PL_GLOBAL_SEED`` forwarding
+ per-worker ``reset_seed``, SURVEY.md §5); this utility turns that into
an executable assertion users can run against their own modules.

On TPU, determinism is a stronger claim than on GPU (no atomics-order
nondeterminism in XLA reductions), so same-seed same-params is the
expected contract — a failure means host-side state leaked into the step
(unseeded numpy/python RNG, time-dependent data order, stateful modules).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np


def fit_fingerprint(trainer) -> np.ndarray:
    """A flat host digest of the trainer's final params.

    Works on both recovery paths: live arrays (local launch) and the
    host state dict a remote launch leaves on the driver
    (``trainer.train_state_dict``, core/trainer.py ``_recover_results``).
    """
    if trainer.train_state is not None:
        params = jax.device_get(trainer.train_state.params)
    elif getattr(trainer, "train_state_dict", None) is not None:
        params = trainer.train_state_dict["params"]
    else:
        raise ValueError("trainer holds no trained state — fit first")
    leaves = jax.tree_util.tree_leaves(params)
    return np.concatenate([np.ravel(np.asarray(x, np.float64))
                           for x in leaves])


def assert_deterministic(module_factory: Callable[[], Any],
                         trainer_factory: Callable[[], Any],
                         rtol: float = 0.0, atol: float = 0.0,
                         datamodule_factory: Optional[Callable] = None
                         ) -> np.ndarray:
    """Fit twice from fresh modules/trainers; assert identical params.

    Factories must build the run from scratch (a reused module or trainer
    would share state and defeat the check). Default tolerance is EXACT
    (rtol=atol=0) — same seed, same mesh, same XLA program must produce
    bit-identical results; loosen only when comparing across layouts.
    Returns the fingerprint so callers can also compare across configs.
    """
    prints = []
    for _ in range(2):
        trainer = trainer_factory()
        if trainer.seed is None:
            raise ValueError(
                "assert_deterministic needs Trainer(seed=...) — an "
                "unseeded run is allowed to differ from itself")
        dm = datamodule_factory() if datamodule_factory else None
        trainer.fit(module_factory(), datamodule=dm)
        prints.append(fit_fingerprint(trainer))
    if rtol == 0.0 and atol == 0.0:
        if prints[0].shape != prints[1].shape:
            raise AssertionError(
                f"two same-seed fits diverged: parameter count changed "
                f"({prints[0].size} vs {prints[1].size} elements) — the "
                "model shape itself depends on host state (e.g. a "
                "feature dim read from unseeded data)")
        # equal_nan: identical NaN patterns ARE deterministic (a NaN loss
        # is a training problem, not a determinism failure)
        if not np.array_equal(prints[0], prints[1], equal_nan=True):
            diff = np.abs(prints[0] - prints[1])
            raise AssertionError(
                f"two same-seed fits diverged: "
                f"max|Δ|={np.nanmax(diff):.3e} "
                f"over {int(np.count_nonzero(diff))}/{diff.size} "
                "elements — host-side state is leaking into training "
                "(unseeded RNG, order-dependent data loading, or "
                "stateful module attributes)")
    else:
        np.testing.assert_allclose(prints[0], prints[1], rtol=rtol,
                                   atol=atol)
    return prints[0]
