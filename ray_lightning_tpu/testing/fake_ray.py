"""In-process ray-compatible fakes: synchronous and threaded-concurrent.

Implements the exact subset of the Ray API the launcher consumes —
``init/is_initialized/remote/put/get/wait/kill`` plus the actor
``.options(...).remote()`` / ``method.remote(...)`` protocol — in two
flavors:

- :class:`FakeRay` — **synchronous**: remote calls run immediately
  in-process and return pre-resolved refs. Fast, deterministic; the seam
  most launcher unit tests use.
- :class:`ThreadedFakeRay` — **concurrent**: each actor owns a
  single-thread executor (Ray's actor model: one message at a time per
  actor, actors concurrent with each other); ``method.remote`` returns a
  future-backed ref, ``ray.wait`` genuinely polls completion, and every
  task's args cross a real pickle boundary (round-1 verdict: the sync
  fake's ``execute.remote`` args never crossed serialization, so the
  per-dispatch payload — trainer ref, rank map, queue — was untested).

Both enforce the serialization-boundary rule the reference documents at
``ray_launcher.py:274-288``: ``put`` (and, in the threaded fake, task
args) round-trip through pickle, so anything unpicklable (actor handles,
jitted functions, device arrays) fails in tests exactly where it would
fail on a cluster. :class:`FakeQueueHandle` pickles *by reference* the way
a Ray queue's actor handle does, so queues survive the boundary while
still funneling to one driver-side queue.

This is the test seam the reference gets from local Ray clusters
(``tests/test_ddp.py:20-61``); combined with fake executor classes injected
via :func:`~ray_lightning_tpu.launchers.utils.set_executable_cls` it covers
rank mapping, env brokering, concurrent dispatch, and the full
launch→collect→recover pipeline without Ray installed.
"""
from __future__ import annotations

import itertools
import pickle
import queue as _queue
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple


class FakeObjectRef:
    """Stand-in for ``ray.ObjectRef``: pre-resolved value or live future."""
    _is_fake_object_ref = True

    def __init__(self, value: Any = None, future: Optional[Future] = None):
        self._value = value
        self._future = future

    @property
    def value(self) -> Any:
        if self._future is not None:
            return self._future.result()
        return self._value

    def done(self) -> bool:
        return self._future is None or self._future.done()

    def __repr__(self) -> str:
        if self._future is not None and not self._future.done():
            return "FakeObjectRef(<pending>)"
        return f"FakeObjectRef({type(self.value).__name__})"


def _resolve(obj: Any) -> Any:
    return obj.value if isinstance(obj, FakeObjectRef) else obj


class FakeActorMethod:
    def __init__(self, handle: "FakeActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args: Any, **kwargs: Any) -> FakeObjectRef:
        handle = self._handle
        if handle._killed:
            raise RuntimeError("Actor was killed")
        backend = handle._backend
        args = tuple(_resolve(a) for a in args)
        kwargs = {k: _resolve(v) for k, v in kwargs.items()}
        if backend is not None and backend.serialize_task_args:
            args, kwargs = pickle.loads(pickle.dumps((args, kwargs)))
        method = getattr(handle._instance, self._name)
        if handle._pool is not None:
            return FakeObjectRef(future=handle._pool.submit(
                method, *args, **kwargs))
        return FakeObjectRef(method(*args, **kwargs))


class FakeActorHandle:
    def __init__(self, instance: Any, options: Dict[str, Any],
                 backend: Optional["FakeRay"] = None,
                 concurrent: bool = False):
        self._instance = instance
        self._options = options
        self._backend = backend
        self._killed = False
        # Ray's actor model: one message processed at a time per actor,
        # actors concurrent with each other → one thread per actor.
        self._pool = ThreadPoolExecutor(max_workers=1) if concurrent else None

    def __getattr__(self, name: str) -> FakeActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return FakeActorMethod(self, name)

    def _shutdown_pool(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=False)


class FakeRemoteClass:
    def __init__(self, cls: type, backend: "FakeRay"):
        self._cls = cls
        self._backend = backend
        self._options: Dict[str, Any] = {}

    def options(self, **options: Any) -> "FakeRemoteClass":
        out = FakeRemoteClass(self._cls, self._backend)
        out._options = options
        return out

    def remote(self, *args: Any, **kwargs: Any) -> FakeActorHandle:
        backend = self._backend
        handle = FakeActorHandle(self._cls(*args, **kwargs),
                                 dict(self._options), backend=backend,
                                 concurrent=backend.concurrent)
        backend.created_actors.append(handle)
        return handle


class FakeQueueHandle:
    """A queue that pickles *by reference* (like a Ray queue actor handle):
    every unpickled copy funnels to the same in-process queue."""

    _registry: Dict[int, _queue.Queue] = {}
    _ids = itertools.count()

    def __init__(self, _id: Optional[int] = None):
        if _id is None:
            _id = next(FakeQueueHandle._ids)
            FakeQueueHandle._registry[_id] = _queue.Queue()
        self._id = _id

    def __reduce__(self):
        return (FakeQueueHandle, (self._id,))

    @property
    def _q(self) -> _queue.Queue:
        return FakeQueueHandle._registry[self._id]

    def put(self, item: Any) -> None:
        self._q.put(item)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        return self._q.get(block=block, timeout=timeout)

    def empty(self) -> bool:
        return self._q.empty()

    def shutdown(self) -> None:
        FakeQueueHandle._registry.pop(self._id, None)


class FakeRay:
    """Drop-in module-like object for ``RayLauncher(ray_module=...)``."""

    ObjectRef = FakeObjectRef
    concurrent = False

    def __init__(self, serialize_puts: bool = True,
                 serialize_task_args: bool = False):
        self._initialized = False
        self.serialize_puts = serialize_puts
        self.serialize_task_args = serialize_task_args
        self.created_actors: List[FakeActorHandle] = []
        self.killed_actors: List[FakeActorHandle] = []

    # -- lifecycle ----------------------------------------------------- #
    def init(self, *args: Any, **kwargs: Any) -> None:
        self._initialized = True

    def is_initialized(self) -> bool:
        return self._initialized

    def shutdown(self) -> None:
        self._initialized = False

    # -- object store -------------------------------------------------- #
    def put(self, obj: Any) -> FakeObjectRef:
        if self.serialize_puts:
            obj = pickle.loads(pickle.dumps(obj))
        return FakeObjectRef(obj)

    def get(self, refs: Any, timeout: Optional[float] = None) -> Any:
        if isinstance(refs, list):
            return [_resolve(r) for r in refs]
        return _resolve(refs)

    def wait(self, refs: List[Any], num_returns: int = 1,
             timeout: Optional[float] = None
             ) -> Tuple[List[Any], List[Any]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            done = [r for r in refs
                    if not isinstance(r, FakeObjectRef) or r.done()]
            if len(done) >= num_returns or (
                    deadline is not None
                    and time.monotonic() >= deadline):
                # ray.wait caps the ready set at num_returns even when more
                # have finished; the rest stay in the unfinished list.
                ready = done[:num_returns]
                return ready, [r for r in refs if r not in ready]
            time.sleep(0.002)  # tl-lint: allow-sleep — ray.wait poll quantum (wall-clock by contract)

    # -- actors -------------------------------------------------------- #
    def remote(self, cls: type) -> FakeRemoteClass:
        return FakeRemoteClass(cls, self)

    def kill(self, actor: FakeActorHandle, no_restart: bool = False) -> None:
        actor._killed = True
        actor._shutdown_pool()
        self.killed_actors.append(actor)


class ThreadedFakeRay(FakeRay):
    """Concurrent fake: actors run in their own threads, task args cross
    pickle, ``wait`` genuinely polls. The closest no-Ray approximation of
    a local cluster's scheduling semantics."""

    concurrent = True

    def __init__(self, serialize_puts: bool = True,
                 serialize_task_args: bool = True):
        super().__init__(serialize_puts=serialize_puts,
                         serialize_task_args=serialize_task_args)

    def make_queue(self) -> FakeQueueHandle:
        # The launcher prefers a backend-supplied queue; this one survives
        # the task-arg pickle boundary by reference, like Ray's.
        return FakeQueueHandle()


class RecordingExecutor:
    """Fake executor: env writes go to a per-actor dict, not ``os.environ``.

    The analog of the reference's ``Node1Actor``/``Node2Actor`` stubs
    (``tests/test_ddp.py:80-114``); subclass and override ``node_ip()`` /
    ``chip_ids()`` to simulate placement.
    """
    instances: List["RecordingExecutor"] = []

    def __init__(self):
        self.env: Dict[str, str] = {}
        self.executed: List[Callable] = []
        type(self).instances.append(self)

    # --- introspection overridden by placement-simulating subclasses --- #
    def node_ip(self) -> str:
        return "127.0.0.1"

    def chip_ids(self) -> List[int]:
        return []

    # --- executor protocol --------------------------------------------- #
    def set_env_var(self, key: str, value: Optional[str]) -> None:
        if value is None:
            self.env.pop(key, None)
        else:
            self.env[key] = value

    def set_env_vars(self, keys: List[str], values: List[str]) -> None:
        for k, v in zip(keys, values):
            self.set_env_var(k, v)

    def get_env_var(self, key: str) -> Optional[str]:
        return self.env.get(key)

    def get_node_ip(self) -> str:
        return self.node_ip()

    def find_free_port(self) -> int:
        return 29500

    def get_node_and_chip_ids(self) -> Tuple[str, List[int]]:
        return self.node_ip(), self.chip_ids()

    def execute(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        self.executed.append(fn)
        return fn(*args, **kwargs)
