"""A synchronous, in-process ray-compatible fake.

Implements the exact subset of the Ray API the launcher consumes —
``init/is_initialized/remote/put/get/wait/kill`` plus the actor
``.options(...).remote()`` / ``method.remote(...)`` protocol — with

- **synchronous execution**: remote calls run immediately in-process and
  return pre-resolved :class:`FakeObjectRef`\\ s;
- **a real serialization boundary**: ``put`` round-trips through pickle, so
  anything unpicklable (actor handles, jitted functions, device arrays)
  fails in tests exactly where it would fail on a cluster — the pitfall the
  reference documents at ``ray_launcher.py:274-288``;
- **top-level ObjectRef resolution** in task args, matching Ray semantics.

This is the test seam the reference gets from local Ray clusters
(``tests/test_ddp.py:20-61``); combined with fake executor classes injected
via :func:`~ray_lightning_tpu.launchers.utils.set_executable_cls` it covers
rank mapping, env brokering, and the full launch→collect→recover pipeline
without Ray installed.
"""
from __future__ import annotations

import pickle
from typing import Any, Callable, Dict, List, Optional, Tuple


class FakeObjectRef:
    """Pre-resolved stand-in for ``ray.ObjectRef``."""
    _is_fake_object_ref = True

    def __init__(self, value: Any):
        self.value = value

    def __repr__(self) -> str:
        return f"FakeObjectRef({type(self.value).__name__})"


def _resolve(obj: Any) -> Any:
    return obj.value if isinstance(obj, FakeObjectRef) else obj


class FakeActorMethod:
    def __init__(self, handle: "FakeActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args: Any, **kwargs: Any) -> FakeObjectRef:
        if self._handle._killed:
            raise RuntimeError("Actor was killed")
        args = tuple(_resolve(a) for a in args)
        kwargs = {k: _resolve(v) for k, v in kwargs.items()}
        method = getattr(self._handle._instance, self._name)
        return FakeObjectRef(method(*args, **kwargs))


class FakeActorHandle:
    def __init__(self, instance: Any, options: Dict[str, Any]):
        self._instance = instance
        self._options = options
        self._killed = False

    def __getattr__(self, name: str) -> FakeActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return FakeActorMethod(self, name)


class FakeRemoteClass:
    def __init__(self, cls: type, registry: List[FakeActorHandle]):
        self._cls = cls
        self._registry = registry
        self._options: Dict[str, Any] = {}

    def options(self, **options: Any) -> "FakeRemoteClass":
        out = FakeRemoteClass(self._cls, self._registry)
        out._options = options
        return out

    def remote(self, *args: Any, **kwargs: Any) -> FakeActorHandle:
        handle = FakeActorHandle(self._cls(*args, **kwargs),
                                 dict(self._options))
        self._registry.append(handle)
        return handle


class FakeRay:
    """Drop-in module-like object for ``RayLauncher(ray_module=...)``."""

    ObjectRef = FakeObjectRef

    def __init__(self, serialize_puts: bool = True):
        self._initialized = False
        self.serialize_puts = serialize_puts
        self.created_actors: List[FakeActorHandle] = []
        self.killed_actors: List[FakeActorHandle] = []

    # -- lifecycle ----------------------------------------------------- #
    def init(self, *args: Any, **kwargs: Any) -> None:
        self._initialized = True

    def is_initialized(self) -> bool:
        return self._initialized

    def shutdown(self) -> None:
        self._initialized = False

    # -- object store -------------------------------------------------- #
    def put(self, obj: Any) -> FakeObjectRef:
        if self.serialize_puts:
            obj = pickle.loads(pickle.dumps(obj))
        return FakeObjectRef(obj)

    def get(self, refs: Any, timeout: Optional[float] = None) -> Any:
        if isinstance(refs, list):
            return [_resolve(r) for r in refs]
        return _resolve(refs)

    def wait(self, refs: List[Any], num_returns: int = 1,
             timeout: Optional[float] = None
             ) -> Tuple[List[Any], List[Any]]:
        # Synchronous backend: everything is already done.
        return list(refs), []

    # -- actors -------------------------------------------------------- #
    def remote(self, cls: type) -> FakeRemoteClass:
        return FakeRemoteClass(cls, self.created_actors)

    def kill(self, actor: FakeActorHandle, no_restart: bool = False) -> None:
        actor._killed = True
        self.killed_actors.append(actor)


class RecordingExecutor:
    """Fake executor: env writes go to a per-actor dict, not ``os.environ``.

    The analog of the reference's ``Node1Actor``/``Node2Actor`` stubs
    (``tests/test_ddp.py:80-114``); subclass and override ``node_ip()`` /
    ``chip_ids()`` to simulate placement.
    """
    instances: List["RecordingExecutor"] = []

    def __init__(self):
        self.env: Dict[str, str] = {}
        self.executed: List[Callable] = []
        type(self).instances.append(self)

    # --- introspection overridden by placement-simulating subclasses --- #
    def node_ip(self) -> str:
        return "127.0.0.1"

    def chip_ids(self) -> List[int]:
        return []

    # --- executor protocol --------------------------------------------- #
    def set_env_var(self, key: str, value: str) -> None:
        self.env[key] = value

    def set_env_vars(self, keys: List[str], values: List[str]) -> None:
        for k, v in zip(keys, values):
            self.env[k] = v

    def get_env_var(self, key: str) -> Optional[str]:
        return self.env.get(key)

    def get_node_ip(self) -> str:
        return self.node_ip()

    def find_free_port(self) -> int:
        return 29500

    def get_node_and_chip_ids(self) -> Tuple[str, List[int]]:
        return self.node_ip(), self.chip_ids()

    def execute(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        self.executed.append(fn)
        return fn(*args, **kwargs)
