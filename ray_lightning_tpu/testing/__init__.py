"""Testing utilities: in-process fakes for the Ray substrate.

The reference can test against real local Ray clusters
(``ray.init(num_cpus=2)``, ``ray.cluster_utils.Cluster`` —
``tests/test_ddp.py:20-61``); this package provides the equivalent seam for
environments without Ray: a synchronous, pickling, ray-compatible fake that
drives the full :class:`~ray_lightning_tpu.launchers.ray_launcher.RayLauncher`
pipeline in-process.
"""
from ray_lightning_tpu.testing.fake_ray import FakeRay
from ray_lightning_tpu.testing.determinism import (assert_deterministic,
                                                   fit_fingerprint)

__all__ = ["FakeRay", "assert_deterministic", "fit_fingerprint"]
