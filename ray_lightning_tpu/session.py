"""Per-worker session: the worker↔driver side-channel.

Parity with ``ray_lightning/session.py:6-63``: a per-worker global singleton
holding ``(rank, queue)``. Worker code (e.g. Tune report callbacks) pushes
``(rank, item)`` tuples; the driver's :func:`ray_lightning_tpu.util.process_results`
loop drains the queue and executes callables in the driver process.

The queue object is executor-backend-specific: a ``multiprocessing`` /
``queue.Queue`` for the local backend, ``ray.util.queue.Queue`` when the Ray
backend is active. The session only requires ``put``/``get``/``empty``.
"""
from __future__ import annotations

from typing import Any, Optional


class TpuLightningSession:
    """Holds this worker's actor rank and the driver-bound queue."""

    def __init__(self, rank: int, queue: Optional[Any]):
        self._rank = rank
        self._queue = queue

    def get_actor_rank(self) -> int:
        return self._rank

    def set_queue(self, queue: Any) -> None:
        self._queue = queue

    def put_queue(self, item: Any) -> None:
        if self._queue is None:
            raise ValueError(
                "Trying to put something into the session queue, but the "
                "queue was not initialized. This usually means the trainer "
                "was not launched through a strategy launcher.")
        self._queue.put((self._rank, item))


_session: Optional[TpuLightningSession] = None


def init_session(rank: int, queue: Optional[Any] = None) -> None:
    """Install the worker-global session (double-init guarded).

    Parity with ``ray_lightning/session.py:30-36``.
    """
    global _session
    if _session is not None:
        raise ValueError(
            "A session is already initialized for this worker process. "
            "Call shutdown_session() first.")
    _session = TpuLightningSession(rank, queue)


def get_session() -> TpuLightningSession:
    if _session is None:
        raise ValueError(
            "No session initialized. `init_session` must be called by the "
            "launcher before worker code uses the session.")
    return _session


def shutdown_session() -> None:
    global _session
    _session = None


def get_actor_rank() -> int:
    """Rank of this worker actor. Parity: ``ray_lightning/session.py:56-58``."""
    return get_session().get_actor_rank()


def put_queue(item: Any) -> None:
    """Push ``(rank, item)`` onto the driver queue. Parity: ``session.py:61-63``."""
    get_session().put_queue(item)
