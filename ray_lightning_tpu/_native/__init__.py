"""Native runtime components (C++), loaded through ctypes.

The compute path is JAX/XLA; this package is the native layer *around* it —
currently the shared-memory ring buffer backing multiprocess data loading
(``shm_ring.cpp``). Compilation happens lazily on first use with the
system ``g++`` and the resulting ``libtlnative.so`` is cached next to the
sources; when no toolchain is available everything degrades to the pure-
Python fallbacks in :mod:`ray_lightning_tpu.data` (set
``TL_DISABLE_NATIVE=1`` to force that path).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "shm_ring.cpp")
_LIB = os.path.join(_HERE, "libtlnative.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    """Compile to a temp file and atomically rename into place.

    Several processes on one host (ranks, trials) may race to build; the
    rename guarantees no process ever ``CDLL``s a half-written .so, and the
    caller holds an fcntl lock so only one process compiles.
    """
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread", _SRC,
        "-o", tmp, "-lrt"
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError, OSError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _needs_build() -> bool:
    return not os.path.exists(_LIB) or (
        os.path.exists(_SRC)
        and os.path.getmtime(_SRC) > os.path.getmtime(_LIB))


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first call. None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("TL_DISABLE_NATIVE"):
            _load_failed = True
            return None
        if _needs_build():
            # cross-process exclusion: one builder, everyone else waits
            # then re-checks (the winner's rename makes the check false)
            import fcntl
            try:
                lockf = open(f"{_LIB}.lock", "w")
            except OSError:
                lockf = None
            try:
                if lockf is not None:
                    fcntl.flock(lockf, fcntl.LOCK_EX)
                if _needs_build() and not _build():
                    _load_failed = True
                    return None
            finally:
                if lockf is not None:
                    fcntl.flock(lockf, fcntl.LOCK_UN)
                    lockf.close()
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _load_failed = True
            return None
        lib.tlshm_create.restype = ctypes.c_void_p
        lib.tlshm_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.tlshm_open.restype = ctypes.c_void_p
        lib.tlshm_open.argtypes = [ctypes.c_char_p]
        lib.tlshm_push.restype = ctypes.c_int
        lib.tlshm_push.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_double
        ]
        lib.tlshm_push_v.restype = ctypes.c_int
        lib.tlshm_push_v.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.c_double
        ]
        lib.tlshm_peek.restype = ctypes.c_int64
        lib.tlshm_peek.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.tlshm_pop.restype = ctypes.c_int64
        lib.tlshm_pop.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_double
        ]
        lib.tlshm_count.restype = ctypes.c_uint64
        lib.tlshm_count.argtypes = [ctypes.c_void_p]
        lib.tlshm_is_closed.restype = ctypes.c_int
        lib.tlshm_is_closed.argtypes = [ctypes.c_void_p]
        lib.tlshm_close.restype = None
        lib.tlshm_close.argtypes = [ctypes.c_void_p]
        lib.tlshm_destroy.restype = None
        lib.tlshm_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return load() is not None


class ShmRing:
    """Cross-process byte-message ring over POSIX shared memory.

    Push/pop block GIL-free inside the native call, so a producer process
    feeding batches overlaps fully with the consumer's device step. Messages
    must be at most half the ring capacity (framing guarantee).
    """

    def __init__(self, name: str, capacity: int = 64 << 20,
                 create: bool = True):
        lib = load()
        if lib is None:
            raise RuntimeError(
                "Native library unavailable (no g++, or TL_DISABLE_NATIVE "
                "set); use the pure-Python loader path instead.")
        self._lib = lib
        self.name = name.encode() if isinstance(name, str) else name
        if create:
            self._h = lib.tlshm_create(self.name, capacity)
        else:
            self._h = lib.tlshm_open(self.name)
        if not self._h:
            raise OSError(
                f"Could not {'create' if create else 'open'} shared-memory "
                f"ring {name!r}")

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        return cls(name, create=False)

    def push(self, data: bytes, timeout: float = 10.0) -> None:
        rc = self._lib.tlshm_push(self._h, data, len(data), timeout)
        if rc == -1:
            raise TimeoutError("ring full")
        if rc == -2:
            raise BrokenPipeError("ring closed")
        if rc == -3:
            raise ValueError(
                f"message of {len(data)} bytes exceeds half the ring "
                "capacity; enlarge the ring")

    def push_buffers(self, buffers, timeout: float = 10.0) -> None:
        """Scatter-gather push: one message assembled from several buffer-
        protocol segments (bytes, memoryviews, numpy arrays), each memcpy'd
        straight from its own memory into the ring — no concatenated bytes
        detour. This is what makes pickle-5 out-of-band batch transport a
        single producer-side copy (see ``data/multiproc.py``).
        """
        import numpy as np

        def as_u8(b):
            # np.frombuffer works for read-only and writable buffers alike
            # and exposes a stable data pointer — but it requires a
            # C-contiguous segment and raises a confusing low-level error
            # for strided views (e.g. a transposed array's memoryview).
            # Normalize those through an explicit contiguous copy; the
            # consumer reassembles from raw bytes, so the copy is
            # semantics-preserving (one extra memcpy on a cold path).
            try:
                return np.frombuffer(b, dtype=np.uint8)
            except (ValueError, BufferError):
                contig = np.ascontiguousarray(b)
                return contig.reshape(-1).view(np.uint8)

        n = len(buffers)
        # the `views` list keeps every segment alive across the native
        # call.
        views = [as_u8(b) for b in buffers]
        ptrs = (ctypes.c_void_p * n)(*[v.ctypes.data for v in views])
        lens = (ctypes.c_uint64 * n)(*[v.nbytes for v in views])
        rc = self._lib.tlshm_push_v(self._h, ptrs, lens, n, timeout)
        if rc == -1:
            raise TimeoutError("ring full")
        if rc == -2:
            raise BrokenPipeError("ring closed")
        if rc == -3:
            total = sum(v.nbytes for v in views)
            raise ValueError(
                f"message of {total} bytes exceeds half the ring "
                "capacity; enlarge the ring")

    def pop_view(self, timeout: float = 10.0) -> Optional[memoryview]:
        """Next message as a writable memoryview over a freshly allocated
        buffer (one shm→host copy, no extra bytes copy), or None when the
        ring is closed and drained. The view owns the buffer: slices of it
        (e.g. numpy arrays reconstructed zero-copy by pickle-5) stay valid
        as long as they are referenced.
        """
        size = self._lib.tlshm_peek(self._h, timeout)
        if size == -2:
            return None
        if size == -1:
            raise TimeoutError("ring empty")
        buf = ctypes.create_string_buffer(int(size))
        n = self._lib.tlshm_pop(self._h, buf, int(size), timeout)
        if n == -2:
            return None
        if n == -1:
            raise TimeoutError("ring empty")
        if n < 0:
            raise OSError(f"ring pop failed ({n})")
        return memoryview(buf)[:int(n)]

    def pop(self, timeout: float = 10.0) -> Optional[bytes]:
        """Next message, or None when the ring is closed and drained."""
        view = self.pop_view(timeout)
        return None if view is None else view.tobytes()

    def __len__(self) -> int:
        return int(self._lib.tlshm_count(self._h))

    @property
    def closed(self) -> bool:
        return bool(self._lib.tlshm_is_closed(self._h))

    def close(self) -> None:
        self._lib.tlshm_close(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.tlshm_destroy(self._h)
            self._h = None

    def __del__(self):  # best-effort; explicit destroy() preferred
        try:
            self.destroy()
        except Exception:  # tl-lint: allow-broad-except — __del__ may run
            pass           # at interpreter teardown, when logging is gone
