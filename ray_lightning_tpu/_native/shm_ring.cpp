// Shared-memory ring buffer for cross-process batch hand-off.
//
// The native runtime piece of the host-side data pipeline: worker processes
// (forked data loaders) push framed byte messages (serialized batches) into
// a POSIX shared-memory segment; the trainer process pops them without
// pickling through a pipe and without holding the Python GIL — calls are
// plain C through ctypes, so the copy and all blocking happens GIL-free and
// overlaps the device step.
//
// Role in the framework (see SURVEY.md §2.2): the reference consumes its
// native capabilities (NCCL rings, Ray's plasma object store) from external
// C++ deps; this file is the equivalent in-repo native layer for the one
// hot host-side path the TPU build owns itself — feeding the chips.
//
// Layout of the segment:
//   [Header | data bytes ...]
// Messages are framed [u64 len][payload], stored contiguously; a len of
// WRAP_MARKER means "skip to start of data area". Synchronization is a
// process-shared pthread mutex + two condvars (not_full / not_empty).
//
// Build: g++ -O3 -shared -fPIC -pthread shm_ring.cpp -o libtlnative.so -lrt

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t WRAP_MARKER = ~0ull;
constexpr uint32_t MAGIC = 0x544c5247;  // "TLRG"

struct Header {
  uint32_t magic;
  uint32_t closed;
  uint64_t capacity;   // bytes in the data area
  uint64_t head;       // read offset into data area
  uint64_t tail;       // write offset into data area
  uint64_t used;       // bytes currently stored (incl. frame headers)
  uint64_t n_messages;
  pthread_mutex_t mutex;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
};

struct Ring {
  Header* hdr;
  char* data;
  size_t map_size;
  int owner;  // created (vs attached) — owner unlinks on destroy
  char name[256];
};

void make_abstime(double timeout_s, timespec* ts) {
  clock_gettime(CLOCK_REALTIME, ts);
  time_t sec = static_cast<time_t>(timeout_s);
  long nsec = static_cast<long>((timeout_s - sec) * 1e9);
  ts->tv_sec += sec;
  ts->tv_nsec += nsec;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

// Space needed to store a message of n payload bytes at offset `tail`
// given `capacity` (accounts for a possible wrap marker).
uint64_t frame_bytes(uint64_t n) { return 8 + n; }

}  // namespace

extern "C" {

// Create a new ring in shared memory. Returns handle or nullptr.
void* tlshm_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a dead run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t map_size = sizeof(Header) + capacity;
  if (ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* hdr = static_cast<Header*>(mem);
  std::memset(hdr, 0, sizeof(Header));
  hdr->capacity = capacity;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutex_init(&hdr->mutex, &ma);
  pthread_mutexattr_destroy(&ma);

  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->not_full, &ca);
  pthread_cond_init(&hdr->not_empty, &ca);
  pthread_condattr_destroy(&ca);

  hdr->magic = MAGIC;

  Ring* r = new Ring();
  r->hdr = hdr;
  r->data = static_cast<char*>(mem) + sizeof(Header);
  r->map_size = map_size;
  r->owner = 1;
  std::strncpy(r->name, name, sizeof(r->name) - 1);
  return r;
}

// Attach to an existing ring. Returns handle or nullptr.
void* tlshm_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = static_cast<Header*>(mem);
  if (hdr->magic != MAGIC) {
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  Ring* r = new Ring();
  r->hdr = hdr;
  r->data = static_cast<char*>(mem) + sizeof(Header);
  r->map_size = static_cast<size_t>(st.st_size);
  r->owner = 0;
  std::strncpy(r->name, name, sizeof(r->name) - 1);
  return r;
}

// True iff a frame of fb bytes can be written contiguously right now
// (either at the tail, or at offset 0 after retiring the tail gap).
static bool fits_locked(const Header* h, uint64_t fb) {
  if (h->capacity - h->used < fb) return false;
  uint64_t head = h->head, tail = h->tail;
  if (h->used > 0 && head > tail) return head - tail >= fb;
  // Free region spans the end of the data area (or the ring is empty).
  if (h->capacity - tail >= fb) return true;
  return head >= fb;  // wrap: the [tail, capacity) gap is retired as used
}

int tlshm_push_v(void* handle, const char* const* bufs,
                 const uint64_t* lens, uint64_t n_bufs, double timeout_s);

// Push one message. 0 = ok, -1 = timeout, -2 = closed, -3 = too large.
int tlshm_push(void* handle, const char* buf, uint64_t n, double timeout_s) {
  return tlshm_push_v(handle, &buf, &n, 1, timeout_s);
}

// Scatter-gather push: one framed message assembled from n_bufs segments,
// memcpy'd straight from caller memory into the ring. This is the
// zero-detour batch path: the Python side hands the pickle-5 meta plus the
// raw numpy array buffers as segments, so array bytes cross exactly once
// (producer memory -> shm) instead of detouring through a concatenated
// bytes object first. Same return codes as tlshm_push.
int tlshm_push_v(void* handle, const char* const* bufs,
                 const uint64_t* lens, uint64_t n_bufs, double timeout_s) {
  Ring* r = static_cast<Ring*>(handle);
  Header* h = r->hdr;
  uint64_t n = 0;
  for (uint64_t i = 0; i < n_bufs; ++i) n += lens[i];
  uint64_t fb = frame_bytes(n);
  if (fb * 2 > h->capacity) return -3;

  timespec deadline;
  make_abstime(timeout_s, &deadline);
  pthread_mutex_lock(&h->mutex);
  while (!fits_locked(h, fb) && !h->closed) {
    if (pthread_cond_timedwait(&h->not_full, &h->mutex, &deadline) ==
        ETIMEDOUT) {
      pthread_mutex_unlock(&h->mutex);
      return -1;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mutex);
    return -2;
  }
  uint64_t tail = h->tail;
  if (h->capacity - tail < fb) {
    if (h->capacity - tail >= 8)
      std::memcpy(r->data + tail, &WRAP_MARKER, 8);
    h->used += h->capacity - tail;
    tail = 0;
  }
  std::memcpy(r->data + tail, &n, 8);
  uint64_t off = tail + 8;
  for (uint64_t i = 0; i < n_bufs; ++i) {
    std::memcpy(r->data + off, bufs[i], lens[i]);
    off += lens[i];
  }
  h->tail = (tail + fb) % h->capacity;
  h->used += fb;
  h->n_messages += 1;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mutex);
  return 0;
}

// Size of the next message without consuming it.
// >=0 = size, -1 = timeout, -2 = closed and drained.
int64_t tlshm_peek(void* handle, double timeout_s) {
  Ring* r = static_cast<Ring*>(handle);
  Header* h = r->hdr;
  timespec deadline;
  make_abstime(timeout_s, &deadline);
  pthread_mutex_lock(&h->mutex);
  while (h->n_messages == 0) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mutex);
      return -2;
    }
    if (pthread_cond_timedwait(&h->not_empty, &h->mutex, &deadline) ==
        ETIMEDOUT) {
      pthread_mutex_unlock(&h->mutex);
      return -1;
    }
  }
  uint64_t head = h->head;
  uint64_t len;
  if (h->capacity - head < 8) {  // tail gap too small for a marker
    std::memcpy(&len, r->data, 8);
  } else {
    std::memcpy(&len, r->data + head, 8);
    if (len == WRAP_MARKER) std::memcpy(&len, r->data, 8);
  }
  pthread_mutex_unlock(&h->mutex);
  return static_cast<int64_t>(len);
}

// Pop one message into buf (cap bytes).
// >=0 = bytes written, -1 = timeout, -2 = closed and drained, -4 = buf small.
int64_t tlshm_pop(void* handle, char* buf, uint64_t cap, double timeout_s) {
  Ring* r = static_cast<Ring*>(handle);
  Header* h = r->hdr;
  timespec deadline;
  make_abstime(timeout_s, &deadline);
  pthread_mutex_lock(&h->mutex);
  while (h->n_messages == 0) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mutex);
      return -2;
    }
    if (pthread_cond_timedwait(&h->not_empty, &h->mutex, &deadline) ==
        ETIMEDOUT) {
      pthread_mutex_unlock(&h->mutex);
      return -1;
    }
  }
  // Compute the wrap-gap retirement into locals and commit head/used only
  // after the len<=cap check: a -4 return must leave the ring untouched so
  // the caller can retry with a bigger buffer.
  uint64_t head = h->head;
  uint64_t len;
  uint64_t gap = 0;
  if (h->capacity - head < 8) {  // tail gap too small for a marker
    gap = h->capacity - head;
    head = 0;
    std::memcpy(&len, r->data, 8);
  } else {
    std::memcpy(&len, r->data + head, 8);
    if (len == WRAP_MARKER) {
      gap = h->capacity - head;
      head = 0;
      std::memcpy(&len, r->data, 8);
    }
  }
  if (len > cap) {
    pthread_mutex_unlock(&h->mutex);
    return -4;
  }
  std::memcpy(buf, r->data + head + 8, len);
  h->head = (head + frame_bytes(len)) % h->capacity;
  h->used -= gap + frame_bytes(len);
  h->n_messages -= 1;
  // Broadcast: several producers may fit in the space one pop frees.
  pthread_cond_broadcast(&h->not_full);
  pthread_mutex_unlock(&h->mutex);
  return static_cast<int64_t>(len);
}

uint64_t tlshm_count(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  pthread_mutex_lock(&r->hdr->mutex);
  uint64_t n = r->hdr->n_messages;
  pthread_mutex_unlock(&r->hdr->mutex);
  return n;
}

int tlshm_is_closed(void* handle) {
  return static_cast<Ring*>(handle)->hdr->closed;
}

// Close: producers stop; consumers drain then see -2.
void tlshm_close(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  pthread_mutex_lock(&r->hdr->mutex);
  r->hdr->closed = 1;
  pthread_cond_broadcast(&r->hdr->not_empty);
  pthread_cond_broadcast(&r->hdr->not_full);
  pthread_mutex_unlock(&r->hdr->mutex);
}

// Detach; the creating process also unlinks the segment.
void tlshm_destroy(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  munmap(r->hdr, r->map_size);
  if (r->owner) shm_unlink(r->name);
  delete r;
}

}  // extern "C"
