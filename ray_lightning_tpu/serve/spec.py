"""Speculative decoding: k cheap draft steps + ONE widened target verify.

Leviathan et al. 2023 ("Fast Inference from Transformers via Speculative
Decoding") on the engine's fixed-shape terms: a small **draft model**
(config-supplied — e.g. a 2-layer distilled sibling sharing the target's
tokenizer/embedding shapes) runs ``k`` autoregressive single-token steps
per slot, then the target model scores all ``k`` proposals in ONE
``(num_slots, k+1)`` **verify** dispatch — the per-row block-write mode
of the cached-attention contract
(:func:`ray_lightning_tpu.models.generate.verify_step`). The longest
matching prefix is accepted plus one target-sampled fix-up/bonus token,
so each target dispatch commits 1..k+1 tokens instead of exactly one.

Why this is the decode lever: decode is bandwidth- and dispatch-bound —
every target dispatch reads all params once and pays the fixed per-call
tunnel cost (~108 ms measured, BENCH_r05), so committing k+1 tokens per
target read/dispatch multiplies throughput by the acceptance rate's
worth of that ceiling. Draft + verify run in the SAME compiled program
(one dispatch per round; ``steps_per_dispatch`` scans that round, so a
spec engine's dispatch amortization composes with multi-step
scheduling).

Acceptance rules (per row, matching the row's own sampling params):

- **greedy** (``temperature == 0``): accept draft token ``d_j`` iff it
  equals the target's argmax at that offset; on divergence commit the
  target argmax instead. Every committed token is therefore EXACTLY the
  token the non-spec engine would have produced — greedy outputs are
  token-identical by construction, invariant to round boundaries,
  acceptance luck, and crash-replay restarts (pinned by
  ``tests/test_spec.py``).
- **sampled** (``temperature > 0``): the standard rejection-resampling
  rule — accept ``d_j`` with probability ``min(1, p(d_j)/q(d_j))``,
  else resample from ``max(p - q, 0)`` normalized — which preserves the
  target distribution exactly. Every random draw derives from the
  request's existing per-step key ``fold_in(fold_in(base, seed),
  step)``: the draft draw from sub-stream ``fold_in(step_key, 1)``, the
  accept uniform from ``fold_in(step_key, 2)``, the resample/bonus from
  ``step_key`` itself. The committed token at step ``s`` is therefore a
  pure function of ``(engine seed, request seed, s, context)`` — round
  boundaries cancel — which is what makes sampled streams replay-exact
  through crash recovery (same argument as the non-spec engine, see
  ``docs/reliability.md``).

Rollback is a position decrement: the verify block-writes K/V for every
draft token, and rejected tokens' K/V simply stays at positions past
the new commit point — later writes land at or before those positions
before any causal mask re-admits them (dense), or land in pages the
slot already owns (paged: no page churn; writes past the slot's
allocated span are scatter-dropped and never needed, since commits are
budget-clamped).

The draft model keeps its own DENSE ``(num_slots, max_seq_len)`` KV
cache regardless of the target's storage (the draft is small — paging
it buys nothing). It is rebuilt per slot activation by a fixed-shape
``(1, max_seq_len)`` full-context prefill (:class:`SpecDecoder` tracks
stale slots), which is also what makes chunked prefill, prefix-cache
adoption, and crash replay compose for free: whatever path activated
the row, the draft re-reads the full host-side context.
"""
from __future__ import annotations

from functools import partial
from typing import List, Set

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.models.generate import (_prefill_impl, decode_step,
                                               sample_logits_rows,
                                               verify_step,
                                               verify_step_paged)
from ray_lightning_tpu.models.quant import materialize_for_program
from ray_lightning_tpu.serve.pages import (dense_storage_commit,
                                           dense_storage_values,
                                           fold_rows, gather_pages,
                                           pick_donated, scatter_pages)

__all__ = ["SpecDecoder"]

#: fold_in sub-stream tags off each step key (see the module docstring)
_DRAFT_STREAM = 1
_ACCEPT_STREAM = 2

_fold_rows = fold_rows


def _row_probs(logits: jax.Array, temperature: jax.Array,
               top_k: jax.Array) -> jax.Array:
    """Per-row sampling distribution over (B, V) logits — softmax of
    EXACTLY the processed logits :func:`sample_logits_rows`'s sampled
    branch draws from (temperature scaling + dynamic rank-mask top_k),
    so the rejection test's p/q match what the samplers actually did.
    Greedy rows (t == 0) get a well-defined (unused) distribution."""
    def row(l, t, tk):
        scaled = l / jnp.where(t > 0, t, 1.0)
        order = jnp.argsort(-l)
        ranks = jnp.zeros_like(order).at[order].set(
            jnp.arange(l.shape[0], dtype=order.dtype))
        scaled = jnp.where((tk > 0) & (ranks >= tk),
                           jnp.finfo(jnp.float32).min, scaled)
        return jax.nn.softmax(scaled)

    return jax.vmap(row)(logits, temperature, top_k)


def _spec_accept(L, draft_toks, draft_logits, cur, pos, active, remaining,
                 temp, top_k, eos, keys, stepno, max_pos, *, k):
    """Accept/commit for one round, vectorized over rows.

    ``L`` (B, k+1, V) target logits, offset ``j`` conditioned on the
    row's context plus drafts ``< j``; ``draft_toks`` (B, k);
    ``draft_logits`` (B, k, V). Returns the updated row state plus
    ``emitted`` (B, k+1) — committed tokens in order, −1 past each
    row's commit count — ``accepted`` (B,), the number of committed
    DRAFT tokens (the acceptance-rate numerator; the +1 fix-up/bonus
    token is target work, not draft credit), and ``rejected`` (B,), 1
    iff a real divergence entered the committed stream this round.
    Draft agreements cut by the budget/eos clamp are neither accepted
    nor rejected — the verify did not contradict them, so they must
    not drag the acceptance rate below the draft's true quality.
    """
    B = cur.shape[0]
    sampled = temp > 0.0
    tgts = jnp.argmax(L, axis=-1).astype(jnp.int32)      # (B, k+1)

    def greedy_only():
        # all-greedy batch (temperature=0 everywhere — the default and
        # the tracked bench regime): accept is an exact argmax match
        # and every fix IS the argmax — no distributions, no draws.
        # Batch-level lax.cond, the same gate sample_logits_rows uses,
        # so the full-vocab softmax/argsort machinery below never
        # executes on the greedy hot path.
        return (jnp.zeros((B, k), jnp.bool_),
                jnp.zeros((B, k), jnp.int32))

    def with_sampled():
        accs = []   # k entries (B,) bool — draft j accepted?
        fixes = []  # k entries (B,) — resample at divergence j
        for j in range(k):
            sk = _fold_rows(keys, stepno + j)
            d = draft_toks[:, j]
            p = _row_probs(L[:, j], temp, top_k)
            q = _row_probs(draft_logits[:, j], temp, top_k)
            p_d = jnp.take_along_axis(p, d[:, None], axis=1)[:, 0]
            q_d = jnp.take_along_axis(q, d[:, None], axis=1)[:, 0]
            u = jax.vmap(jax.random.uniform)(
                _fold_rows(sk, jnp.full((B,), _ACCEPT_STREAM,
                                        jnp.int32)))
            # u < p/q spelled multiplication-first: q_d == 0
            # (numerically impossible for a proposed token, but belt)
            # rejects cleanly
            accs.append(u * q_d < p_d)
            # resample from the residual max(p - q, 0); zero residual
            # mass (p == q exactly — rejection then has probability 0,
            # belt again) falls back to p
            residual = jnp.maximum(p - q, 0.0)
            total = jnp.sum(residual, axis=-1, keepdims=True)
            res_dist = jnp.where(total > 0, residual, p)
            fixes.append(jax.vmap(
                lambda kk, r: jax.random.categorical(
                    kk, jnp.log(r + 1e-30))
            )(sk, res_dist).astype(jnp.int32))
        return jnp.stack(accs, axis=1), jnp.stack(fixes, axis=1)

    acc_s, fix_s = jax.lax.cond(jnp.any(sampled), with_sampled,
                                greedy_only)
    acc = jnp.where(sampled[:, None], acc_s, draft_toks == tgts[:, :k])
    fix = jnp.where(sampled[:, None], fix_s, tgts[:, :k])   # (B, k)
    # bonus token after a fully-accepted block: the target's own sample
    # at offset k, drawn with the plain step key — exactly the draw the
    # non-spec engine would have made at that step (sample_logits_rows
    # gates its own greedy/sampled machinery)
    bonus = sample_logits_rows(L[:, k], _fold_rows(keys, stepno + k),
                               temp, top_k)
    fixes_all = jnp.concatenate([fix, bonus[:, None]], axis=1)

    a = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
    fix_at_a = jnp.take_along_axis(fixes_all, a[:, None],
                                   axis=1)                # (B, 1)
    idx = jnp.arange(k + 1)[None, :]                      # (1, k+1)
    drafts_pad = jnp.concatenate(
        [draft_toks, jnp.zeros((B, 1), jnp.int32)], axis=1)
    tok = jnp.where(idx < a[:, None], drafts_pad, fix_at_a)

    # commit mask: a prefix per row — through the accepted drafts plus
    # the fix/bonus, clamped by the token budget, cut after the first
    # eos, zero for inactive rows
    within = (idx <= a[:, None]) & (idx < remaining[:, None])
    is_eos = (tok == eos[:, None]) & (eos >= 0)[:, None]
    eos_before = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) \
        - is_eos.astype(jnp.int32)
    within = within & (eos_before == 0) & active[:, None]
    n = jnp.sum(within.astype(jnp.int32), axis=1)        # committed
    emitted = jnp.where(within, tok, -1)
    accepted = jnp.minimum(n, a)                         # draft credit
    # a real rejection = the divergence fix-up actually committed
    # (n == a+1 with a < k); clamped-away drafts were never judged into
    # the stream and count toward neither side of the rate
    rejected = (active & (a < k) & (n == a + 1)).astype(jnp.int32)

    last = jnp.take_along_axis(tok, jnp.clip(n - 1, 0, k)[:, None],
                               axis=1)
    commit = active & (n > 0)
    cur = jnp.where(commit[:, None], last, cur)
    pos = jnp.minimum(pos + n[:, None], max_pos)
    stepno = stepno + n
    remaining = remaining - n
    hit_eos = jnp.any(is_eos & within, axis=1)
    finished = active & (hit_eos | (remaining <= 0))
    active = active & ~finished
    return (cur, pos, active, remaining, stepno, emitted, accepted,
            rejected, finished)


def _draft_propose(draft_model, draft_params, draft_cache, cur, pos,
                   keys, stepno, temp, top_k, max_pos, *, k):
    """One round's draft half, shared by every spec program variant:
    k+1 single-token draft feeds — iteration j feeds token t_j (t_0 =
    cur, then the proposals) at ``pos + j`` and proposes d_{j+1}; the
    last proposal is discarded, its feed is the full-accept KV
    coverage. Returns ``(draft_cache, draft_toks (B, k),
    draft_logits (B, k, V))``."""

    def draft_step(dc, j):
        draft_cache, t = dc
        logits, draft_cache = decode_step(
            draft_model, draft_params, draft_cache, t,
            jnp.minimum(pos + j, max_pos))
        sk = _fold_rows(keys, stepno + j)
        dk = _fold_rows(
            sk, jnp.full(stepno.shape, _DRAFT_STREAM, jnp.int32))
        d = sample_logits_rows(logits, dk, temp, top_k)
        return (draft_cache, d[:, None]), (d, logits)

    (draft_cache, _), (drafts, dlogits) = jax.lax.scan(
        draft_step, (draft_cache, cur), jnp.arange(k + 1))
    draft_toks = jnp.moveaxis(drafts, 0, 1)[:, :k]       # (B, k)
    draft_logits = jnp.moveaxis(dlogits, 0, 1)[:, :k]    # (B, k, V)
    return draft_cache, draft_toks, draft_logits


def _spec_rounds_impl(model, draft_model, params, draft_params, cache,
                      draft_cache, cur, pos, active, remaining, temp,
                      top_k, eos, keys, stepno, adapter_ids=None, *,
                      k, rounds):
    """``rounds`` spec rounds in ONE dispatch. Each round: k+1 draft
    single-token steps (the extra feed writes the last proposal's K/V so
    a fully-accepted round leaves the draft cache covering every
    committed position), one ``(B, k+1)`` target verify, and the accept
    rule — all fused, so the per-dispatch fixed cost amortizes over up
    to ``rounds * (k+1)`` committed tokens.

    Inactive rows run the same math at frozen positions (static
    shapes); their junk draft/verify writes land in storage the next
    admission fully overwrites (dense whole-row inject / paged page
    re-inject — the paged wrapper additionally write-masks them).
    ``cache`` may be int8 dense storage, handled like the plain step;
    ``params``/``draft_params`` may be weight-quantized — dequantized
    here once per dispatch, outside the round scan.

    ``adapter_ids`` (B,) per-row LoRA bank ids reach the TARGET verify
    only: the spec identity contract is "same committed tokens as the
    non-spec engine", and that engine's tokens come from the (adapted)
    target distribution — greedy acceptance compares draft proposals
    against the adapted argmax, sampled acceptance corrects toward the
    adapted ``p``, so the draft model stays UNADAPTED (one draft serves
    every adapter; a mismatched draft only costs acceptance rate, never
    correctness).
    """
    params = materialize_for_program(params, model.cfg)
    draft_params = materialize_for_program(draft_params, draft_model.cfg)
    storage = cache
    cache = dense_storage_values(model, storage)
    max_pos = model.cfg.max_seq_len - 1

    def round_body(carry, _):
        cache, draft_cache, cur, pos, active, remaining, stepno = carry
        draft_cache, draft_toks, draft_logits = _draft_propose(
            draft_model, draft_params, draft_cache, cur, pos, keys,
            stepno, temp, top_k, max_pos, k=k)
        tokens_in = jnp.concatenate([cur, draft_toks], axis=1)
        vpos = jnp.minimum(pos + jnp.arange(k + 1)[None, :], max_pos)
        L, cache = verify_step(model, params, cache, tokens_in, vpos,
                               adapter_ids)
        (cur, pos, active, remaining, stepno, emitted, accepted,
         rejected, finished) = _spec_accept(
            L, draft_toks, draft_logits, cur, pos, active, remaining,
            temp, top_k, eos, keys, stepno, max_pos, k=k)
        return ((cache, draft_cache, cur, pos, active, remaining,
                 stepno), (emitted, accepted, rejected, finished))

    (cache, draft_cache, cur, pos, active, remaining, stepno), \
        (emitted, accepted, rejected, finished) = jax.lax.scan(
            round_body,
            (cache, draft_cache, cur, pos, active, remaining, stepno),
            None, length=rounds)
    cache = dense_storage_commit(model, storage, cache)
    return (cache, draft_cache, cur, pos, active, remaining, stepno,
            emitted, accepted, rejected, finished)


def _spec_rounds_paged_impl(model, draft_model, params, draft_params,
                            arena, page_table, draft_cache, cur, pos,
                            active, remaining, temp, top_k, eos, keys,
                            stepno, adapter_ids=None, *, k, rounds):
    """The spec round program on paged target storage: gather the dense
    view (dequantizing int8 arenas), run the IDENTICAL rounds body,
    scatter mapped pages back — rows inactive at dispatch entry are
    write-masked exactly as in the plain paged step."""
    view = gather_pages(model, arena, page_table)
    write_pt = jnp.where(active[:, None], page_table, -1)
    (view, draft_cache, cur, pos, active, remaining, stepno, emitted,
     accepted, rejected, finished) = _spec_rounds_impl(
        model, draft_model, params, draft_params, view, draft_cache,
        cur, pos, active, remaining, temp, top_k, eos, keys, stepno,
        adapter_ids, k=k, rounds=rounds)
    arena = scatter_pages(model, arena, view, write_pt)
    return (arena, draft_cache, cur, pos, active, remaining, stepno,
            emitted, accepted, rejected, finished)


def _spec_rounds_page_native_impl(model, draft_model, params,
                                  draft_params, arena, page_table,
                                  draft_cache, cur, pos, active,
                                  remaining, temp, top_k, eos, keys,
                                  stepno, adapter_ids=None, *, k,
                                  rounds):
    """The spec round program in **page-native** mode: the widened
    ``(B, k+1)`` verify reads and writes target K/V straight through
    the (write-masked) page table inside the model's attention
    (:func:`~ray_lightning_tpu.models.generate.verify_step_paged`) —
    no dense view gathers or scatters per dispatch. The draft half and
    the accept rule are byte-for-byte the shared
    :func:`_draft_propose` / :func:`_spec_accept`, so commits cannot
    drift from the dense-gather spec path. Rollback stays a position
    decrement: rejected drafts' K/V landed in pages the slot already
    owns, and writes past its span dropped at the page-table mask.
    """
    params = materialize_for_program(params, model.cfg)
    draft_params = materialize_for_program(draft_params, draft_model.cfg)
    max_pos = model.cfg.max_seq_len - 1

    def round_body(carry, _):
        arena, draft_cache, cur, pos, active, remaining, stepno = carry
        draft_cache, draft_toks, draft_logits = _draft_propose(
            draft_model, draft_params, draft_cache, cur, pos, keys,
            stepno, temp, top_k, max_pos, k=k)
        tokens_in = jnp.concatenate([cur, draft_toks], axis=1)
        vpos = jnp.minimum(pos + jnp.arange(k + 1)[None, :], max_pos)
        L, arena = verify_step_paged(model, params, arena, tokens_in,
                                     vpos, page_table, adapter_ids)
        (cur, pos, active, remaining, stepno, emitted, accepted,
         rejected, finished) = _spec_accept(
            L, draft_toks, draft_logits, cur, pos, active, remaining,
            temp, top_k, eos, keys, stepno, max_pos, k=k)
        return ((arena, draft_cache, cur, pos, active, remaining,
                 stepno), (emitted, accepted, rejected, finished))

    (arena, draft_cache, cur, pos, active, remaining, stepno), \
        (emitted, accepted, rejected, finished) = jax.lax.scan(
            round_body,
            (arena, draft_cache, cur, pos, active, remaining, stepno),
            None, length=rounds)
    return (arena, draft_cache, cur, pos, active, remaining, stepno,
            emitted, accepted, rejected, finished)


def _draft_refill_impl(draft_model, draft_params, pool_cache, tokens,
                       length, slot):
    """Rebuild ONE slot's draft KV row from its full host-side context:
    a fixed-shape ``(1, P)`` ragged prefill (P = max_seq_len, so any
    admissible context fits one program) + whole-row inject at ``slot``.
    The row is overwritten end to end — junk from the slot's previous
    tenant or from parked spec rounds never survives an activation."""
    pf_cache, _last = _prefill_impl(draft_model, draft_params, tokens,
                                    length)
    batch_axis = 1 if draft_model.cfg.scan_layers else 0

    def inject(pool, pf):
        if pool.ndim < 4:
            return pool
        return jax.lax.dynamic_update_slice_in_dim(pool, pf, slot,
                                                   axis=batch_axis)

    return jax.tree_util.tree_map(inject, pool_cache, pf_cache)


_STATICS = ("model", "draft_model", "k", "rounds")
_spec_rounds_donated = partial(
    jax.jit, static_argnames=_STATICS, donate_argnums=(4, 5))(
        _spec_rounds_impl)
_spec_rounds_plain = partial(
    jax.jit, static_argnames=_STATICS)(_spec_rounds_impl)
_spec_paged_donated = partial(
    jax.jit, static_argnames=_STATICS, donate_argnums=(4, 6))(
        _spec_rounds_paged_impl)
_spec_paged_plain = partial(
    jax.jit, static_argnames=_STATICS)(_spec_rounds_paged_impl)
_spec_page_native_donated = partial(
    jax.jit, static_argnames=_STATICS, donate_argnums=(4, 6))(
        _spec_rounds_page_native_impl)
_spec_page_native_plain = partial(
    jax.jit, static_argnames=_STATICS)(_spec_rounds_page_native_impl)
_draft_refill_donated = partial(
    jax.jit, static_argnames=("draft_model",), donate_argnums=(2,))(
        _draft_refill_impl)
_draft_refill_plain = partial(
    jax.jit, static_argnames=("draft_model",))(_draft_refill_impl)


_pick = pick_donated  # shared CPU donation gating (serve/pages.py)


class SpecDecoder:
    """Draft-model state + compiled programs for one engine's spec path.

    Owns the draft's dense ``(num_slots, max_seq_len)`` KV cache (device
    memory — released by :meth:`shutdown`, which the owning engine's
    ``shutdown()`` drives) and the stale-slot ledger: every slot
    activation (fresh admit, final chunk, crash replay) marks its row
    stale, and the engine refills stale rows with a full-context draft
    prefill before the next spec dispatch.
    """

    def __init__(self, draft_model, draft_params, *, num_slots: int,
                 k: int, target_cfg):
        cfg = draft_model.cfg
        if not cfg.decode:
            raise ValueError(
                "the draft model must be decode-mode: rebuild its config "
                "with decode=True (params are compatible)")
        if cfg.vocab_size != target_cfg.vocab_size:
            raise ValueError(
                f"draft vocab_size ({cfg.vocab_size}) must match the "
                f"target's ({target_cfg.vocab_size}) — draft proposals "
                "are verified id-for-id")
        if cfg.max_seq_len != target_cfg.max_seq_len:
            raise ValueError(
                f"draft max_seq_len ({cfg.max_seq_len}) must match the "
                f"target's ({target_cfg.max_seq_len}) — draft and target "
                "decode the same absolute positions")
        if k < 1:
            raise ValueError(f"spec_k must be >= 1, got {k}")
        self.model = draft_model
        self.params = draft_params
        self.k = k
        self.num_slots = num_slots
        self.prefill_len = cfg.max_seq_len
        self.cache = draft_model.init(
            jax.random.PRNGKey(0), jnp.zeros((num_slots, 1), jnp.int32),
            positions=jnp.zeros((num_slots, 1), jnp.int32))["cache"]
        self._stale: Set[int] = set()
        self.refills = 0

    # ----------------------------------------------------------- ledger
    @property
    def stale(self) -> List[int]:
        return sorted(self._stale)

    def mark_stale(self, slot: int) -> None:
        self._stale.add(slot)

    def discard(self, slot: int) -> None:
        self._stale.discard(slot)

    # --------------------------------------------------------- programs
    def refill(self, slot: int, context: List[int]) -> None:
        """Rebuild ``slot``'s draft KV from ``context`` (the row's
        prompt + all committed tokens except the current one — the
        draft cache must cover positions ``0..pos-1`` so the next round
        feeds the current token at ``pos``)."""
        P = self.prefill_len
        if not 1 <= len(context) <= P:
            raise ValueError(
                f"draft refill context length {len(context)} outside "
                f"[1, {P}]")
        tokens = np.zeros((1, P), np.int32)
        tokens[0, :len(context)] = context
        fn = _pick(_draft_refill_donated, _draft_refill_plain)
        self.cache = fn(self.model, self.params, self.cache, tokens,
                        np.array([len(context)], np.int32),
                        np.int32(slot))
        self.refills += 1
        self._stale.discard(slot)

    def shutdown(self) -> None:
        """Drop the draft KV cache (device memory)."""
        self.cache = None
        self._stale.clear()
