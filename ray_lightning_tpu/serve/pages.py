"""Paged KV allocation: fixed-size pages from one arena + prefix reuse.

This is the host-side half of the paged serving engine (vLLM's
PagedAttention block allocator reshaped for XLA — see
``docs/serving.md``): instead of reserving one whole
``(max_seq_len, H, D)`` KV row per request, :class:`PagePool` backs each
request's logical KV with fixed-size **pages** cut from one
``(num_pages, page_size, H, D)`` arena per KV leaf, tracked by a per-slot
**page table** — a plain ``(num_slots, pages_per_slot)`` int32 gather
index the engine materializes a dense view from around its fixed-shape
compiled programs. A 30-token chat request holds
``ceil((prompt + budget) / page_size)`` pages instead of a
``max_seq_len`` row, so ``num_slots`` (the step program's batch, i.e.
concurrency) decouples from KV memory (the arena).

All allocation decisions are host-side, exact, and deterministic:
lowest-index-first for both slots and pages, so identical op sequences
produce identical page tables (pinned by ``tests/test_paged.py``).

:class:`PrefixCache` adds shared-prefix reuse on top: prompt prefixes
are content-keyed at page granularity (chain links
``(parent_entry_id, page_tokens)`` — equivalent to keying page ``j`` on
the full ``prompt[:(j+1)*page_size]`` tuple, collision-free by
construction, but each key stays O(page_size)), and a request whose
prompt extends a cached chain adopts those pages **read-only**
(refcounted) instead of re-prefilling them.
The cache holds its own reference on every published page, so a
retired publisher keeps its prefix warm; eviction under pressure drops
least-recently-matched entries whose page only the cache still holds.
"""
from __future__ import annotations

from bisect import insort
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.models.quant import (kv_dequantize, kv_quantize,
                                            kv_scales)
from ray_lightning_tpu.serve.request import OccupancyError

#: accepted ``kv_dtype`` spellings: None/"bf16" = store KV at the model's
#: compute dtype (the default, byte-identical to the pre-quantization
#: engines); "int8" = absmax-scaled int8 storage with f32 scales in a
#: parallel leaf (LLM.int8-style storage-only quantization: compute
#: stays at cfg.dtype, only the at-rest arena bytes halve)
KV_DTYPE_INT8 = "int8"


def check_kv_dtype(kv_dtype) -> bool:
    """Normalize/validate a ``kv_dtype`` option; returns True for the
    quantized path."""
    if kv_dtype in (None, "bf16"):
        return False
    if kv_dtype == KV_DTYPE_INT8:
        return True
    raise ValueError(
        f"kv_dtype must be None, 'bf16' or 'int8', got {kv_dtype!r}")


# ---------------------------------------------------------------- int8 KV
# Quantized KV storage is a 2-tuple ``(q_tree, s_tree)`` with the SAME
# pytree structure as the plain cache: KV leaves (ndim >= 4) hold int8
# codes in ``q_tree`` and f32 absmax scales (keepdims, reduced axes per
# granularity) in ``s_tree``; sub-4d bookkeeping leaves (cache_index)
# live unchanged in ``q_tree`` with a zero-size placeholder in
# ``s_tree``. The tuple flows through the jitted programs as an
# ordinary pytree — dequantize on the way in, re-quantize on the way
# out, both fused into the dispatch.
#
# The absmax quantize/dequantize math itself lives in models/quant.py
# (imported above): the page-native attention path inside the model
# needs the identical functions, and models must not depend on serve —
# re-exported here so existing callers keep their import site.


def _dense_reduce_axes(leaf) -> Tuple[int, ...]:
    # dense pool granularity: per (slot, position, head) — reduce the
    # head_dim axis only (finest practical: scales add ~1/(2*D) bytes)
    return (leaf.ndim - 1,)


def quantize_dense_cache(model, values):
    """Plain dense cache tree → the ``(q, s)`` storage tuple
    (per-position-per-head scales)."""
    def q_leaf(leaf):
        if leaf.ndim < 4:
            return leaf
        return kv_quantize(leaf, kv_scales(leaf, _dense_reduce_axes(leaf)))

    def s_leaf(leaf):
        if leaf.ndim < 4:
            return jnp.zeros((), jnp.float32)
        return kv_scales(leaf, _dense_reduce_axes(leaf))

    tm = jax.tree_util.tree_map
    return tm(q_leaf, values), tm(s_leaf, values)


def dense_storage_values(model, storage):
    """Materialize compute-dtype KV values from dense storage: identity
    for plain storage, dequantize for the ``(q, s)`` int8 tuple (the
    bookkeeping leaves pass through from ``q``)."""
    if not isinstance(storage, tuple):
        return storage
    q, s = storage
    dt = model.cfg.dtype
    return jax.tree_util.tree_map(
        lambda ql, sl: ql if ql.ndim < 4 else kv_dequantize(ql, sl, dt),
        q, s)


def dense_storage_commit(model, storage, values):
    """Write updated compute-dtype values back into dense storage:
    identity for plain storage, re-quantize for int8 (untouched rows
    round-trip idempotently: absmax codes saturate at exactly 127, so
    re-quantizing a dequantized group reproduces the same codes and
    scales — parked rows stay frozen through any number of dispatches)."""
    if not isinstance(storage, tuple):
        return values
    q, s = storage

    def commit_q(ql, vl):
        if ql.ndim < 4:
            return vl   # updated bookkeeping lives in the q tree
        return kv_quantize(vl, kv_scales(vl, _dense_reduce_axes(vl)))

    def commit_s(sl, vl):
        if vl.ndim < 4:
            return sl
        return kv_scales(vl, _dense_reduce_axes(vl))

    tm = jax.tree_util.tree_map
    return tm(commit_q, q, values), tm(commit_s, s, values)


# --------------------------------------------------- arena gather/scatter
def page_axis(model) -> int:
    """Arena/cache leaves are ``(pages|B, seq, H, D)`` unrolled or
    ``(n_layers, pages|B, seq, H, D)`` scanned — page axis == batch
    axis."""
    return 1 if model.cfg.scan_layers else 0


def arena_num_pages(model, arena) -> int:
    axis = page_axis(model)
    tree = arena[0] if isinstance(arena, tuple) else arena
    return next(leaf.shape[axis]
                for leaf in jax.tree_util.tree_leaves(tree)
                if leaf.ndim >= 4)


def _page_reduce_axes(axis: int, leaf) -> Tuple[int, ...]:
    # paged granularity: per (page, head) — reduce page_size and
    # head_dim; scales leaf is (…, P, 1, H, 1)
    return (axis + 1, axis + 3)


def gather_pages(model, arena, page_table):
    """Materialize the dense per-slot KV view from the arena: one gather
    per KV leaf, ``(S, pp)`` page table → ``(S, pp * page_size, …)``
    rows. Unmapped (−1) entries clamp to page 0 — finite stale bytes the
    per-row attention mask never admits (every attended position lies in
    a mapped page by construction) and the scatter never writes back.
    Int8 arenas dequantize inside the gather (page codes × page scales →
    compute dtype), so every program downstream sees the same
    compute-dtype view either way."""
    axis = page_axis(model)
    S, pp = page_table.shape
    idx = jnp.maximum(page_table.reshape(-1), 0)

    def to_view(pages):
        shape = list(pages.shape)
        shape[axis:axis + 2] = [S, pp * shape[axis + 1]]
        return pages.reshape(shape)

    if not isinstance(arena, tuple):
        def gather(leaf):
            if leaf.ndim < 4:
                return leaf
            return to_view(jnp.take(leaf, idx, axis=axis))

        return jax.tree_util.tree_map(gather, arena)

    q, s = arena
    dt = model.cfg.dtype

    def gather_q(ql, sl):
        if ql.ndim < 4:
            return ql
        pages = kv_dequantize(jnp.take(ql, idx, axis=axis),
                              jnp.take(sl, idx, axis=axis), dt)
        return to_view(pages)

    return jax.tree_util.tree_map(gather_q, q, s)


def scatter_pages(model, arena, view, page_table):
    """Write the dense view's rows back to their arena pages (inverse of
    :func:`gather_pages`). Unmapped entries scatter to a dropped
    out-of-range index. Pages shared between slots (refcounted prefix
    pages) receive identical values from every holder — nothing writes
    inside an adopted page (decode and chunk writes land at positions
    past the shared prefix) — so duplicate indices stay deterministic.
    Int8 arenas quantize inside the scatter: per-page-per-head absmax
    scales recomputed from the view's pages (untouched pages round-trip
    idempotently, same saturation argument as the dense commit)."""
    axis = page_axis(model)
    num_pages = arena_num_pages(model, arena)
    S, pp = page_table.shape
    pt = page_table.reshape(-1)
    idx = jnp.where(pt >= 0, pt, num_pages)

    def to_pages(arena_leaf, view_leaf):
        ps = arena_leaf.shape[axis + 1]
        shape = list(view_leaf.shape)
        shape[axis:axis + 2] = [S * pp, ps]
        return view_leaf.reshape(shape)

    def write(arena_leaf, pages):
        if axis == 0:
            return arena_leaf.at[idx].set(pages, mode="drop")
        return arena_leaf.at[:, idx].set(pages, mode="drop")

    if not isinstance(arena, tuple):
        def scatter(arena_leaf, view_leaf):
            if arena_leaf.ndim < 4:
                return arena_leaf
            return write(arena_leaf, to_pages(arena_leaf, view_leaf))

        return jax.tree_util.tree_map(scatter, arena, view)

    q, s = arena

    def scatter_q(ql, sl, vl):
        if ql.ndim < 4:
            return ql
        pages = to_pages(ql, vl)
        return write(ql, kv_quantize(
            pages, kv_scales(pages, _page_reduce_axes(axis, pages))))

    def scatter_s(ql, sl, vl):
        if ql.ndim < 4:
            return sl
        pages = to_pages(ql, vl)
        return write(sl, kv_scales(pages, _page_reduce_axes(axis, pages)))

    tm = jax.tree_util.tree_map
    return tm(scatter_q, q, s, view), tm(scatter_s, q, s, view)


class SlotPoolFull(OccupancyError):
    """No free KV slot (or, paged, not enough free pages) — admission
    control should have prevented this.

    Carries occupancy context so shed-load callers can log actionable
    rejections instead of a bare "full": ``slots_free``, ``pages_free``
    (None on the dense path), ``pages_needed`` (what the rejected
    request wanted, None for slot exhaustion) and ``active`` (in-flight
    request count).
    """

    def __init__(self, message: str, *, slots_free: Optional[int] = None,
                 pages_free: Optional[int] = None,
                 pages_needed: Optional[int] = None,
                 active: Optional[int] = None, **ctx):
        # **ctx: the tenancy layer extends the context (e.g. the tenant
        # whose max_active_slots quota refused the admission)
        super().__init__(message, slots_free=slots_free,
                         pages_free=pages_free, pages_needed=pages_needed,
                         active=active, **ctx)


def fold_rows(keys: jax.Array, data: jax.Array) -> jax.Array:
    """Per-row ``fold_in``: (B, 2) raw uint32 keys x (B,) ints — the key
    plumbing every serve program shares (engine step, prefill inject,
    spec rounds)."""
    return jax.vmap(jax.random.fold_in)(keys, data)


def pick_donated(donated, plain):
    """Donate device buffers wherever the backend honors it — the CPU
    backend ignores donation loudly, so tests stay quiet on the plain
    variant (one gating policy for every serve program)."""
    return plain if jax.default_backend() == "cpu" else donated


def check_seed_free(active_requests: Dict[int, "Request"],
                    request: "Request") -> None:
    """The no-key-reuse invariant shared by both pools: two co-resident
    slots may never carry the same sampling seed (their per-step
    ``fold_in`` key streams would collide token-for-token)."""
    for req in active_requests.values():
        if req.seed == request.seed:
            raise ValueError(
                f"PRNG key reuse across slots: request {request.id} "
                f"and in-flight request {req.id} share seed "
                f"{request.seed} — co-resident sample streams would "
                "collide; give one an explicit distinct seed")


class PagePool:
    """Owns the paged KV arena and the slot → pages mapping.

    ``arena`` is the cache pytree whose KV leaves are
    ``(num_pages, page_size, H, D)`` (layer-stacked when
    ``scan_layers``); sub-4d leaves (the shared ``cache_index``
    bookkeeping) keep the template values — the engine's per-row
    ``kv_positions`` path never reads them, and the chunk program
    overrides them per dispatch. The arena is built lazily on first
    access so pure accounting users (admission planning, the capacity
    bench) never allocate device memory.

    ``page_table`` is the ``(num_slots, pages_per_slot)`` int32 gather
    index (−1 = unmapped); refcounts make pages shareable: an adopted
    prefix page is freed only when its last holder (slot or
    :class:`PrefixCache`) lets go.
    """

    def __init__(self, model, num_slots: int, page_size: int,
                 num_pages: Optional[int] = None,
                 kv_dtype: Optional[str] = None):
        cfg = model.cfg
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if cfg.max_seq_len % page_size != 0:
            raise ValueError(
                f"page_size ({page_size}) must divide max_seq_len "
                f"({cfg.max_seq_len}) — the page table tiles the whole "
                "sequence axis")
        self._model = model
        self.kv_dtype = kv_dtype
        self._quantized = check_kv_dtype(kv_dtype)
        self.page_size = page_size
        self.pages_per_slot = cfg.max_seq_len // page_size
        self.num_pages = (num_pages if num_pages is not None
                          else num_slots * self.pages_per_slot)
        if self.num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got "
                             f"{self.num_pages}")
        self.num_slots = num_slots
        self.page_table = np.full((num_slots, self.pages_per_slot), -1,
                                  np.int32)
        self._arena = None
        self._free_pages: List[int] = list(range(self.num_pages))
        self._free_slots: List[int] = list(range(num_slots))
        self._ref = np.zeros((self.num_pages,), np.int64)
        self._requests: Dict[int, "Request"] = {}   # slot -> request
        self._span: Dict[int, int] = {}             # slot -> mapped pages

    # ------------------------------------------------------------- arena
    def _arena_template(self, shapes_only: bool = False):
        """The plain (unquantized) arena pytree — materialized, or as
        ShapeDtypeStructs when ``shapes_only`` (the byte-accounting
        probe must never allocate device memory)."""
        model = self._model
        run = jax.eval_shape if shapes_only else (
            lambda f, *a, **kw: f(*a, **kw))
        init = run(model.init, jax.random.PRNGKey(0),
                   jnp.zeros((1, 1), jnp.int32),
                   positions=jnp.zeros((1, 1), jnp.int32))
        template = init["cache"]
        axis = page_axis(model)

        def to_arena(leaf):
            if leaf.ndim < 4:
                return leaf
            shape = list(leaf.shape)
            shape[axis] = self.num_pages
            shape[axis + 1] = self.page_size
            if shapes_only:
                return jax.ShapeDtypeStruct(tuple(shape), leaf.dtype)
            return jnp.zeros(shape, leaf.dtype)

        return jax.tree_util.tree_map(to_arena, template)

    @property
    def arena(self):
        if self._arena is None:
            plain = self._arena_template()
            if self._quantized:
                axis = page_axis(self._model)

                def q_leaf(leaf):
                    if leaf.ndim < 4:
                        return leaf
                    return jnp.zeros(leaf.shape, jnp.int8)

                def s_leaf(leaf):
                    if leaf.ndim < 4:
                        # placeholder mirrors the bookkeeping leaf's
                        # SHAPE (not a scalar): the page-native path
                        # ships the scales tree as a flax collection,
                        # and scanned layouts slice every leaf of it
                        # along the layer axis
                        return jnp.zeros(leaf.shape, jnp.float32)
                    shape = list(leaf.shape)
                    for ax in _page_reduce_axes(axis, leaf):
                        shape[ax] = 1
                    return jnp.ones(shape, jnp.float32)

                tm = jax.tree_util.tree_map
                self._arena = (tm(q_leaf, plain), tm(s_leaf, plain))
            else:
                self._arena = plain
        return self._arena

    @arena.setter
    def arena(self, value):
        self._arena = value

    @property
    def bytes_per_page(self) -> int:
        """At-rest bytes one arena page costs across every KV leaf
        (int8: codes + the per-page-per-head f32 scales). Computed from
        shapes only — pure accounting callers (the equal-byte capacity
        bench/tests) never allocate the arena."""
        axis = page_axis(self._model)
        total = 0
        for leaf in jax.tree_util.tree_leaves(
                self._arena_template(shapes_only=True)):
            if leaf.ndim < 4:
                continue
            numel = 1
            for d, n in enumerate(leaf.shape):
                if d != axis:
                    numel *= n
            if self._quantized:
                scale_numel = 1
                reduced = _page_reduce_axes(axis, leaf)
                for d, n in enumerate(leaf.shape):
                    if d != axis and d not in reduced:
                        scale_numel *= n
                total += numel + scale_numel * 4
            else:
                total += numel * jnp.dtype(leaf.dtype).itemsize
        return total

    # -------------------------------------------------------- accounting
    @property
    def free_slots(self) -> int:
        return len(self._free_slots)

    @property
    def free_pages(self) -> int:
        return len(self._free_pages)

    @property
    def active(self) -> Dict[int, "Request"]:
        return dict(self._requests)

    def slot_of(self, request_id: int) -> Optional[int]:
        for slot, req in self._requests.items():
            if req.id == request_id:
                return slot
        return None

    def refcount(self, page: int) -> int:
        return int(self._ref[page])

    def refcounts(self, pages: np.ndarray) -> np.ndarray:
        """Vectorized refcount read (the prefix cache's evictable-count
        probe runs on every scheduling tick)."""
        return self._ref[pages]

    def pages_needed(self, request) -> int:
        """Worst-case pages for one request: its prompt plus its whole
        token budget (allocation is up-front at admission — no mid-decode
        growth, so an admitted request can never OOM the arena)."""
        total = request.prompt_len + request.max_new_tokens
        return -(-total // self.page_size)

    # --------------------------------------------------------- lifecycle
    def acquire(self, request, prefix_pages: Sequence[int] = ()) -> int:
        """Assign a slot and allocate its pages. ``prefix_pages`` are
        already-filled pages adopted read-only from a
        :class:`PrefixCache` chain (refcount bumped here); the remainder
        comes fresh from the free list, lowest index first."""
        if not self._free_slots:
            raise SlotPoolFull(
                f"all {self.num_slots} KV slots in use",
                slots_free=0, pages_free=self.free_pages,
                active=len(self._requests))
        check_seed_free(self._requests, request)
        need = self.pages_needed(request)
        fresh_need = need - len(prefix_pages)
        # adoption is capped below the full prompt (the engine always
        # recomputes at least the final prompt token into a private page)
        assert fresh_need >= 1, (need, len(prefix_pages))
        if fresh_need > len(self._free_pages):
            raise SlotPoolFull(
                f"request {request.id} needs {fresh_need} free KV "
                f"pages ({need} total, {len(prefix_pages)} from prefix "
                f"cache) but only {len(self._free_pages)} are free",
                slots_free=self.free_slots, pages_free=self.free_pages,
                pages_needed=fresh_need, active=len(self._requests))
        slot = self._free_slots.pop(0)
        fresh = [self._free_pages.pop(0) for _ in range(fresh_need)]
        row = list(prefix_pages) + fresh
        self.page_table[slot, :] = -1
        self.page_table[slot, :len(row)] = row
        for p in prefix_pages:
            self._ref[p] += 1
        for p in fresh:
            self._ref[p] = 1
        self._requests[slot] = request
        self._span[slot] = len(row)
        return slot

    def release(self, slot: int):
        """Retire a slot: decref its pages (shared prefix pages survive
        while the cache or another adopter still holds them), clear its
        page-table row, return the request."""
        req = self._requests.pop(slot)
        for j in range(self._span.pop(slot)):
            self.decref(int(self.page_table[slot, j]))
        self.page_table[slot, :] = -1
        insort(self._free_slots, slot)
        return req

    def incref(self, page: int) -> None:
        self._ref[page] += 1

    def decref(self, page: int) -> None:
        self._ref[page] -= 1
        assert self._ref[page] >= 0, page
        if self._ref[page] == 0:
            insort(self._free_pages, page)


class PrefixCache:
    """Content-keyed reuse of prompt-prefix KV pages.

    Entries are keyed by **chain links**: page ``j``'s key is
    ``(parent_entry_id, tokens of page j)``, where the parent is page
    ``j-1``'s entry (id 0 = the empty root). The parent id encodes the
    entire preceding token prefix by identity — exact and collision-free
    like a full ``prompt[:(j+1)*page_size]`` tuple key, but each key is
    O(page_size), so match/publish on a long system prompt stay linear
    instead of quadratic. Ids are assigned in publish order and never
    reused (an evicted middle entry permanently orphans its children;
    unmatchable, they age out through the same LRU eviction).

    The cache holds one page refcount per entry. ``match`` walks the
    longest cached chain for a new prompt (LRU-touching each hit),
    ``publish`` caches a finished prefill's full-prompt pages, and
    ``evict`` frees least-recently-matched entries whose page nobody
    else holds. Hit statistics are recorded by the engine at admission
    (``record_admission``) — AFTER slot/page acquisition succeeds — so
    ``hits`` counts pages actually adopted (the chunk-alignment cap
    applied, rolled-back admissions excluded), in lockstep with the
    ``serve_prefix_pages_reused_total`` counter.

    Adoption is always capped one token short of the whole prompt: the
    final prompt token must be recomputed (its logits seed the first
    sample, and KV caches store K/V, not logits) and that recompute has
    to land in a private page.
    """

    def __init__(self, pool: PagePool):
        self.pool = pool
        # (parent_id, page_tokens) -> (entry_id, arena page)
        self._entries: "OrderedDict[Tuple[int, Tuple[int, ...]], " \
            "Tuple[int, int]]" = OrderedDict()
        self._next_id = 1    # 0 is the empty-prefix root
        self._pages_arr = np.empty((0,), np.int64)  # cached entry pages
        self._pages_dirty = False
        self.hits = 0        # pages adopted by admissions
        self.lookups = 0     # pages that were eligible for adoption
        self.publishes = 0   # pages added to the cache
        self.evictions = 0   # pages dropped under pressure

    def __len__(self) -> int:
        return len(self._entries)

    def match(self, tokens: Sequence[int]) -> List[int]:
        """Longest chain of cached pages covering a strict prefix of
        ``tokens``; every hit page is LRU-touched."""
        ps = self.pool.page_size
        usable = max(0, (len(tokens) - 1) // ps)
        pages: List[int] = []
        parent = 0
        for j in range(usable):
            key = (parent, tuple(tokens[j * ps:(j + 1) * ps]))
            entry = self._entries.get(key)
            if entry is None:
                break
            parent, page = entry
            pages.append(page)
            self._entries.move_to_end(key)
        return pages

    def record_admission(self, eligible: int, adopted: int) -> None:
        """Count one admission's prefix reuse: ``eligible`` pages could
        have come from cache, ``adopted`` actually did."""
        self.lookups += eligible
        self.hits += adopted

    def publish(self, prompt: Sequence[int], slot: int) -> int:
        """Cache every page of ``slot`` wholly covered by ``prompt``
        (their KV is fully written once its prefill completed). Returns
        the number of newly cached pages."""
        pool = self.pool
        ps = pool.page_size
        added = 0
        parent = 0
        for j in range(len(prompt) // ps):
            key = (parent, tuple(prompt[j * ps:(j + 1) * ps]))
            entry = self._entries.get(key)
            if entry is not None:
                parent = entry[0]
                continue
            page = int(pool.page_table[slot, j])
            entry_id = self._next_id
            self._next_id += 1
            self._entries[key] = (entry_id, page)
            pool.incref(page)
            parent = entry_id
            added += 1
        if added:
            self._pages_dirty = True
        self.publishes += added
        return added

    def evictable(self) -> int:
        """Pages the cache could free right now (refcount == 1: only the
        cache still holds them). Called on every scheduling tick with
        waiters, so the entry→page array is cached (invalidated on
        publish/evict/drop) and the refcount test is one vectorized
        read instead of a Python loop over entries."""
        if self._pages_dirty:
            self._pages_arr = np.fromiter(
                (p for _eid, p in self._entries.values()), np.int64,
                count=len(self._entries))
            self._pages_dirty = False
        if not len(self._pages_arr):
            return 0
        return int(np.count_nonzero(
            self.pool.refcounts(self._pages_arr) == 1))

    def evict(self, n: int, protect: Sequence[int] = ()) -> int:
        """Free up to ``n`` pages, least-recently-matched first, skipping
        entries still adopted by a live slot and ``protect``\\ ed pages
        (e.g. a chain the current admission is about to adopt)."""
        guard = set(protect)
        freed = 0
        for key, (_eid, page) in list(self._entries.items()):
            if freed >= n:
                break
            if page in guard or self.pool.refcount(page) != 1:
                continue
            del self._entries[key]
            self._pages_dirty = True
            self.pool.decref(page)
            freed += 1
            self.evictions += 1
        return freed

    @property
    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    def drop(self) -> None:
        """Release every cache-held page reference (engine shutdown)."""
        for _eid, page in self._entries.values():
            self.pool.decref(page)
        self._entries.clear()
        self._pages_dirty = True
