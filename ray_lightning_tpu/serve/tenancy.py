"""Multi-tenant SLO-aware scheduling: tenant classes + weighted
fair-share queueing + class-aware admission control.

The FIFO scheduler treats all traffic as one class: a batch tenant
flooding the queue starves an interactive tenant's TTFT, and overload
sheds whoever arrives last rather than whichever class is over its
share (ROADMAP item 5). This module is the scheduling-policy layer a
multi-tenant platform needs, built so that **scheduling stays
ordering-only**: the tenancy layer decides *when* a request is
admitted, never *what tokens* it receives — every request's sample-key
stream is ``fold_in(fold_in(engine_base, req.seed), step)``, a pure
function of no scheduler state, so a request's tokens are identical to
a solo run whatever classes ride the queue next to it (enforced by
``tests/test_tenancy.py`` and the bench's tenancy gates).

- :class:`TenantClass` — one traffic class: a priority **tier**
  (``interactive`` tiers drain before ``batch`` tiers), a fair-share
  **weight** arbitrating within the tier, an optional TTFT-SLO target
  (feeds the per-tenant ``serve_tenant_slo_miss_total_<class>``
  counter), a per-class default deadline, and per-class quotas
  (``max_queue_depth`` sheds at submit, ``max_active_slots`` caps the
  KV slots the class may hold concurrently).
- :class:`TenantScheduler` — drop-in
  :class:`~ray_lightning_tpu.serve.scheduler.FifoScheduler` replacement
  holding one FIFO deque per class, driven by **deficit-weighted
  round-robin inside each tier**: each admission pick serves the first
  class (declaration order — the deterministic tie-break) holding >= 1
  deficit credit, replenishing every non-empty class ``quantum*weight``
  credits when none does, so admission counts converge to the weight
  ratios whenever classes stay backlogged. Interactive tiers drain
  first; **starvation counters** bound how long that priority can hold:
  every interactive pick made while batch work waits credits each
  waiting batch class its weight, and a class crossing
  ``starvation_threshold`` takes the next pick regardless of tier — the
  lowest-weight batch class is served at least once every
  ``ceil(threshold/weight) + 1`` admissions under sustained interactive
  saturation. All tie-breaks are declaration-order/FIFO deterministic,
  so tick-clock traces (and their JSONL event logs) replay
  byte-identically.
- :class:`ClassQueueFull` — a
  :class:`~ray_lightning_tpu.serve.scheduler.QueueFull` subclass raised
  when one *class* is at its own ``max_queue_depth``: the class sheds
  at the door with its name and depth in the occupancy context instead
  of consuming the global queue's headroom (class-aware admission
  control — the global bound still raises plain ``QueueFull``, now
  carrying the per-class depth/oldest-age breakdown).

A configuration holding only the default class is behaviorally
identical to the plain FIFO scheduler — one class's DWRR *is* FIFO, the
global bound and deadline policy are unchanged — which is what lets
``ServeClient(tenant_classes=...)`` arm tenancy without perturbing a
single existing trace (A/B-pinned by ``tests/test_tenancy.py``).

Crash replay and fleet failover preserve **class assignment** for free
(the class rides :attr:`Request.tenant` through snapshots and
re-admission); fair-share **state** is reconstructed, not checkpointed:
a rebuilt scheduler restarts its deficit/starvation counters at zero
and re-converges within one replenish round — bounded O(quantum)
transient unfairness, never lost or duplicated work
(``docs/serving.md#multi-tenant-scheduling``).
"""
from __future__ import annotations

import dataclasses
import math
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from ray_lightning_tpu.serve.request import DEFAULT_TENANT, Request
from ray_lightning_tpu.serve.scheduler import (ACTION_PREFILL,
                                               FifoScheduler, QueueFull,
                                               SchedulerConfig)

__all__ = ["TenantClass", "TenantScheduler", "ClassQueueFull",
           "DEFAULT_TENANT", "TIER_INTERACTIVE", "TIER_BATCH",
           "resolve_tenant_classes"]

TIER_INTERACTIVE = "interactive"
TIER_BATCH = "batch"


class ClassQueueFull(QueueFull):
    """One tenant class is at its own ``max_queue_depth``.

    Class-aware admission control: the class sheds at the door
    (``tenant`` / ``class_queue_depth`` / ``class_oldest_age`` in the
    occupancy context) instead of letting one tenant's backlog consume
    the global queue. A :class:`QueueFull` subclass, so every existing
    shed path (trace replay, fleet next-candidate offering) handles it
    unchanged."""

    def __init__(self, message: str, *, tenant: Optional[str] = None,
                 class_queue_depth: Optional[int] = None,
                 class_oldest_age: Optional[float] = None, **ctx):
        super().__init__(message, tenant=tenant,
                         class_queue_depth=class_queue_depth,
                         class_oldest_age=class_oldest_age, **ctx)


@dataclasses.dataclass(frozen=True)
class TenantClass:
    """One traffic class: priority tier + fair-share weight + quotas.

    ``tier``: ``"interactive"`` tiers drain before ``"batch"`` tiers
    (starvation counters bound the priority — see the module
    docstring). ``weight`` arbitrates within a tier: backlogged classes
    converge to admission shares proportional to their weights.

    ``ttft_slo``: optional target (client clock units) — retirements
    whose TTFT exceeds it bump ``serve_tenant_slo_miss_total_<name>``;
    the scheduler itself never reads it (SLOs are observed, admission
    is policy). ``default_deadline``: applied to this class's requests
    submitted without an explicit deadline (offset from arrival,
    overriding the global ``SchedulerConfig.default_deadline``).

    ``max_queue_depth``: per-class admission bound — at quota the class
    sheds :class:`ClassQueueFull` instead of queueing.
    ``max_active_slots``: cap on KV slots the class may hold
    concurrently (decoding + chunk-prefilling); a class at its slot
    quota contributes no admission candidates until a slot retires, so
    a batch class can be fenced off a reserved interactive slot.

    ``adapter``: the class's default LoRA adapter (multi-adapter
    serving, serve/adapters.py) — requests in this class submitted
    without an explicit ``adapter=`` decode under it; an explicit
    per-request adapter always wins. Resolution happens at engine
    admission (the resolved name is stamped onto the request, so
    crash replay and fleet failover re-bind identically).
    """
    name: str
    weight: float = 1.0
    tier: str = TIER_INTERACTIVE
    ttft_slo: Optional[float] = None
    default_deadline: Optional[float] = None
    max_queue_depth: Optional[int] = None
    max_active_slots: Optional[int] = None
    adapter: Optional[str] = None

    def __post_init__(self):
        if not self.name or not isinstance(self.name, str):
            raise ValueError(f"tenant class name must be a non-empty "
                             f"string, got {self.name!r}")
        if self.tier not in (TIER_INTERACTIVE, TIER_BATCH):
            raise ValueError(
                f"tier must be {TIER_INTERACTIVE!r} or {TIER_BATCH!r}, "
                f"got {self.tier!r}")
        if not self.weight > 0.0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        if self.ttft_slo is not None and self.ttft_slo <= 0:
            raise ValueError(f"ttft_slo must be > 0, got {self.ttft_slo}")
        if self.default_deadline is not None and self.default_deadline <= 0:
            raise ValueError(f"default_deadline must be > 0, got "
                             f"{self.default_deadline}")
        if self.max_queue_depth is not None and self.max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got "
                             f"{self.max_queue_depth}")
        if self.max_active_slots is not None and self.max_active_slots < 1:
            raise ValueError(f"max_active_slots must be >= 1, got "
                             f"{self.max_active_slots}")
        if self.adapter is not None and (
                not self.adapter or not isinstance(self.adapter, str)):
            raise ValueError(f"adapter must be a non-empty string or "
                             f"None, got {self.adapter!r}")


def resolve_tenant_classes(
        classes: Sequence[TenantClass]) -> "OrderedDict[str, TenantClass]":
    """Validate a class list into the declaration-ordered name map the
    scheduler and the engine share. Appends the default class (plain
    interactive, weight 1 — today's untenanted behavior) when the
    caller didn't declare their own ``"default"``, so requests that
    never name a tenant keep working."""
    if not classes:
        raise ValueError("tenant_classes must name at least one class")
    out: "OrderedDict[str, TenantClass]" = OrderedDict()
    for cls in classes:
        if not isinstance(cls, TenantClass):
            raise ValueError(
                f"tenant_classes entries must be TenantClass, got "
                f"{type(cls).__name__}")
        if cls.name in out:
            raise ValueError(f"duplicate tenant class {cls.name!r}")
        out[cls.name] = cls
    if DEFAULT_TENANT not in out:
        out[DEFAULT_TENANT] = TenantClass(DEFAULT_TENANT)
    return out


class _ClassQueue:
    """One class's live scheduler state: its FIFO deque + the DWRR
    deficit credit (within-tier fair share) + the starvation credit
    (cross-tier no-starvation bound) + shed/admit accounting."""

    __slots__ = ("cls", "index", "queue", "deficit", "starve",
                 "admitted", "shed")

    def __init__(self, cls: TenantClass, index: int):
        self.cls = cls
        self.index = index  # declaration order: THE deterministic tie-break
        self.queue: Deque[Request] = deque()
        self.deficit = 0.0
        self.starve = 0.0
        self.admitted = 0
        self.shed = 0


class TenantScheduler(FifoScheduler):
    """Per-class queues + deficit-weighted round-robin admission.

    Drop-in for :class:`FifoScheduler` (the chunk/decode drain policy,
    the prefill batching threshold, the page-aware admission probe and
    the deadline machinery are all inherited or mirrored exactly):
    only the *order requests leave the waiting side* changes, and with
    a single class it doesn't change at all. Selection is a pure
    function of (per-class queues, deficit/starvation counters,
    per-class active-slot occupancy), committed only when requests are
    actually popped — ``peek_action`` and the admission probe read the
    same plan without mutating it, the ``_drain_verdict`` discipline.
    """

    def __init__(self, classes: Sequence[TenantClass],
                 config: Optional[SchedulerConfig] = None,
                 starvation_threshold: float = 8.0):
        super().__init__(config)
        if starvation_threshold <= 0:
            raise ValueError(f"starvation_threshold must be > 0, got "
                             f"{starvation_threshold}")
        self.starvation_threshold = starvation_threshold
        self.classes = resolve_tenant_classes(classes)
        self._queues: "OrderedDict[str, _ClassQueue]" = OrderedDict(
            (name, _ClassQueue(cls, i))
            for i, (name, cls) in enumerate(self.classes.items()))
        self._tiers: Dict[str, List[_ClassQueue]] = {
            TIER_INTERACTIVE: [cq for cq in self._queues.values()
                               if cq.cls.tier == TIER_INTERACTIVE],
            TIER_BATCH: [cq for cq in self._queues.values()
                         if cq.cls.tier == TIER_BATCH]}
        # the base deque stays empty: every FifoScheduler surface that
        # touched it is overridden below — the inherited pieces
        # (drain_action latch, config validation) are queue-free

    # ---------------------------------------------------------- queries
    def __len__(self) -> int:
        return sum(len(cq.queue) for cq in self._queues.values())

    @property
    def waiting(self) -> List[Request]:
        """Queued requests, class-declaration order then FIFO within
        each class (the failover re-admission order — deterministic;
        token streams are order-independent by the serve key-stream
        contract, so any deterministic order is correct)."""
        return [req for cq in self._queues.values() for req in cq.queue]

    def class_depths(self) -> Dict[str, int]:
        """Per-class queued counts — the shed-context breakdown and the
        fleet router's class-aware load signal."""
        return {name: len(cq.queue) for name, cq in self._queues.items()}

    def class_oldest(self, now: Optional[float]) -> Dict[str, float]:
        """Per-class head age (clock units), classes with measurable
        heads only — the oldest-age breakdown shed context carries."""
        out: Dict[str, float] = {}
        if now is None:
            return out
        for name, cq in self._queues.items():
            if cq.queue and cq.queue[0].arrival_time is not None:
                out[name] = now - cq.queue[0].arrival_time
        return out

    def oldest_age(self, now: Optional[float]) -> Optional[float]:
        ages = self.class_oldest(now)
        return max(ages.values()) if ages else None

    def shed_counts(self) -> Dict[str, int]:
        """Per-class submit-time sheds (quota + global), cumulative."""
        return {name: cq.shed for name, cq in self._queues.items()}

    def admitted_counts(self) -> Dict[str, int]:
        """Per-class admissions popped for prefill, cumulative — what
        the fair-share convergence and no-starvation tests read."""
        return {name: cq.admitted for name, cq in self._queues.items()}

    # ---------------------------------------------------------- mutation
    def submit(self, request: Request,
               now: Optional[float] = None) -> None:
        """Enqueue under class-aware admission control: the request's
        class must exist, its own ``max_queue_depth`` sheds
        :class:`ClassQueueFull` (the class is over ITS share — the
        global queue may have room), and the global bound sheds
        :class:`QueueFull` carrying the per-class breakdown."""
        cq = self._queues.get(request.tenant)
        if cq is None:
            raise ValueError(
                f"unknown tenant {request.tenant!r}: declared classes "
                f"are {list(self._queues)}")
        cls = cq.cls
        if cls.max_queue_depth is not None \
                and len(cq.queue) >= cls.max_queue_depth:
            cq.shed += 1
            raise ClassQueueFull(
                f"tenant {cls.name!r} at max_queue_depth="
                f"{cls.max_queue_depth}", tenant=cls.name,
                class_queue_depth=len(cq.queue),
                class_oldest_age=self.class_oldest(now).get(cls.name),
                queue_depth=len(self), oldest_age=self.oldest_age(now))
        if len(self) >= self.config.max_queue_depth:
            cq.shed += 1
            raise QueueFull(
                f"queue at max_queue_depth={self.config.max_queue_depth}",
                queue_depth=len(self), oldest_age=self.oldest_age(now),
                class_depths=self.class_depths(),
                class_oldest=self.class_oldest(now) or None)
        # per-class deadline policy: the class's own default wins, the
        # global SchedulerConfig default backs it up (one shared copy
        # of the stamping rules — the FIFO path cannot drift from this
        # one)
        self._stamp_admission(
            request, now,
            cls.default_deadline if cls.default_deadline is not None
            else self.config.default_deadline)
        cq.queue.append(request)

    def requeue_front(self, requests: List[Request]) -> None:
        """Seed-deferred requests rejoin their own class's queue head in
        original relative order (their admission credit was already
        spent — a deferral costs the class one quantum of fairness,
        never a token)."""
        for req in reversed(requests):
            self._queues[req.tenant].queue.appendleft(req)

    def expire(self, now: float) -> List[Request]:
        expired: List[Request] = []
        for cq in self._queues.values():
            gone = [r for r in cq.queue
                    if r.deadline is not None and now >= r.deadline]
            if gone:
                dead = {id(r) for r in gone}
                cq.queue = deque(r for r in cq.queue
                                 if id(r) not in dead)
                expired.extend(gone)
        if expired:
            self._reset_idle()
        return expired

    # --------------------------------------------------------- selection
    def _active_by_class(self, engine) -> Dict[str, int]:
        """KV slots each class currently holds (decoding AND
        chunk-prefilling — both are acquired slots), for the
        ``max_active_slots`` quota."""
        counts: Dict[str, int] = {}
        for req in engine.active_requests.values():
            tenant = getattr(req, "tenant", DEFAULT_TENANT)
            counts[tenant] = counts.get(tenant, 0) + 1
        return counts

    def _plan(self, limit: int, active_by_class: Dict[str, int]) \
            -> Tuple[List[Request], Dict[str, float], Dict[str, float],
                     Dict[str, int]]:
        """Fair-share selection order, PURE: the next ``limit`` requests
        the scheduler would admit, plus the deficit/starvation state
        that selection would leave behind. ``peek_action`` and the
        admission-width probe discard the state; :meth:`_take` commits
        it — one copy of the policy, so the lookahead can never drift
        from the pops (the ``_drain_verdict`` discipline). Selection is
        sequential, so the plan is prefix-stable: the first k picks of
        ``_plan(L)`` equal ``_plan(k)`` for any k <= L."""
        deficit = {n: cq.deficit for n, cq in self._queues.items()}
        starve = {n: cq.starve for n, cq in self._queues.items()}
        taken = {n: 0 for n in self._queues}
        picks: List[Request] = []

        def eligible(cq: _ClassQueue) -> bool:
            if taken[cq.cls.name] >= len(cq.queue):
                return False
            cap = cq.cls.max_active_slots
            if cap is not None and (active_by_class.get(cq.cls.name, 0)
                                    + taken[cq.cls.name]) >= cap:
                return False
            return True

        while len(picks) < limit:
            inter = [cq for cq in self._tiers[TIER_INTERACTIVE]
                     if eligible(cq)]
            batch = [cq for cq in self._tiers[TIER_BATCH] if eligible(cq)]
            if not inter and not batch:
                break
            starved = [cq for cq in batch
                       if starve[cq.cls.name] >= self.starvation_threshold]
            if inter and starved:
                # the no-starvation escape hatch: a batch class whose
                # credit crossed the threshold takes this pick even
                # though interactive work waits (highest credit first,
                # declaration order on ties — deterministic)
                chosen = max(starved, key=lambda cq: (starve[cq.cls.name],
                                                      -cq.index))
                starve[chosen.cls.name] = 0.0
            elif inter:
                chosen = self._drr_pick(inter, deficit)
                for cq in batch:
                    # passed over in favor of a higher tier: credit
                    # accrues by weight, so heavier batch classes cross
                    # the threshold sooner
                    starve[cq.cls.name] += cq.cls.weight
            else:
                chosen = self._drr_pick(batch, deficit)
                starve[chosen.cls.name] = 0.0
            picks.append(chosen.queue[taken[chosen.cls.name]])
            taken[chosen.cls.name] += 1
        return picks, deficit, starve, taken

    @staticmethod
    def _drr_pick(cands: List[_ClassQueue],
                  deficit: Dict[str, float]) -> _ClassQueue:
        """One deficit-round-robin pick among ``cands`` (declaration
        order): first class holding a full credit wins; when none does,
        every candidate is replenished ``quantum * weight`` with the
        quantum sized so the lightest candidate reaches one credit —
        shares stay proportional to weights (DRR is quantum-scale
        invariant) and the replenish loop terminates in one round."""
        while True:
            for cq in cands:
                if deficit[cq.cls.name] >= 1.0:
                    deficit[cq.cls.name] -= 1.0
                    return cq
            quantum = 1.0 / min(cq.cls.weight for cq in cands)
            for cq in cands:
                deficit[cq.cls.name] += quantum * cq.cls.weight

    def _take(self, k: int, engine) -> List[Request]:
        """Pop the next ``k`` fair-share picks and COMMIT the
        deficit/starvation state the plan computed."""
        picks, deficit, starve, taken = self._plan(
            k, self._active_by_class(engine))
        for req in picks:
            cq = self._queues[req.tenant]
            head = cq.queue.popleft()
            assert head is req, "tenancy plan desynced from its queues"
            cq.admitted += 1
        for name, cq in self._queues.items():
            cq.deficit = deficit[name]
            cq.starve = starve[name]
        self._reset_idle()
        return picks

    def _reset_idle(self) -> None:
        # an idle class banks no credit: deficits/starvation reset when
        # its queue drains, so a returning burst competes from scratch
        # instead of cashing in hours of phantom backlog
        for cq in self._queues.values():
            if not cq.queue:
                cq.deficit = 0.0
                cq.starve = 0.0

    # ----------------------------------------------------------- policy
    def _admit_width(self, engine) -> int:
        """The FifoScheduler admission-width rule over the fair-share
        plan instead of the FIFO head prefix — same free-slot gate,
        same page-aware probe, same prefill batching threshold, so a
        default-only configuration is decision-for-decision identical
        to the base scheduler."""
        free = engine.free_slots
        chunks = getattr(engine, "chunk_pending", 0)
        total = len(self)
        if not total or free <= 0:
            return 0
        limit = min(total, free)
        cands = self._plan(limit, self._active_by_class(engine))[0]
        if not cands:
            return 0  # every queued class is at its active-slot quota
        probe = getattr(engine, "admissible_prefix", None)
        if probe is not None:
            k = min(len(cands), probe(cands))
        else:
            k = min(len(cands), engine.prefill_batch)
        if k <= 0:
            return 0
        if engine.active_count == 0 and not chunks:
            return k
        need = max(1, math.ceil(
            (1.0 - self.config.prefill_priority)
            * min(engine.prefill_batch, free)))
        return k if total >= need else 0

    def next_action(self, engine) -> Tuple[str, List[Request]]:
        k = self._admit_width(engine)
        if k > 0:
            return ACTION_PREFILL, self._take(k, engine)
        return self.drain_action(engine), []
