"""Write-ahead request journal: driver-death survival for serving.

Every recovery the serve stack already owns — replica failover (PR 8),
kill -9 ledger replay (PR 16), poison containment (PR 18) — assumes the
*driver* survives: the progress ledger, fleet clock epoch, tenancy
counters and adapter bindings all live in driver memory. This module
moves the request state machine onto disk so a driver crash (OOM,
SIGKILL, host reboot) loses nothing that matters:

- **Admissions** — the full :class:`~ray_lightning_tpu.serve.request.
  Request` (prompt, sampling params, seed, deadline, tenant class,
  adapter binding, any ``replay_tokens`` it re-admitted with).
- **Frontier progress** — emitted-token deltas per request at each
  synced step (the same ``step_sync`` frontier the PR 13 replay
  contract commits: :meth:`ServeEngine.snapshot_in_flight` only ever
  reports tokens the driver has actually observed).
- **Retirements** — completion ids with finish reason, so restart is
  exactly-once over the fsync horizon and never re-emits a request
  whose retire record is durable.

The file is append-only JSONL: each line is ``crc32hex SPACE payload``
where the CRC32 is over the canonical JSON payload bytes. Records are
schema-versioned (the ``open`` record carries ``v`` and the writer
generation). Durability is batched: the writer fsyncs every
``sync_every`` appends (and on :meth:`shutdown`), so the crash-loss
window is bounded by ``sync_every`` records — a retire record lost to
that window replays its request on restart (at-least-once beyond the
fsync horizon, exactly-once within it; see
docs/reliability.md#driver-death-survival--warm-restart).

The reader (:func:`read_journal`) folds the log into a
:class:`JournalState`: a torn final record — the half-written line an
interrupted ``write(2)`` leaves — is dropped and flagged
(``torn_tail``); a bad CRC *before* the final line is damage, not a
torn tail, and raises :class:`JournalCorrupt`.

Token identity across restart holds by the same argument as replica
failover: a request's sampling-key stream is
``fold_in(fold_in(engine_base, request.seed), step)`` — position-
indexed and a pure function of no driver state — so re-feeding
``prompt + frontier`` through prefill resumes the stream at step
``len(frontier)`` bit-identically (docs/reliability.md).

``Journal(path)`` is handed to ``ServeClient(journal=)`` /
``ReplicaFleet(journal=)``; the owning client/fleet closes it in its
own ``shutdown()``. ``journal=None`` (the default) is the repo-wide
zero-cost contract: every hot-path hook is one attribute read and a
``None`` check.
"""
from __future__ import annotations

import dataclasses
import json
import os
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_lightning_tpu.serve.request import Completion, Request

__all__ = ["Journal", "JournalState", "JournalCorrupt", "read_journal",
           "SCHEMA_VERSION"]

#: bump when a record's shape changes incompatibly; readers refuse
#: journals written by a NEWER schema (older ones they can still fold)
SCHEMA_VERSION = 1

REC_OPEN = "open"      # writer (re)opened the journal: {v, gen}
REC_ADMIT = "admit"    # request admitted: {req: <full Request doc>}
REC_FRONT = "front"    # frontier delta: {id, k, d[, ft]}
REC_RETIRE = "retire"  # request retired: {id, reason, n}

#: journal telemetry (docs/observability.md)
COUNTER_JOURNAL_RECORDS = "serve_journal_records_total"
COUNTER_JOURNAL_SYNCS = "serve_journal_syncs_total"
COUNTER_JOURNAL_REPLAYED = "serve_journal_replayed_requests_total"
COUNTER_JOURNAL_STALE = "serve_journal_stale_dropped_total"
EVENT_JOURNAL_RESTORED = "journal.restored"
EVENT_JOURNAL_STALE = "journal.stale_dropped"

_REQ_FIELDS = frozenset(f.name for f in dataclasses.fields(Request))


def _canonical(doc: Dict[str, Any]) -> str:
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def _crc(payload: str) -> int:
    return zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF


class JournalCorrupt(ValueError):
    """Mid-file damage (bad CRC / bad JSON before the final record) or
    a journal written by a newer schema than this reader understands.
    Distinct from a torn tail, which the reader tolerates silently."""


class Journal:
    """Append-only WAL over one serving session's request state.

    ``sync_every`` bounds the durability window: the writer fsyncs
    after every ``sync_every`` appended records (1 = every record —
    maximum durability, maximum syscall cost). The ``open`` record is
    always synced immediately so the generation fence is durable
    before the first admission.

    ``generation`` is the split-brain fence for the process backend:
    the driver stamps it into every worker at spawn, and a restarted
    driver (which reopens the journal with a bumped generation via
    ``restore``) refuses any queue message still carrying the dead
    driver's generation.

    Call :meth:`shutdown` (or :meth:`close`) when done; the owning
    ``ServeClient``/``ReplicaFleet`` does this from its own
    ``shutdown()``. Safe mid-flight: closing never truncates, and the
    reader tolerates whatever tail a crash left behind.
    """

    def __init__(self, path: str, *, sync_every: int = 8,
                 generation: int = 0, telemetry: Any = None):
        if sync_every < 1:
            raise ValueError(
                f"sync_every must be >= 1, got {sync_every}")
        if generation < 0:
            raise ValueError(
                f"generation must be >= 0, got {generation}")
        self.path = str(path)
        self.sync_every = int(sync_every)
        self.generation = int(generation)
        self._tel = telemetry
        self._file: Optional[Any] = open(self.path, "a", encoding="utf-8")
        self._unsynced = 0
        # frontier lengths already journaled per live request id —
        # what turns note_frontier's cumulative streams into deltas
        self._sent: Dict[int, int] = {}
        self._ft_sent: set = set()
        self._retired: set = set()
        self.records = 0
        self.syncs = 0
        self._append({"t": REC_OPEN, "v": SCHEMA_VERSION,
                      "gen": self.generation})
        self.sync()

    @property
    def closed(self) -> bool:
        return self._file is None

    # ---------------------------------------------------------- writing
    def _append(self, doc: Dict[str, Any]) -> None:
        f = self._file
        if f is None:
            raise RuntimeError(f"journal {self.path} is closed")
        payload = _canonical(doc)
        f.write(f"{_crc(payload):08x} {payload}\n")
        self.records += 1
        self._unsynced += 1
        if self._unsynced >= self.sync_every:
            self.sync()
        tel = self._tel
        if tel is not None:
            tel.metrics.counter(
                COUNTER_JOURNAL_RECORDS,
                help="records appended to the serve WAL").inc()

    def sync(self) -> None:
        """Flush + fsync any unsynced appends (no-op when clean)."""
        f = self._file
        if f is None or not self._unsynced:
            return
        f.flush()
        os.fsync(f.fileno())
        self._unsynced = 0
        self.syncs += 1
        tel = self._tel
        if tel is not None:
            tel.metrics.counter(
                COUNTER_JOURNAL_SYNCS,
                help="batched fsyncs of the serve WAL").inc()

    def admit(self, request: Request) -> None:
        """Journal one admission — the full request, so restart can
        rebuild it byte-for-byte (tenant and adapter binding included).
        Re-admitting an id (failover replay, warm restart) re-journals
        it; the reader takes the LAST admit record as authoritative and
        resets the id's frontier to its ``replay_tokens``."""
        doc = dataclasses.asdict(request)
        doc["prompt"] = [int(t) for t in doc["prompt"]]
        self._append({"t": REC_ADMIT, "req": doc})
        self._sent[request.id] = len(request.replay_tokens or ())
        if request.first_token_time is not None:
            self._ft_sent.add(request.id)

    def note_frontier(self, request_id: int, tokens: Sequence[int],
                      first_token_time: Optional[float] = None) -> None:
        """Journal the part of ``tokens`` (the request's CUMULATIVE
        synced stream, replay included) not yet on disk. No delta and
        no fresh first-token stamp → no record, so idle ticks write
        nothing. Unknown or already-retired ids are ignored."""
        sent = self._sent.get(request_id)
        if sent is None:
            return
        delta = [int(t) for t in tokens[sent:]]
        ft: Optional[float] = None
        if first_token_time is not None and request_id not in self._ft_sent:
            ft = float(first_token_time)
        if not delta and ft is None:
            return
        doc: Dict[str, Any] = {"t": REC_FRONT, "id": int(request_id),
                               "k": sent, "d": delta}
        if ft is not None:
            doc["ft"] = ft
            self._ft_sent.add(request_id)
        self._append(doc)
        self._sent[request_id] = sent + len(delta)

    def retire(self, completion: Completion) -> None:
        """Journal one retirement — the exactly-once commit point.
        Duplicate retires of an id are dropped here, so a journal never
        holds two retire records for one admission epoch; the durable
        record is what stops restart from re-emitting the request."""
        rid = int(completion.request_id)
        if rid in self._retired:
            return
        self._retired.add(rid)
        self._sent.pop(rid, None)
        self._ft_sent.discard(rid)
        self._append({"t": REC_RETIRE, "id": rid,
                      "reason": completion.finish_reason,
                      "n": len(completion.tokens)})

    # --------------------------------------------------------- teardown
    def shutdown(self) -> None:
        """Sync and close the file handle. Idempotent."""
        f = self._file
        if f is None:
            return
        self.sync()
        self._file = None
        f.close()

    #: file-handle idiom alias (the teardown lint accepts either)
    close = shutdown


@dataclasses.dataclass
class JournalState:
    """A journal folded into its end state by :func:`read_journal`.

    ``admitted`` maps id → the last-journaled :class:`Request` (with
    ``first_token_time`` re-applied from frontier records);
    ``frontier`` maps id → the full synced token stream (replay
    tokens included); ``retired`` maps id → finish reason.
    ``duplicate_retires`` counts retire records for already-retired
    ids (always 0 for a journal written by :class:`Journal`; the
    report tool surfaces it as a damage diagnosis).
    """
    path: str
    generation: int = 0
    schema_version: int = SCHEMA_VERSION
    admitted: Dict[int, Request] = dataclasses.field(default_factory=dict)
    frontier: Dict[int, List[int]] = dataclasses.field(default_factory=dict)
    retired: Dict[int, str] = dataclasses.field(default_factory=dict)
    records: int = 0
    torn_tail: bool = False
    duplicate_retires: int = 0

    def pending(self) -> List[Tuple[Request, List[int]]]:
        """Unretired admissions with their journaled frontiers, in id
        order — exactly what warm restart re-admits."""
        return [(self.admitted[rid], list(self.frontier.get(rid, [])))
                for rid in sorted(self.admitted)
                if rid not in self.retired]

    @property
    def next_request_id(self) -> int:
        return max(self.admitted, default=-1) + 1


def _parse_line(line: str) -> Dict[str, Any]:
    if len(line) < 10 or line[8] != " ":
        raise ValueError(f"malformed record header: {line[:16]!r}")
    want = int(line[:8], 16)
    payload = line[9:]
    if _crc(payload) != want:
        raise ValueError("CRC mismatch")
    doc = json.loads(payload)
    if not isinstance(doc, dict) or "t" not in doc:
        raise ValueError("record is not a typed object")
    return doc


def read_journal(path: str) -> JournalState:
    """Fold a journal file into its :class:`JournalState`.

    Tolerates exactly one torn record, at the tail (dropped,
    ``torn_tail=True``): that is what an interrupted append looks
    like. Any earlier unparseable record, a frontier delta that does
    not extend its request's journaled stream contiguously, or a
    newer-schema ``open`` record raises :class:`JournalCorrupt`.
    """
    state = JournalState(path=str(path))
    with open(path, "r", encoding="utf-8") as f:
        raw = f.read()
    lines = raw.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    for i, line in enumerate(lines):
        try:
            doc = _parse_line(line)
        except ValueError:
            if i == len(lines) - 1:
                state.torn_tail = True
                break
            raise JournalCorrupt(
                f"{path}: unreadable record at line {i + 1} of "
                f"{len(lines)} — damage before the tail, not a torn "
                f"final record")
        kind = doc.get("t")
        if kind == REC_OPEN:
            v = int(doc.get("v", 0))
            if v > SCHEMA_VERSION:
                raise JournalCorrupt(
                    f"{path}: schema v{v} is newer than this reader "
                    f"(v{SCHEMA_VERSION})")
            state.schema_version = v
            state.generation = max(state.generation,
                                   int(doc.get("gen", 0)))
        elif kind == REC_ADMIT:
            rdoc = doc.get("req") or {}
            req = Request(**{k: v for k, v in rdoc.items()
                             if k in _REQ_FIELDS})
            state.admitted[req.id] = req
            state.frontier[req.id] = list(req.replay_tokens or ())
        elif kind == REC_FRONT:
            rid = int(doc["id"])
            cur = state.frontier.get(rid)
            if cur is None or rid in state.retired:
                state.records += 1
                continue
            if int(doc.get("k", -1)) != len(cur):
                raise JournalCorrupt(
                    f"{path}: frontier gap for request {rid} at line "
                    f"{i + 1}: record continues from {doc.get('k')}, "
                    f"journaled stream holds {len(cur)}")
            cur.extend(int(t) for t in doc.get("d", ()))
            if "ft" in doc:
                req = state.admitted.get(rid)
                if req is not None and req.first_token_time is None:
                    req.first_token_time = float(doc["ft"])
        elif kind == REC_RETIRE:
            rid = int(doc["id"])
            if rid in state.retired:
                state.duplicate_retires += 1
            else:
                state.retired[rid] = str(doc.get("reason", ""))
        # unknown record kinds from an older writer are skipped
        state.records += 1
    return state
