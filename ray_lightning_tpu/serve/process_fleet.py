"""Process-backend replica fleet: one dispatch process per replica.

:class:`ProcessReplicaFleet` is what ``ReplicaFleet(backend="process")``
constructs — the same fleet contract as the in-process backend
(``serve/fleet.py``: router affinity, snapshot+replay failover, tenancy
class preservation, watchdog/hung-dispatch verdicts, standby promotion,
autoscaling) re-seated on the launcher/actor machinery the training
gangs use:

- every replica is a :class:`~ray_lightning_tpu.launchers.serve_worker.
  ServeReplicaWorker` inside a spawned
  :class:`~ray_lightning_tpu.launchers.process_backend.ProcessRay`
  actor, driving its OWN dispatch loop — N replicas really dispatch N
  engines concurrently (the in-process fleet time-slices one thread,
  which is why its measured throughput is ~0.5× a single engine);
- submits are RPCs returning structured verdicts; completions, token
  progress, occupancy mirrors, and obs events flow back over ONE
  manager-hosted queue (the existing queue transport — it pickles by
  reference and **survives worker death**, so a kill -9's last flushed
  batch is still drainable);
- the fleet clock rides the heartbeat channel: workers beat
  ``(replica_id, ops, t)`` from their dispatch-loop thread through a
  dedicated queue, the driver re-stamps on receipt and runs the same
  :class:`~ray_lightning_tpu.reliability.gang.GangMonitor` silence
  arithmetic as a training gang — a wedged dispatch loop stops beating
  and is failed over in bounded wall time;
- the router is the in-process :class:`~ray_lightning_tpu.serve.fleet.
  Router`, UNMODIFIED: each seat exposes a duck-typed scheduler/engine
  mirror fed by per-turn status messages (and refreshed synchronously
  inside every submit verdict), so scoring reads the same signals it
  would read off live objects.

**Failover** has no snapshot RPC to call — a kill -9 answers nothing —
so the driver keeps its own ledger: every admitted request's object
plus the cumulative tokens its replica last flushed. On a death verdict
the ledger entries re-admit to survivors with ``replay_tokens`` set to
the flushed stream; the PR 3 replay contract (sampling keys are a pure
function of (engine seed, request seed, step)) regenerates whatever was
emitted-but-unflushed, so greedy AND sampled outputs stay
token-identical. Death classification consults the process backend's
``_dead`` latch FIRST (:func:`~ray_lightning_tpu.reliability.gang.
actor_alive` — the PR 11 rule): a hard-killed replica is reported
``replica.dead`` even when the first symptom was a failed submit RPC
under load, never misclassified as a dispatch error.

Clock: wall seconds only (``clock=`` is rejected) — the driver stamps
``epoch = time.time()`` at construction and every worker computes
``now() = time.time() - epoch``, so deadlines, arrival times, and TTFT
stamps mean the same thing on every process (one host, one clock).
Autoscaler hysteresis counts **evaluations** (at most one per
``scale_eval_interval`` wall seconds), not ticks — the pump loop spins
far faster than the in-process fleet's dispatch rounds.

See ``docs/serving.md#replica-fleet`` for when to pick each backend.
"""
from __future__ import annotations

import queue as _queue
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_lightning_tpu.reliability import faults, log_suppressed
from ray_lightning_tpu.reliability.faults import SITE_SERVE_DRIVER
from ray_lightning_tpu.serve.containment import SeatTable
from ray_lightning_tpu.serve.journal import (COUNTER_JOURNAL_STALE,
                                             EVENT_JOURNAL_STALE)
from ray_lightning_tpu.serve.fleet import (COUNTER_FAILOVERS,
                                           COUNTER_POISON_FAILED,
                                           COUNTER_READMITTED, COUNTER_SHED,
                                           EVENT_DEGRADED, EVENT_FAILOVER,
                                           EVENT_POISON_FAILED,
                                           EVENT_PROBATION,
                                           EVENT_PROBATION_CLEARED,
                                           EVENT_QUARANTINE,
                                           EVENT_READMIT_PARKED,
                                           EVENT_REPLICA_DRAINING,
                                           EVENT_REPLICA_PROMOTED,
                                           EVENT_RESTORED,
                                           EVENT_SCALE_IN, EVENT_SCALE_OUT,
                                           EVENT_SHED, FleetConfig,
                                           FleetDegraded, FleetSaturated,
                                           GAUGE_QUARANTINED,
                                           GAUGE_QUEUE_DEPTH,
                                           GAUGE_REPLICAS_LIVE, ReplicaFleet,
                                           Router, RouterConfig)
from ray_lightning_tpu.serve.request import (Completion, DEFAULT_TENANT,
                                             FINISH_REJECTED, FINISH_TIMEOUT,
                                             Request)
from ray_lightning_tpu.serve.scheduler import QueueFull

__all__ = ["ProcessReplicaFleet"]

#: process-backend death classification events (docs/observability.md).
#: ``replica.dead``: the worker PROCESS is gone (kill -9, OOM, exit) —
#: the ``_dead``-latch-first rule guarantees this verdict wins over a
#: concurrent RPC/dispatch error. ``replica.error``: the process is
#: alive but its dispatch loop crashed (MSG_CRASH). A live-but-silent
#: replica keeps the in-process fleet's hang verdict (``fleet.failover``
#: with ``dead=False``).
EVENT_REPLICA_DEAD = "replica.dead"
EVENT_REPLICA_ERROR = "replica.error"


def _classify_failure(actor: Any, crashed: bool) -> str:
    """``"dead"`` | ``"error"`` | ``"hung"`` for a failed replica.

    The ``_dead`` latch is consulted FIRST (via
    :func:`~ray_lightning_tpu.reliability.gang.actor_alive`): the
    process backend's reader thread latches it on pipe EOF *before*
    failing any in-flight future, and ``Process.is_alive()`` can report
    a just-killed child as running in the teardown window — so under
    load a hard-killed replica's first symptom is often a dispatch
    error, and classifying on the symptom would report
    ``replica.error``. Same fix as the PR 11 gang-side
    ``worker.dead``-vs-``worker.error`` flake."""
    from ray_lightning_tpu.reliability.gang import actor_alive
    if not actor_alive(actor):
        return "dead"
    return "error" if crashed else "hung"


class _MirrorPages:
    __slots__ = ("num_pages",)

    def __init__(self) -> None:
        self.num_pages = 1


class _MirrorEngine:
    """Engine occupancy mirror the unmodified Router scores: updated
    from MSG_STATUS payloads (and submit verdicts)."""

    __slots__ = ("active_count", "chunk_pending", "free_pages", "pool")

    def __init__(self) -> None:
        self.active_count = 0
        self.chunk_pending = 0
        self.free_pages: Optional[int] = None
        self.pool = _MirrorPages()


class _MirrorScheduler:
    """Scheduler depth mirror. ``class_depths()`` is always present and
    empty when the fleet is untenanted — ``Router.class_load`` then
    scores 0 for every request, byte-identical to the in-process
    untenanted order."""

    __slots__ = ("depth", "oldest", "_class_depths", "_class_oldest")

    def __init__(self) -> None:
        self.depth = 0
        self.oldest: Optional[float] = None
        self._class_depths: Dict[str, int] = {}
        self._class_oldest: Dict[str, float] = {}

    def __len__(self) -> int:
        return self.depth

    def oldest_age(self, now: float) -> Optional[float]:
        return self.oldest

    def class_depths(self) -> Dict[str, int]:
        return dict(self._class_depths)

    def class_oldest(self, now: float) -> Dict[str, float]:
        return dict(self._class_oldest)


class _MirrorClient:
    __slots__ = ("scheduler", "engine", "dispatch_in_flight")

    def __init__(self) -> None:
        self.scheduler = _MirrorScheduler()
        self.engine = _MirrorEngine()
        self.dispatch_in_flight = False


class _ProcessReplica:
    """One process-backed replica seat: actor handle + routing mirror +
    carried watchdog beat state. Duck-compatible with the in-process
    ``_Replica`` everywhere the Router touches it (``.id``,
    ``.admitting``, ``.client.scheduler``, ``.client.engine``)."""

    __slots__ = ("id", "actor", "info", "client", "draining", "crashed",
                 "crash_msg", "crash_implicated", "last_beat", "last_step",
                 "beats")

    def __init__(self, replica_id: int, actor: Any, info: Dict[str, Any]):
        self.id = replica_id
        self.actor = actor
        self.info = dict(info)
        self.client = _MirrorClient()
        self.draining = False
        self.crashed = False
        self.crash_msg: Optional[str] = None
        #: request ids the dying worker reported as in its engine when
        #: the dispatch loop crashed (MSG_CRASH 4th field) — None when
        #: the crash predates the field or the process died messageless
        #: (kill -9), in which case implication falls back to ALL
        #: displaced (conservative; probation exonerates innocents)
        self.crash_implicated: Optional[List[int]] = None
        self.last_beat: Optional[float] = None
        self.last_step = -1
        self.beats = 0

    @property
    def admitting(self) -> bool:
        return not self.draining and not self.crashed

    @property
    def busy(self) -> bool:
        eng = self.client.engine
        return bool(self.client.scheduler.depth or eng.active_count
                    or eng.chunk_pending
                    or self.client.dispatch_in_flight)

    def apply_stats(self, stats: Dict[str, Any]) -> None:
        sched = self.client.scheduler
        eng = self.client.engine
        sched.depth = int(stats.get("queue_depth", 0))
        sched.oldest = stats.get("oldest_age")
        sched._class_depths = dict(stats.get("class_depths") or {})
        sched._class_oldest = dict(stats.get("class_oldest") or {})
        eng.active_count = int(stats.get("active", 0))
        eng.chunk_pending = int(stats.get("chunk_pending", 0))
        eng.free_pages = stats.get("free_pages")
        eng.pool.num_pages = int(stats.get("num_pages") or 1)
        self.client.dispatch_in_flight = bool(
            stats.get("dispatch_in_flight", False))


class _Tracked:
    """Driver-side ledger entry: the admitted request object plus the
    cumulative tokens its replica last flushed — everything failover
    needs when the replica can no longer answer a snapshot RPC."""

    __slots__ = ("req", "replica", "tokens")

    def __init__(self, req: Request, replica: int):
        self.req = req
        self.replica = replica
        self.tokens: List[int] = []


class ProcessReplicaFleet(ReplicaFleet):
    """N :class:`~ray_lightning_tpu.serve.client.ServeClient` replicas,
    each in its own spawned worker process — the ``backend="process"``
    face of :class:`~ray_lightning_tpu.serve.fleet.ReplicaFleet` (the
    switch in ``ReplicaFleet.__new__`` lands here; ``isinstance(fleet,
    ReplicaFleet)`` holds). Same public surface: ``submit`` /
    ``serve_trace`` / ``run_until_idle`` / ``tick`` / ``shutdown`` plus
    the reliability counters the bench reads. See the module docstring
    for the transport/failover design and ``docs/serving.md`` for
    backend selection guidance.

    Extra knobs over the in-process fleet: ``worker_env`` (static env
    for every replica process, merged over the platform defaults),
    ``per_seat_env`` (callable mapping a spawn seat to device-pinning
    env — how a TPU host gives each replica its own chip slice),
    ``submit_timeout`` (seconds one admission RPC may take),
    ``scale_eval_interval`` (autoscaler evaluation cadence, wall
    seconds), ``orphan_grace_s`` (arm driver-death orphan reaping:
    workers that lose the driver self-terminate within this window, and
    every worker-side queue op is timeout-bounded by it — set it
    whenever a :class:`~ray_lightning_tpu.serve.journal.Journal` is
    armed for warm restart). ``clock=`` is rejected: the process
    backend is wall-clock by construction (trace times and deadlines
    are in seconds).
    """

    def __init__(self, model, params, *, backend: str = "process",
                 num_replicas: int = 2, num_standby: int = 0,
                 fleet_config: Optional[FleetConfig] = None,
                 router_config: Optional[RouterConfig] = None,
                 telemetry: Any = None,
                 clock: Optional[Callable[[], float]] = None,
                 worker_env: Optional[Dict[str, str]] = None,
                 per_seat_env: Optional[Callable[[int], Dict[str, str]]]
                 = None,
                 submit_timeout: float = 60.0,
                 scale_eval_interval: float = 0.05,
                 journal: Any = None,
                 orphan_grace_s: Optional[float] = None,
                 **engine_kwargs: Any):
        if num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {num_replicas}")
        if num_standby < 0:
            raise ValueError(
                f"num_standby must be >= 0, got {num_standby}")
        if clock is not None:
            raise ValueError(
                "backend='process' is wall-clock only (workers stamp "
                "time.time() against the fleet's shared epoch) — drop "
                "clock= or use backend='inproc' for tick-clock traces")
        self.backend = "process"
        self._model = model
        # ship a host-side copy: every worker process re-puts (and, for
        # quantized fleets, re-quantizes) the SAME raw values, so
        # failover replay across replicas stays bit-identical
        import jax
        self._params_host = jax.tree_util.tree_map(np.asarray, params)
        self._engine_kwargs = dict(engine_kwargs)
        self._cfg = fleet_config or FleetConfig()
        self._tel = telemetry
        self._worker_env = dict(worker_env or {})
        self._per_seat_env = per_seat_env
        self._submit_timeout = float(submit_timeout)
        self.scale_eval_interval = float(scale_eval_interval)
        self._epoch = time.time()
        self._ticks = 0
        self._next_id = 0
        self._next_replica_id = 0
        self._next_seat = 0
        self.completions: Dict[int, Completion] = {}
        #: request id -> _Tracked for everything admitted somewhere and
        #: not yet retired — the failover ledger AND the busy probe
        self._inflight: Dict[int, _Tracked] = {}
        # driver-death survival (docs/reliability.md): the WAL records
        # admissions/frontiers/retirements; its generation is the
        # split-brain fence — stamped into every spawned worker's
        # messages and beats, and checked in both queue drains, so a
        # warm-restarted driver (generation+1) refuses anything raced
        # over from the dead driver's workers. journal=None keeps the
        # repo-wide zero-cost contract.
        self._journal = journal
        self._generation = (journal.generation
                            if journal is not None else 0)
        self._orphan_grace_s = (float(orphan_grace_s)
                                if orphan_grace_s is not None else None)
        self.stale_dropped = 0

        from ray_lightning_tpu.launchers.process_backend import ProcessRay
        self._ray = ProcessRay(orphan_grace_s=self._orphan_grace_s)
        self._ray.init()
        self._out = self._ray.make_queue()
        self._hb = self._ray.make_queue()

        rcfg = router_config or RouterConfig()
        affinity = rcfg.affinity_tokens
        if affinity is None:
            affinity = (engine_kwargs.get("prefill_chunk") or 0
                        if engine_kwargs.get("prefix_cache") else 0)
        self.router = Router(rcfg, affinity_tokens=affinity,
                             telemetry=telemetry)

        self._replicas: List[_ProcessReplica] = []
        self._shutdown_done = False
        try:
            for _ in range(num_replicas):
                self._activate(self._spawn_actor())
            if num_standby:
                from ray_lightning_tpu.reliability.elastic import \
                    StandbyPool
                self.standby = StandbyPool(self._ray,
                                           num_standby=num_standby,
                                           warmup=None,
                                           telemetry=telemetry)
                self.standby.fill(self._spawn_actor)
            else:
                self.standby = None
        except BaseException:
            # a failed spawn mid-construction must not leak the ones
            # that already started (no fleet object = no shutdown())
            self._ray.shutdown()
            raise

        from ray_lightning_tpu.reliability.gang import GangConfig
        grace = self._cfg.startup_grace
        if grace is None:
            # the in-process default (grace = timeout) assumes dispatch
            # turns are driver-ticked; a fresh PROCESS legitimately goes
            # quiet through its first compile-heavy dispatch
            grace = max(self._cfg.heartbeat_timeout, 60.0)
        self._gang_cfg = GangConfig(
            heartbeat_timeout=self._cfg.heartbeat_timeout,
            startup_grace=grace, clock=self.now)
        self._monitor = None
        self._rebuild_monitor()

        self._pressure_ticks = 0
        self._idle_ticks = 0
        self._last_scale_eval = 0.0
        self._ttft_ewma: Optional[float] = None
        self._target_replicas = num_replicas

        self.failovers = 0
        self.readmitted = 0
        self.readmit_failed = 0
        self.scale_outs = 0
        self.scale_ins = 0
        self.failover_s_total = 0.0

        # failure containment (same inert-by-default contract as the
        # in-process fleet: nothing here changes a decision until
        # max_request_failovers / flap_window are set)
        self.poison_failed = 0
        self._parked: List[Request] = []
        self._probation: List[Request] = []
        self._probation_rep: Optional[int] = None
        self._probation_obj: Optional[Request] = None
        self._degraded = False
        self._seats: Optional[SeatTable] = None
        if self._cfg.flap_window is not None:
            from ray_lightning_tpu.reliability.retry import RetryPolicy
            policy = self._cfg.quarantine_backoff or RetryPolicy(
                max_attempts=8, base_delay=1.0, max_delay=60.0,
                multiplier=2.0, jitter=0.1)
            self._seats = SeatTable(self._cfg.flap_window,
                                    self._cfg.flap_threshold, policy)
            for rep in self._replicas:
                self._seats.occupy(rep.id, self.now(), grow=True)

    # ------------------------------------------------------------ clock
    @property
    def ops(self) -> int:
        """Pump rounds so far (NOT dispatch turns — those happen in the
        worker processes; per-replica dispatch counts ride the
        heartbeats into ``replica_steps``)."""
        return self._ticks

    def now(self) -> float:
        return time.time() - self._epoch

    # --------------------------------------------------------- replicas
    @property
    def replicas_live(self) -> int:
        return len(self._replicas)

    @property
    def replica_ids(self) -> List[int]:
        return [rep.id for rep in self._replicas]

    @property
    def replica_steps(self) -> Dict[int, int]:
        """Per-replica dispatch-turn counts from the latest beats — the
        bench's per-replica utilization source."""
        return {rep.id: rep.last_step for rep in self._replicas}

    @property
    def process_backend(self):
        """The owning :class:`ProcessRay` module (tests assert
        ``live_actor_count() == 0`` after :meth:`shutdown`)."""
        return self._ray

    def _spawn_actor(self) -> Any:
        from ray_lightning_tpu.launchers.serve_worker import (
            ServeReplicaWorker, default_worker_env)
        seat = self._next_seat
        self._next_seat += 1
        env = default_worker_env(seat)
        env.update(self._worker_env)
        if self._per_seat_env is not None:
            env.update(self._per_seat_env(seat))
        if self._orphan_grace_s is not None:
            # arms the worker's ppid watchdog (process_backend): a
            # SIGKILLed driver's workers self-reap within the grace
            # window instead of decoding into the void forever
            from ray_lightning_tpu.launchers.process_backend import \
                ORPHAN_GRACE_ENV
            env[ORPHAN_GRACE_ENV] = repr(self._orphan_grace_s)
        hb_interval = min(0.25, max(0.005,
                                    self._cfg.heartbeat_timeout / 8.0))
        # construct crosses a fresh interpreter (jax import + engine
        # build); the backend's 60 s default is tight on a loaded host
        return self._ray.remote(ServeReplicaWorker).options(
            worker_env=env, construct_timeout=300.0).remote(
            self._model, self._params_host, self._engine_kwargs,
            self._out, self._hb, self._epoch,
            heartbeat_interval=hb_interval,
            # ship the driver's armed fault plan (if any) so worker-side
            # engines fire the same sites — chaos drills (and the
            # poison leg of the bench) hold identically on this backend
            fault_plan=faults.get_armed(),
            # real worker-side spans (MSG_SPAN) only when the driver is
            # armed: a disarmed fleet's workers keep the no-op span
            forward_spans=self._tel is not None,
            # the split-brain fence stamp: every message/beat this
            # worker puts carries the spawning driver's generation
            generation=self._generation,
            orphan_grace_s=self._orphan_grace_s)

    def _activate(self, handle: Any) -> _ProcessReplica:
        rid = self._next_replica_id
        self._next_replica_id += 1
        info = self._ray.get(handle.set_replica.remote(rid), timeout=120)
        rep = _ProcessReplica(rid, handle, info)
        self._replicas.append(rep)
        return rep

    def _rebuild_monitor(self) -> None:
        """Same carried-beat contract as the in-process fleet: a
        rebuild must not reset a wedged replica's silence clock."""
        from ray_lightning_tpu.reliability.gang import GangMonitor
        self._monitor = GangMonitor(len(self._replicas), self._gang_cfg)
        self._monitor.start()
        for idx, rep in enumerate(self._replicas):
            if rep.last_beat is not None:
                self._monitor.seed(idx, last_beat=rep.last_beat,
                                   last_step=rep.last_step,
                                   beats=rep.beats)

    # ------------------------------------------------------- submission
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0, top_k: Optional[int] = None,
               eos_id: Optional[int] = None, seed: Optional[int] = None,
               deadline: Optional[float] = None,
               tenant: Optional[str] = None,
               adapter: Optional[str] = None) -> int:
        """Route + enqueue one request; same contract as the in-process
        fleet (``ValueError`` for never-fits, :class:`FleetSaturated`
        when every replica refuses). ``adapter=`` rides the request
        across the transport — every worker's engine was built with the
        fleet's ``adapters=`` kwargs, so binding happens worker-side."""
        req = Request(id=self._next_id, prompt=prompt,
                      max_new_tokens=max_new_tokens,
                      temperature=temperature, top_k=top_k, eos_id=eos_id,
                      seed=seed, deadline=deadline,
                      tenant=tenant or DEFAULT_TENANT,
                      adapter=adapter)
        self._admit(req)
        self._next_id += 1
        return req.id

    # ---------------------------------------------------- hot adapters
    def load_adapter(self, name: str, adapter) -> Optional[str]:
        """Hot adapter churn needs a broadcast RPC the process
        transport does not carry yet — declare the resident set up
        front via ``adapters=`` (every worker engine builds with it),
        or use the in-process backend for hot load/unload."""
        raise NotImplementedError(
            "hot adapter load/unload is not supported on the process "
            "backend — pass the resident set via adapters= at fleet "
            "build, or use backend='inproc'")

    def unload_adapter(self, name: str) -> None:
        raise NotImplementedError(
            "hot adapter load/unload is not supported on the process "
            "backend — pass the resident set via adapters= at fleet "
            "build, or use backend='inproc'")

    def _admit(self, req: Request) -> _ProcessReplica:
        """Offer ``req`` down the router's preference order via submit
        RPCs. A refusal verdict sheds to the next candidate; a dead
        actor mid-RPC triggers its failover and the offer continues
        down the survivors."""
        if req.arrival_time is None:
            # stamped driver-side so the ledger copy used for failover
            # replay carries it; the worker's submit_request keeps an
            # existing stamp (the router-seat contract)
            req.arrival_time = self.now()
        ranked = self.router.order(self._replicas, req)
        if self._probation_rep is not None:
            # the probation replica is reserved for its solo suspect —
            # regular traffic routes around it until the run clears
            ranked = [r for r in ranked if r.id != self._probation_rep]
        affine_target = self.router.affine_target(req)
        for rep in ranked:
            if rep not in self._replicas:
                continue  # failed over by an earlier candidate's RPC
            load = self.router.load(rep)
            try:
                verdict = self._ray.get(
                    rep.actor.submit.remote(req),
                    timeout=self._submit_timeout)
            except ValueError:
                # engine.validate: the request can never fit any
                # replica's compiled shapes (all engines are identical)
                raise
            except Exception as exc:  # noqa: BLE001 — an actor dying mid-RPC enters failover
                log_suppressed(
                    "fleet.submit", exc,
                    f"replica {rep.id} unreachable during admission; "
                    "failing it over and continuing down the order")
                for comp in self._fail_replica(rep):
                    self.completions[comp.request_id] = comp
                    if self._journal is not None:
                        self._journal.retire(comp)
                continue
            if not verdict["ok"]:
                continue  # QueueFull/ClassQueueFull: shed to the next
            rep.apply_stats(verdict["stats"])
            self.router.note_admission(
                rep, req, load=load,
                affine=(affine_target is not None
                        and rep.id == affine_target))
            self._inflight[req.id] = _Tracked(req, rep.id)
            if self._journal is not None:
                # journaled AFTER the seat is won (a fleet-wide refusal
                # never journals — rejected requests are not admissions)
                # and with replay_tokens as-fed: a failover re-admission
                # re-journals with its binding, resetting the reader's
                # frontier to the replayed prefix
                self._journal.admit(req)
            return rep
        now = self.now()
        total = sum(r.client.scheduler.depth for r in self._replicas)
        oldest = [r.client.scheduler.oldest for r in self._replicas]
        oldest = [a for a in oldest if a is not None]
        class_depths: Dict[str, int] = {}
        class_oldest: Dict[str, float] = {}
        for r in self._replicas:
            for name, depth in r.client.scheduler.class_depths().items():
                class_depths[name] = class_depths.get(name, 0) + depth
            for name, age in r.client.scheduler.class_oldest(now).items():
                class_oldest[name] = max(class_oldest.get(name, age), age)
        if self._degraded and self._seats is not None:
            raise FleetDegraded(
                "fleet degraded (quarantined seats below min_replicas); "
                "every survivor's admission control refused the request",
                quarantined=self._seats.gated(now),
                live=len(self._replicas),
                queue_depth=total,
                oldest_age=max(oldest) if oldest else None,
                replicas=len(ranked),
                class_depths=class_depths or None,
                class_oldest=class_oldest or None)
        raise FleetSaturated(
            "every replica's admission control refused the request",
            queue_depth=total, oldest_age=max(oldest) if oldest else None,
            replicas=len(ranked),
            class_depths=class_depths or None,
            class_oldest=class_oldest or None)

    # ------------------------------------------------------------- loop
    def tick(self) -> List[Completion]:
        """One pump round: drain worker messages + heartbeats, apply
        liveness and silence verdicts, restore capacity toward the
        target, run the (throttled) autoscaler. Non-blocking — workers
        dispatch continuously regardless; this only moves results and
        supervision forward. Returns completions recorded this round
        (failover casualties included)."""
        # the driver tick boundary — the serve.driver chaos site (a
        # raise here IS the driver death the warm-restart tests replay)
        faults.fire(SITE_SERVE_DRIVER)
        done: List[Completion] = []
        self._pump_parked(done)
        self._drain_messages(done)
        self._drain_beats()
        for rep in list(self._replicas):
            if rep.crashed or not _alive(rep.actor):
                done.extend(self._fail_replica(rep))
        idx_map = dict(enumerate(self._replicas))
        for i in self._monitor.silent_ranks():
            rep = idx_map.get(i)
            if rep is not None and rep in self._replicas:
                done.extend(self._fail_replica(rep))
        if len(self._replicas) < self._target_replicas and (
                self._seats is None
                or self._seats.allow_build(self.now())):
            # quarantined seats gate this catch-up: a crash-looping
            # seat rebuilds on its backoff schedule, not every pump
            rep, source = self._adopt_standby_or_build(cold_ok=True)
            self._rebuild_monitor()
            if self._tel is not None and rep is not None:
                self._tel.event(EVENT_REPLICA_PROMOTED,
                                replica=rep.id, source=source,
                                replicas_live=len(self._replicas))
        if self._cfg.autoscale:
            self._autoscale()
        self._pump_probation(done)
        self._ticks += 1
        tel = self._tel
        if self._seats is not None:
            gated = self._seats.gated(self.now())
            deg = (gated > 0
                   and len(self._replicas) < self._cfg.min_replicas)
            if deg != self._degraded:
                self._degraded = deg
                if tel is not None:
                    tel.event(EVENT_DEGRADED if deg else EVENT_RESTORED,
                              quarantined=gated,
                              replicas_live=len(self._replicas))
            if tel is not None:
                tel.metrics.gauge(
                    GAUGE_QUARANTINED,
                    help="empty replica seats inside their quarantine "
                         "backoff window").set(gated)
        if tel is not None:
            tel.metrics.gauge(
                GAUGE_REPLICAS_LIVE,
                help="serving replicas currently live (draining "
                     "included)").set(len(self._replicas))
            tel.metrics.gauge(
                GAUGE_QUEUE_DEPTH,
                help="requests waiting across every replica's queue"
            ).set(sum(r.client.scheduler.depth for r in self._replicas))
        for comp in done:
            self.completions[comp.request_id] = comp
            if self._journal is not None:
                self._journal.retire(comp)
        return done

    # -------------------------------------------------- message pumping
    def _drain_messages(self, done: List[Completion]) -> None:
        from ray_lightning_tpu.launchers.serve_worker import (
            MSG_COMPLETION, MSG_CRASH, MSG_EVENT, MSG_METRIC,
            MSG_PROGRESS, MSG_SPAN, MSG_STATUS)
        by_id = {rep.id: rep for rep in self._replicas}
        while True:
            try:
                item = self._out.get(block=False)
            except (_queue.Empty, EOFError, OSError):
                return
            if not (isinstance(item, tuple) and len(item) == 4):
                continue
            _kind, rid, batch, gen = item
            if gen != self._generation:
                # split-brain fence: a batch raced over from a dead
                # driver's worker (its generation predates our restart)
                self._note_stale(gen)
                continue
            rep = by_id.get(rid)
            for msg in batch:
                mk = msg[0]
                if mk == MSG_COMPLETION:
                    comp = msg[2]
                    self._inflight.pop(comp.request_id, None)
                    done.append(comp)
                    self._note_ttft(rid, comp)
                elif mk == MSG_PROGRESS:
                    for req_id, prog in msg[2].items():
                        t = self._inflight.get(req_id)
                        if t is not None and t.replica == rid:
                            t.tokens = list(prog["tokens"])
                            ft = prog.get("first_token_time")
                            if ft is not None:
                                # ride the ledger's request object: a
                                # re-admission must not restamp TTFT
                                t.req.first_token_time = ft
                            if self._journal is not None:
                                # the flushed stream IS this backend's
                                # synced frontier: exactly what failover
                                # (and warm restart) would replay
                                self._journal.note_frontier(
                                    req_id, t.tokens,
                                    t.req.first_token_time)
                elif mk == MSG_STATUS:
                    if rep is not None:
                        rep.apply_stats(msg[2])
                elif mk == MSG_EVENT:
                    if self._tel is not None:
                        self._tel.event(msg[2], **msg[3])
                elif mk == MSG_METRIC:
                    if self._tel is not None:
                        self._apply_metric(msg)
                elif mk == MSG_SPAN:
                    if self._tel is not None:
                        # a worker's closed span (fleet-timeline µs):
                        # import seat-tagged so the stitched Chrome
                        # trace puts each replica on its own pid track.
                        # A dead replica's last flushed spans land here
                        # too — _fail_replica drains before teardown.
                        _mk, srid, name, ts, dur, depth, args = msg
                        self._tel.spans.record_closed(
                            name, ts, dur, depth,
                            dict(args, seat=srid))
                elif mk == MSG_CRASH:
                    if rep is not None:
                        rep.crashed = True
                        rep.crash_msg = msg[2]
                        rep.crash_implicated = (
                            list(msg[3]) if len(msg) > 3 else None)

    def _apply_metric(self, msg: Tuple) -> None:
        _mk, _rid, kind, name, help_, op, value = msg
        m = self._tel.metrics
        handle = getattr(m, kind)(name, help=help_)
        getattr(handle, op)(value)

    def _drain_beats(self) -> None:
        """The fleet clock riding the heartbeat channel: fold worker
        beats into the gang monitor (driver-stamped, like a training
        rank's) and the replicas' carried beat state."""
        idx_of = {rep.id: i for i, rep in enumerate(self._replicas)}
        while True:
            try:
                item = self._hb.get(block=False)
            except (_queue.Empty, EOFError, OSError):
                return
            if not (isinstance(item, tuple) and len(item) == 4):
                continue
            rid, step, _worker_t, gen = item
            if gen != self._generation:
                self._note_stale(gen)
                continue
            i = idx_of.get(rid)
            if i is None:
                continue  # beat from a replica failed over mid-flight
            self._monitor.observe(i, int(step))
            rep = self._replicas[i]
            rep.last_beat = self.now()
            rep.last_step = max(rep.last_step, int(step))
            rep.beats += 1

    def _note_stale(self, gen: Any) -> None:
        """One fenced-off message: wrong-generation traffic from a dead
        driver's worker (or a malformed item). Counted, evented, and
        dropped — never folded into the ledger or the monitor."""
        self.stale_dropped += 1
        if self._tel is not None:
            self._tel.event(EVENT_JOURNAL_STALE, generation=gen,
                            expected=self._generation)
            self._tel.metrics.counter(
                COUNTER_JOURNAL_STALE,
                help="wrong-generation worker messages refused by the "
                     "driver's split-brain fence").inc()

    def _note_ttft(self, replica_id: int, comp: Completion) -> None:
        ttft = comp.time_to_first_token
        if ttft is not None:
            self.router.record_ttft(replica_id, ttft)
            a = self.router.config.ttft_alpha
            self._ttft_ewma = (ttft if self._ttft_ewma is None
                               else (1.0 - a) * self._ttft_ewma
                               + a * ttft)

    # --------------------------------------------------------- failover
    def _fail_replica(self, rep: _ProcessReplica) -> List[Completion]:
        """Tear down a dead/crashed/hung replica and re-admit its
        ledger entries to survivors via replay. The manager-hosted
        out-queue survives the death, so one final drain first harvests
        everything the worker managed to flush — completions recorded
        there never replay, and the freshest token progress tightens
        what does."""
        if rep not in self._replicas:
            return []
        t0 = time.perf_counter()
        self.failovers += 1
        done: List[Completion] = []
        self._drain_messages(done)
        self._drain_beats()
        verdict = _classify_failure(rep.actor, rep.crashed)
        tel = self._tel
        idx = self._replicas.index(rep)
        post = self._monitor.postmortems(
            silent=(idx,) if verdict == "hung" else (),
            dead=(idx,) if verdict != "hung" else ()).get(idx)
        displaced = sorted(
            (t for t in self._inflight.values() if t.replica == rep.id),
            key=lambda t: t.req.id)
        in_flight = sum(1 for t in displaced
                        if t.tokens or t.req.first_token_time is not None)
        # implication across the process boundary: an "error" verdict
        # ships the crashing engine's exact in-flight set (MSG_CRASH),
        # so only those ids are implicated. A messageless death
        # (kill -9 → "dead", wedge → "hung") names nobody — every
        # displaced request is implicated conservatively; probation
        # exonerates innocents (the implication-vs-proof caveat,
        # docs/reliability.md#failure-containment).
        if verdict == "error" and rep.crash_implicated is not None:
            guilty = set(rep.crash_implicated)
            for t in displaced:
                if t.req.id in guilty:
                    t.req.crash_implications += 1
        else:
            for t in displaced:
                t.req.crash_implications += 1
        if self._probation_rep == rep.id:
            # the probation replica died — almost certainly the suspect
            # crashed it. Release the reservation; the suspect rides
            # the normal re-admission path below with its bumped count
            # (back to probation, or out at the budget).
            self._probation_rep = None
            self._probation_obj = None
        if tel is not None:
            if verdict == "dead":
                tel.event(EVENT_REPLICA_DEAD, replica=rep.id,
                          last_dispatch=(post.last_step if post else -1))
            elif verdict == "error":
                tel.event(EVENT_REPLICA_ERROR, replica=rep.id,
                          detail=rep.crash_msg)
            tel.event(EVENT_FAILOVER, replica=rep.id,
                      dead=(verdict != "hung"),
                      in_flight=in_flight,
                      queued=len(displaced) - in_flight,
                      chunking=rep.client.engine.chunk_pending,
                      last_dispatch=(post.last_step if post else -1),
                      beat_age=(round(post.last_beat_age_s, 3)
                                if post else None))
            tel.metrics.counter(
                COUNTER_FAILOVERS,
                help="replicas drained after death or hang").inc()
        try:
            self._ray.kill(rep.actor)
        except Exception as exc:  # noqa: BLE001 — teardown is best-effort
            log_suppressed("fleet.teardown", exc,
                           f"replica {rep.id} kill failed")
        self._replicas.remove(rep)
        self.router.forget(rep.id)
        if self._seats is not None:
            next_build = self._seats.record_death(rep.id, self.now())
            if next_build is not None and tel is not None:
                tel.event(EVENT_QUARANTINE, replica=rep.id,
                          next_build=round(next_build, 6))
        for t in displaced:
            self._inflight.pop(t.req.id, None)
        promoted_early = False
        if not self._replicas:
            self._promote()
            promoted_early = True
        for t in displaced:
            done.extend(self._readmit(t.req, t.tokens or None))
        if not promoted_early:
            self._promote()
        self._rebuild_monitor()
        self.failover_s_total += time.perf_counter() - t0
        return done

    def _readmit(self, req: Request,
                 toks: Optional[List[int]]) -> List[Completion]:
        """PR 3 replay re-admission across the process boundary: the
        ledger's request object (original arrival/deadline/first-token
        stamps, tenant class) re-feeds with ``replay_tokens`` set to
        the last flushed stream — the survivor's prefill resumes the
        sampling-key stream at the same ``fold_in`` step.

        Containment semantics match the in-process fleet exactly:
        budget-spent requests retire ``failed``, twice-implicated ones
        queue for solo probation, transiently-refused ones park for
        bounded retry instead of insta-failing."""
        tel = self._tel
        if toks is not None:
            req.replay_tokens = list(toks)
            if tel is not None:
                tel.event("recovery.replay", id=req.id,
                          replayed_tokens=len(toks))
        budget = self._cfg.max_request_failovers
        if budget is not None and req.crash_implications >= budget:
            return self._retire_poison(req)
        if (budget is not None
                and req.crash_implications >= self._cfg.probation_after):
            self._probation.append(req)
            if tel is not None:
                tel.event(EVENT_PROBATION, id=req.id, phase="queued",
                          implications=req.crash_implications)
            return []
        fed = req.prompt_len + len(req.replay_tokens or ())
        survivors = self._replicas
        if survivors:
            if fed <= survivors[0].info["max_replay_len"]:
                try:
                    self._admit(req)
                except QueueFull as exc:
                    # FleetSaturated (the RPC admission path's refusal)
                    # subclasses QueueFull — transiently full, not
                    # unseatable: park for bounded re-admission
                    log_suppressed("fleet.readmit", exc,
                                   f"request {req.id} refused by every "
                                   "survivor; parked for retry")
                    self._park(req)
                    return []
                except ValueError as exc:
                    log_suppressed("fleet.readmit", exc,
                                   f"request {req.id} unseatable after "
                                   "failover; retiring as failed")
                else:
                    self._count_readmitted()
                    return []
        elif self._seats is not None:
            # degraded: no survivor YET, but quarantine backoff will
            # rebuild one — park rather than insta-fail (the fit check
            # happens against the rebuilt replica at pump time)
            self._park(req)
            return []
        return [self._fail_request(req)]

    def _pump_parked(self, done: List[Completion]) -> None:
        """Process-backend parked-retry pump: same contract as the
        in-process fleet (deadline expiries retire ``timeout``, fits
        re-admit through the router, still-full stays parked) with the
        fit check against the replica info dict and refusals arriving
        as :class:`FleetSaturated` from the RPC admission path."""
        if not self._parked:
            return
        still: List[Request] = []
        now = self.now()
        for req in self._parked:
            if req.deadline is not None and now >= req.deadline:
                comp = Completion(
                    request_id=req.id, prompt=list(req.prompt),
                    tokens=list(req.replay_tokens or []),
                    finish_reason=FINISH_TIMEOUT,
                    arrival_time=req.arrival_time,
                    first_token_time=req.first_token_time,
                    finish_time=now,
                    prefix_hit_tokens=req.prefix_hit_tokens,
                    tenant=req.tenant, adapter=req.adapter)
                self.completions[comp.request_id] = comp
                done.append(comp)
                continue
            survivors = self._replicas
            if not survivors:
                still.append(req)
                continue
            fed = req.prompt_len + len(req.replay_tokens or ())
            if fed > survivors[0].info["max_replay_len"]:
                done.append(self._fail_request(req))
                continue
            try:
                self._admit(req)
            except QueueFull:
                still.append(req)
            except ValueError as exc:
                log_suppressed("fleet.readmit", exc,
                               f"parked request {req.id} permanently "
                               "unseatable; retiring as failed")
                done.append(self._fail_request(req))
            else:
                self._count_readmitted()
        self._parked = still

    def _pump_probation(self, done: List[Completion]) -> None:
        """Process-backend probation lane: identical policy to the
        in-process fleet; the solo seat rides a submit RPC plus a
        ledger entry (the suspect must stay failover-tracked — its
        probation replica dying IS the strongest poison signal), and
        the reserved replica's idleness reads the mirror stats plus
        the driver ledger."""
        obj = self._probation_obj
        if obj is not None:
            comp = self.completions.get(obj.id)
            if comp is None:
                return  # suspect still running solo
            obj.crash_implications = 0
            rep_id, self._probation_rep = self._probation_rep, None
            self._probation_obj = None
            if self._tel is not None:
                self._tel.event(EVENT_PROBATION_CLEARED, id=obj.id,
                                replica=rep_id,
                                finish_reason=comp.finish_reason)
        if not self._probation:
            return
        if self._probation_rep is None:
            admitting = sorted(
                (r for r in self._replicas if r.admitting),
                key=lambda r: r.id)
            if not admitting:
                return
            if len(admitting) < 2 and self._target_replicas > 1:
                return  # a second replica is coming; keep traffic moving
            self._probation_rep = admitting[0].id
        rep = next((r for r in self._replicas
                    if r.id == self._probation_rep), None)
        if rep is None or not rep.admitting:
            self._probation_rep = None
            return
        if rep.busy or any(t.replica == rep.id
                           for t in self._inflight.values()):
            return  # let the reserved replica drain its regular work
        req = self._probation[0]
        fed = req.prompt_len + len(req.replay_tokens or ())
        if fed > rep.info["max_replay_len"]:
            self._probation.pop(0)
            done.append(self._fail_request(req))
            return
        try:
            verdict = self._ray.get(rep.actor.submit.remote(req),
                                    timeout=self._submit_timeout)
        except ValueError:
            self._probation.pop(0)
            done.append(self._fail_request(req))
            return
        except Exception as exc:  # noqa: BLE001 — a dying probation seat fails over on the next pump
            log_suppressed("fleet.probation", exc,
                           f"probation replica {rep.id} unreachable; "
                           "retrying the suspect next pump")
            return
        if not verdict["ok"]:
            return  # idle replica refused (quota edge); retry next pump
        rep.apply_stats(verdict["stats"])
        self._probation.pop(0)
        self._inflight[req.id] = _Tracked(req, rep.id)
        if self._journal is not None:
            # the probation seat is an admission too — a driver death
            # mid-probation must still replay the suspect
            self._journal.admit(req)
        self._probation_obj = req
        if self._tel is not None:
            self._tel.event(EVENT_PROBATION, id=req.id, phase="seated",
                            replica=rep.id,
                            implications=req.crash_implications)

    def _adopt_standby_or_build(self, *, cold_ok: bool,
                                grow: bool = False) \
            -> Tuple[Optional[_ProcessReplica], Optional[str]]:
        handle = self.standby.take() if self.standby is not None else None
        source = "standby" if handle is not None else None
        if handle is None:
            if not cold_ok:
                return None, None
            handle = self._spawn_actor()
            source = "cold"
        try:
            rep = self._activate(handle)
        except Exception as exc:  # noqa: BLE001 — a corpse standby must not wedge the promotion path
            log_suppressed("fleet.promote", exc,
                           "standby activation failed; cold-building")
            try:
                self._ray.kill(handle)
            except Exception as kill_exc:  # noqa: BLE001 — best-effort
                log_suppressed("fleet.teardown", kill_exc,
                               "could not kill failed standby")
            rep = self._activate(self._spawn_actor())
            source = "cold"
        if self._seats is not None:
            self._seats.occupy(rep.id, self.now(), grow=grow)
        if self.standby is not None:
            self.standby.refill_async(self._spawn_actor)
        return rep, source

    def _promote(self) -> None:
        if self._seats is not None and not self._seats.allow_build(
                self.now()):
            # every empty seat is quarantined: the failover path must
            # not hot-rebuild into a crash-looping seat — degraded
            # mode (shed + survivors) covers the gap until the
            # backoff elapses and the catch-up path rebuilds
            return
        rep, source = self._adopt_standby_or_build(
            cold_ok=len(self._replicas) < self._cfg.min_replicas)
        if rep is None:
            return
        if self._tel is not None:
            self._tel.event(EVENT_REPLICA_PROMOTED, replica=rep.id,
                            source=source,
                            replicas_live=len(self._replicas))

    # ------------------------------------------------------- autoscaler
    def _autoscale(self) -> None:
        """Same hysteresis policy as the in-process fleet, counted in
        **evaluations** throttled to one per ``scale_eval_interval``
        wall seconds (the pump spins far faster than a dispatch
        round would)."""
        now = self.now()
        if now - self._last_scale_eval < self.scale_eval_interval:
            self._drain_drained()
            return
        self._last_scale_eval = now
        cfg = self._cfg
        admitting = [r for r in self._replicas if r.admitting]
        total_q = sum(r.client.scheduler.depth for r in self._replicas)
        pressured = (
            total_q > cfg.scale_out_queue_depth * max(1, len(admitting))
            or (cfg.ttft_slo is not None and self._ttft_ewma is not None
                and self._ttft_ewma > cfg.ttft_slo))
        if pressured:
            self._pressure_ticks += 1
            self._idle_ticks = 0
        elif total_q == 0:
            self._idle_ticks += 1
            self._pressure_ticks = 0
        else:
            self._pressure_ticks = 0
            self._idle_ticks = 0
        if (self._pressure_ticks >= cfg.hysteresis
                and len(self._replicas) < cfg.max_replicas):
            self._scale_out()
            self._pressure_ticks = 0
        elif (self._idle_ticks >= cfg.hysteresis
                and len(admitting) > cfg.min_replicas):
            self._drain_one(admitting)
            self._idle_ticks = 0
        self._drain_drained()

    def _drain_drained(self) -> None:
        for rep in [r for r in self._replicas if r.draining]:
            if not rep.busy and not any(
                    t.replica == rep.id for t in self._inflight.values()):
                self._retire_replica(rep)

    def _scale_out(self) -> None:
        rep, source = self._adopt_standby_or_build(cold_ok=True,
                                                   grow=True)
        self.scale_outs += 1
        self._target_replicas = len(self._replicas)
        self._rebuild_monitor()
        if self._tel is not None:
            self._tel.event(EVENT_SCALE_OUT, replica=rep.id,
                            source=source,
                            replicas_live=len(self._replicas))

    def _drain_one(self, admitting: List[_ProcessReplica]) -> None:
        candidates = [r for r in admitting
                      if r.id != self._probation_rep] or admitting
        rep = max(candidates, key=lambda r: r.id)
        rep.draining = True
        if self._tel is not None:
            self._tel.event(EVENT_REPLICA_DRAINING, replica=rep.id,
                            in_flight=rep.client.engine.active_count,
                            queued=rep.client.scheduler.depth)

    def _retire_replica(self, rep: _ProcessReplica) -> None:
        """Scale-in completion: the drained worker stops gracefully
        (its engine releases device memory) before the actor dies."""
        try:
            self._ray.get(rep.actor.stop.remote(), timeout=30)
        except Exception as exc:  # noqa: BLE001 — teardown is best-effort
            log_suppressed("fleet.teardown", exc,
                           f"replica {rep.id} graceful stop failed")
        try:
            self._ray.kill(rep.actor)
        except Exception as exc:  # noqa: BLE001 — teardown is best-effort
            log_suppressed("fleet.teardown", exc,
                           f"replica {rep.id} kill failed")
        self._replicas.remove(rep)
        self.router.forget(rep.id)
        if self._seats is not None:
            self._seats.vacate(rep.id)  # deliberate drain, not a death
        self.scale_ins += 1
        self._target_replicas = len(self._replicas)
        self._rebuild_monitor()
        if self._tel is not None:
            self._tel.event(EVENT_SCALE_IN, replica=rep.id,
                            replicas_live=len(self._replicas))

    # ---------------------------------------------------------- driving
    def _busy(self) -> bool:
        return (bool(self._inflight) or bool(self._parked)
                or bool(self._probation)
                or self._probation_obj is not None)

    def run_until_idle(self, max_ticks: int = 100_000) \
            -> Dict[int, Completion]:
        """Pump until every admitted request has retired somewhere."""
        ticks = 0
        while self._busy():
            got = self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"fleet loop did not drain in {max_ticks} pump "
                    f"rounds ({len(self._inflight)} requests still "
                    "tracked)")
            if not got and self._busy():
                time.sleep(0.002)  # tl-lint: allow-sleep — pump idle quantum; dispatch runs in the worker processes regardless
        return dict(self.completions)

    def serve_trace(self, trace: Sequence[Tuple[float, dict]],
                    max_ticks: int = 100_000) -> Dict[int, Completion]:
        """Replay a scripted arrival trace (times in WALL SECONDS from
        fleet construction — the process backend has no tick clock).
        Same shed contract as the in-process fleet: entries the whole
        fleet refuses retire as ``finish_reason="rejected"``."""
        tel = self._tel
        pending = sorted(trace, key=lambda item: item[0])
        idx = 0
        ticks = 0
        while idx < len(pending) or self._busy():
            now = self.now()
            while idx < len(pending) and pending[idx][0] <= now:
                kwargs = pending[idx][1]
                try:
                    self.submit(**kwargs)
                except (QueueFull, ValueError) as exc:
                    rid = self._next_id
                    self._next_id += 1
                    self.completions[rid] = Completion(
                        request_id=rid,
                        prompt=[int(t) for t in kwargs.get("prompt", [])],
                        tokens=[], finish_reason=FINISH_REJECTED,
                        arrival_time=now, finish_time=now,
                        tenant=kwargs.get("tenant") or DEFAULT_TENANT)
                    if tel is not None:
                        tel.event(EVENT_SHED, id=rid,
                                  why=type(exc).__name__,
                                  context=str(exc))
                        tel.metrics.counter(
                            COUNTER_SHED,
                            help="requests shed fleet-wide at admission"
                        ).inc()
                idx += 1
            got = self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"fleet trace did not drain in {max_ticks} pump "
                    "rounds")
            if idx < len(pending) and not self._busy():
                # idle gap before the next arrival: yield the driver
                # core to the workers. No watchdog restamp needed —
                # process replicas beat through idle time on their own
                time.sleep(  # tl-lint: allow-sleep — wall-clock idle yield between trace arrivals
                    min(1e-3, max(0.0, pending[idx][0] - self.now())))
            elif not got and self._busy():
                time.sleep(0.002)  # tl-lint: allow-sleep — pump idle quantum; dispatch runs in the worker processes regardless
        return dict(self.completions)

    # ---------------------------------------------------------- teardown
    def shutdown(self) -> None:
        """Graceful worker stops, then the whole process backend (every
        actor process + the queue manager). Idempotent."""
        if self._shutdown_done:
            return
        self._shutdown_done = True
        for rep in self._replicas:
            try:
                self._ray.get(rep.actor.stop.remote(), timeout=15)
            except Exception as exc:  # noqa: BLE001 — teardown is best-effort
                log_suppressed("fleet.teardown", exc,
                               f"replica {rep.id} graceful stop failed")
        self._replicas = []
        if self.standby is not None:
            self.standby.shutdown()
        self.router.shutdown()
        self._monitor = None
        self._inflight.clear()
        journal = self._journal
        if journal is not None:
            self._journal = None
            journal.shutdown()
        self._ray.shutdown()
        self._out = None
        self._hb = None


def _alive(actor: Any) -> bool:
    from ray_lightning_tpu.reliability.gang import actor_alive
    return actor_alive(actor)
