"""Replica-seat crash-loop quarantine for the serve fleets.

A fleet seat is the *position* a replica occupies, surviving the replica
itself: when a replica dies, its seat records the death and decides how
eagerly the fleet may rebuild into it. A seat whose sliding-window death
count reaches ``flap_threshold`` is **quarantined** — rebuilds into it
follow a :class:`~ray_lightning_tpu.reliability.RetryPolicy` exponential
backoff (deterministic jitter, salted by seat id so seats sharing one
policy de-correlate) instead of the hot build→die→build loop a
deterministic fault otherwise produces via the fleet's catch-up path.

The table is clock-agnostic: the in-process fleet feeds it tick counts,
the process fleet wall-clock seconds — ``flap_window`` and the policy's
delays are in whatever units the owning fleet's ``now()`` speaks.

Recovery is implicit and deterministic: deaths age out of the sliding
window, so a seat whose rebuilt replica survives longer than
``flap_window`` re-enters the next death at attempt 0 (healthy
fast-rebuild). There is no success callback to miss.

Built and consulted only when ``FleetConfig.flap_window`` is set — a
default fleet never constructs a table, keeping it decision-for-decision
identical to the pre-containment fleet.
"""
from __future__ import annotations

from typing import List, Optional

from ray_lightning_tpu.reliability.retry import RetryPolicy


class _Seat:
    """One replica position: its death history and rebuild gate."""

    __slots__ = ("id", "occupant", "deaths", "attempt", "next_build")

    def __init__(self, seat_id: int):
        self.id = seat_id
        self.occupant: Optional[int] = None  # replica id, None = empty
        self.deaths: List[float] = []        # death times inside the window
        self.attempt = 0                     # consecutive quarantine count
        self.next_build = float("-inf")      # earliest rebuild time


class SeatTable:
    """Sliding-window per-seat death counter + backoff-gated rebuilds.

    ``record_death`` returns the seat's ``next_build`` time when the
    death tripped (or extended) a quarantine, ``None`` for a healthy
    fast-rebuild — the fleet uses the distinction to emit its
    ``fleet.quarantine`` event with the exact scheduled rebuild time.
    """

    def __init__(self, flap_window: float, flap_threshold: int,
                 policy: RetryPolicy):
        if flap_window <= 0:
            raise ValueError(f"flap_window must be > 0, got {flap_window}")
        if flap_threshold < 1:
            raise ValueError(
                f"flap_threshold must be >= 1, got {flap_threshold}")
        self.flap_window = flap_window
        self.flap_threshold = flap_threshold
        self.policy = policy
        self._seats: List[_Seat] = []
        self._next_id = 0

    # ------------------------------------------------------------ seats
    def _seat_of(self, replica_id: int) -> Optional[_Seat]:
        for seat in self._seats:
            if seat.occupant == replica_id:
                return seat
        return None

    def occupy(self, replica_id: int, now: float,
               grow: bool = False) -> int:
        """Seat a (re)built replica; returns the seat id (the backoff
        jitter salt). Fills the lowest buildable empty seat; ``grow``
        (initial build / scale-out) appends a fresh seat when none is
        free — new capacity never waits behind a quarantined seat."""
        free = [s for s in self._seats
                if s.occupant is None and s.next_build <= now]
        if free:
            seat = min(free, key=lambda s: s.id)
        elif grow or all(s.occupant is not None for s in self._seats):
            seat = _Seat(self._next_id)
            self._next_id += 1
            self._seats.append(seat)
        else:
            raise RuntimeError(
                "no buildable seat (all empty seats quarantined) — "
                "callers must check allow_build() first")
        seat.occupant = replica_id
        return seat.id

    def vacate(self, replica_id: int) -> None:
        """Clean removal (scale-in drain): the seat retires with its
        replica — a deliberate shrink is not a death."""
        seat = self._seat_of(replica_id)
        if seat is not None:
            self._seats.remove(seat)

    # ----------------------------------------------------------- deaths
    def record_death(self, replica_id: int, now: float) -> Optional[float]:
        """Mark ``replica_id``'s seat dead at ``now``; gate its rebuild.

        Returns the quarantined seat's ``next_build`` time, or ``None``
        when the windowed death count stayed under ``flap_threshold``
        (seat rebuilds immediately, attempt counter reset)."""
        seat = self._seat_of(replica_id)
        if seat is None:
            # a replica the table never seated (pre-containment adopt
            # path, tests poking internals): give it a seat posthumously
            # so its death still counts
            sid = self.occupy(replica_id, now, grow=True)
            seat = next(s for s in self._seats if s.id == sid)
        seat.occupant = None
        cutoff = now - self.flap_window
        seat.deaths = [t for t in seat.deaths if t > cutoff]
        seat.deaths.append(now)
        if len(seat.deaths) >= self.flap_threshold:
            seat.attempt += 1
            seat.next_build = now + self.policy.delay(
                seat.attempt, salt=seat.id)
            return seat.next_build
        seat.attempt = 0
        seat.next_build = now
        return None

    # ------------------------------------------------------------ gates
    def allow_build(self, now: float) -> bool:
        """May the fleet's catch-up/promote path rebuild right now?
        True iff some empty seat's backoff has elapsed (or no seats
        are empty at all — nothing to gate)."""
        empty = [s for s in self._seats if s.occupant is None]
        if not empty:
            return True
        return any(s.next_build <= now for s in empty)

    def gated(self, now: float) -> int:
        """Empty seats still inside their backoff window — the
        ``serve_fleet_quarantined`` gauge."""
        return sum(1 for s in self._seats
                   if s.occupant is None and s.next_build > now)
