"""Replica-fleet serving: supervised engine replicas behind a router.

One :class:`~ray_lightning_tpu.serve.client.ServeClient` caps throughput
at one chip's worth of KV slots, and a process death takes every
in-flight request with it. This module is the serving analog of the
training gang stack (PRs 5–6), built entirely from primitives the repo
already owns:

- **Replicas** — ``num_replicas`` independent engine+scheduler loops
  (each a :class:`ServeClient`) sharing ONE fleet clock, so deadlines,
  arrival times and TTFT stamps mean the same thing on every replica —
  and keep meaning it when a request moves between replicas. All
  replicas share the engine ``seed``: a request's sampling-key stream is
  ``fold_in(fold_in(base(seed), request.seed), step)``, a pure function
  of *no replica state*, which is what makes failover replay-exact.
- **Router** — admission picks the live replica with the least load
  (queue depth + occupied slots + streaming chunks, then paged-arena
  occupancy, then TTFT EWMA; lowest id breaks ties, so traces are
  deterministic), with optional **prefix affinity**: requests sharing a
  prompt prefix prefer the replica that already published those KV
  pages (prefix-cache locality — a cache hit on the affine replica
  beats an idle slot on a cold one). A replica that refuses
  (:class:`~ray_lightning_tpu.serve.scheduler.QueueFull`) sheds *to the
  next candidate*; only when every replica refuses does the fleet raise
  a global :class:`FleetSaturated` carrying the aggregated occupancy
  context (PR 7's shed-load contract, fleet-wide).
- **Supervision** — the training-gang model transplanted: every replica
  dispatch turn beats a driver-clock ledger (reusing
  :class:`~ray_lightning_tpu.reliability.gang.GangMonitor`'s beat
  arithmetic), so a replica whose dispatch loop wedges
  (``serve.replica`` ``stall`` faults, or anything that stops it
  beating) is declared hung in bounded time, exactly like a silent
  rank. A dead or hung replica is **drained**: its
  ``snapshot_in_flight()`` re-admits to surviving replicas through the
  PR 3 replay path — prompt + already-emitted tokens re-feed through
  prefill, token streams continue at the same ``fold_in`` step, so
  greedy outputs stay token-identical across failover — and a warm
  standby replica (reusing
  :class:`~ray_lightning_tpu.reliability.elastic.StandbyPool`) is
  promoted to restore capacity, with the pool refilled off the critical
  path. Event order is pinned: ``fleet.failover`` →
  ``recovery.replay`` (per re-admitted request) →
  ``fleet.replica_promoted``.
- **Autoscaler** — scale-out when queue-depth / TTFT-SLO pressure
  persists past a hysteresis window (warm standby first, cold build
  after); scale-in by *draining* — the victim stops admitting, its
  in-flight work retires normally, and only then is it shut down.
  Overload and failures shed or move *requests*; they never kill work
  that is already running.

Everything is synchronous and single-threaded like the rest of the
serving stack: ``fleet.tick()`` gives each live replica one dispatch
turn, then runs the watchdog and the autoscaler, so tick-clock traces
replay bit-identically and every chaos scenario is seedable through the
``serve.replica`` fault site. Telemetry follows the repo-wide contract:
``telemetry=None`` (the default) allocates nothing — every emission
sits behind one attribute read and a ``None`` check.

See ``docs/serving.md#replica-fleet``.
"""
from __future__ import annotations

import dataclasses
import math
import re
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_lightning_tpu.reliability import faults, log_suppressed
from ray_lightning_tpu.reliability.faults import (InjectedFault, MODE_STALL,
                                                  SITE_SERVE_DRIVER,
                                                  SITE_SERVE_REPLICA)
# NOTE: reliability.gang / reliability.supervisor are imported lazily
# inside ReplicaFleet — importing them here closes a cycle (supervisor →
# serve package → this module → gang → supervisor) when the first import
# of the repo enters through the reliability package.
from ray_lightning_tpu.serve.client import ServeClient
from ray_lightning_tpu.serve.containment import SeatTable
from ray_lightning_tpu.serve.request import (Completion, DEFAULT_TENANT,
                                             FINISH_REJECTED,
                                             FINISH_TIMEOUT,
                                             OccupancyError, Request)
from ray_lightning_tpu.serve.scheduler import ACTION_IDLE, QueueFull

__all__ = ["ReplicaFleet", "Router", "RouterConfig", "FleetConfig",
           "FleetSaturated", "FleetDegraded"]

#: fleet telemetry sites (docs/observability.md)
EVENT_ROUTE = "fleet.route"
EVENT_SHED = "fleet.shed"
EVENT_FAILOVER = "fleet.failover"
EVENT_REPLICA_PROMOTED = "fleet.replica_promoted"
EVENT_SCALE_OUT = "fleet.scale_out"
EVENT_REPLICA_DRAINING = "fleet.replica_draining"
EVENT_SCALE_IN = "fleet.scale_in"
# failure containment (docs/reliability.md#failure-containment)
EVENT_DEGRADED = "fleet.degraded"
EVENT_RESTORED = "fleet.restored"
EVENT_QUARANTINE = "fleet.quarantine"
EVENT_PROBATION = "fleet.probation"
EVENT_PROBATION_CLEARED = "fleet.probation_cleared"
EVENT_POISON_FAILED = "fleet.poison_failed"
EVENT_READMIT_PARKED = "fleet.readmit_parked"

GAUGE_REPLICAS_LIVE = "serve_fleet_replicas_live"
GAUGE_QUEUE_DEPTH = "serve_fleet_queue_depth"
GAUGE_QUARANTINED = "serve_fleet_quarantined"
COUNTER_FAILOVERS = "serve_fleet_failovers_total"
COUNTER_READMITTED = "serve_fleet_readmitted_requests_total"
COUNTER_SHED = "serve_fleet_shed_total"
COUNTER_POISON_FAILED = "serve_fleet_poison_failed_total"
HISTOGRAM_ROUTER_LOAD = "serve_fleet_router_load"


class FleetSaturated(QueueFull):
    """Every replica refused admission: the *global* shed verdict.

    Raised only after the router has offered the request to every
    admitting replica and each one's own admission control said no.
    Aggregates the per-replica occupancy context the refusals carried
    (PR 7's shed-load contract): ``queue_depth`` is the fleet-wide
    waiting total, ``oldest_age`` the staleness of the oldest queue head
    anywhere, ``replicas`` how many replicas were offered the request.
    """

    def __init__(self, message: str, *,
                 queue_depth: Optional[int] = None,
                 oldest_age: Optional[float] = None,
                 replicas: Optional[int] = None,
                 class_depths: Optional[dict] = None,
                 class_oldest: Optional[dict] = None):
        # skip QueueFull.__init__ (narrower kwargs): the OccupancyError
        # base renders any context. Tenancy armed, ``class_depths`` /
        # ``class_oldest`` aggregate the per-class queue depths and
        # oldest head ages across every offered replica, so shed
        # logging names the saturated CLASS, not just the fleet totals.
        OccupancyError.__init__(self, message, queue_depth=queue_depth,
                                oldest_age=oldest_age, replicas=replicas,
                                class_depths=class_depths,
                                class_oldest=class_oldest)


class FleetDegraded(FleetSaturated):
    """Shed while the fleet is *degraded*: quarantined seats hold it
    below ``min_replicas`` and the survivors' admission control said no.

    A subclass of :class:`FleetSaturated` so every existing shed path
    (``serve_trace``'s ``QueueFull`` catch, caller backoff) handles it
    unchanged — the distinct type is the operator signal that capacity
    is gone to quarantine, not to load: retrying harder will not help
    until a backoff elapses. Carries ``quarantined`` (gated seats) and
    ``live`` (surviving replicas) on top of the saturation context.
    """

    def __init__(self, message: str, *, quarantined: Optional[int] = None,
                 live: Optional[int] = None, **ctx):
        super().__init__(message, **ctx)
        self.quarantined = quarantined
        self.live = live


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Routing policy knobs.

    ``affinity_tokens``: prompt-prefix length (in tokens) keying the
    prefix-affinity map — requests whose first ``affinity_tokens``
    tokens match prefer the replica that last admitted that prefix
    (its prefix cache holds the pages). ``None`` (default) resolves
    automatically: ``prefill_chunk`` on prefix-cache engines (the
    smallest publishable unit), affinity off otherwise. ``0`` forces it
    off. ``affinity_capacity`` bounds the map (LRU).

    ``ttft_alpha``: EWMA smoothing for the per-replica TTFT signal the
    scoring falls back to on load ties.
    """
    affinity_tokens: Optional[int] = None
    affinity_capacity: int = 1024
    ttft_alpha: float = 0.25

    def __post_init__(self):
        if self.affinity_tokens is not None and self.affinity_tokens < 0:
            raise ValueError(
                f"affinity_tokens must be >= 0 or None, got "
                f"{self.affinity_tokens}")
        if self.affinity_capacity < 1:
            raise ValueError(
                f"affinity_capacity must be >= 1, got "
                f"{self.affinity_capacity}")
        if not 0.0 < self.ttft_alpha <= 1.0:
            raise ValueError(
                f"ttft_alpha must be in (0, 1], got {self.ttft_alpha}")


class Router:
    """Load- and affinity-aware replica choice, deterministic by design.

    Scoring reads only live signals the obs layer already exports per
    replica: scheduler queue depth, occupied KV slots, streaming chunk
    prefills, paged-arena occupancy, and a TTFT EWMA folded in from
    retirements. Ties break on the stable replica id, so identical
    fleet states route identically — the property every pinned trace
    test leans on.
    """

    def __init__(self, config: Optional[RouterConfig] = None,
                 affinity_tokens: Optional[int] = None,
                 telemetry: Any = None):
        self.config = config or RouterConfig()
        if affinity_tokens is None:
            # standalone construction: the config field is the source
            # of truth (its None-auto resolution needs engine context,
            # which only ReplicaFleet has — it passes the resolved
            # count explicitly)
            affinity_tokens = self.config.affinity_tokens or 0
        self.affinity_tokens = int(affinity_tokens)
        self._tel = telemetry
        self._affinity: "OrderedDict[Tuple[int, ...], int]" = OrderedDict()
        self._ttft: Dict[int, float] = {}
        self.decisions = 0
        self.affinity_hits = 0

    # --------------------------------------------------------- scoring
    @staticmethod
    def load(replica: "_Replica") -> int:
        """Work parked on a replica: waiting + decoding + chunking."""
        engine = replica.client.engine
        return (len(replica.client.scheduler) + engine.active_count
                + engine.chunk_pending)

    @staticmethod
    def class_load(replica: "_Replica", request: Request) -> int:
        """Waiting requests of ``request``'s own tenant class on this
        replica (0 without a tenant scheduler — untenanted routing is
        byte-identical to the pre-tenancy order). The tenant-aware
        tiebreak: among equally loaded replicas, a class's requests
        steer away from the replica where THAT class is backed up
        (and closest to its per-class quota shedding them)."""
        depths = getattr(replica.client.scheduler, "class_depths", None)
        if depths is None:
            return 0
        return depths().get(request.tenant, 0)

    @staticmethod
    def occupancy(replica: "_Replica") -> float:
        """Paged-arena page occupancy in [0, 1] (0.0 on dense engines):
        the tiebreak that steers work away from arenas running out of
        pages before their slots run out."""
        engine = replica.client.engine
        free = engine.free_pages
        if free is None:
            return 0.0
        return 1.0 - free / engine.pool.num_pages

    def _key(self, request: Request) -> Optional[Tuple[int, ...]]:
        n = self.affinity_tokens
        if n <= 0 or len(request.prompt) < n:
            return None
        return tuple(request.prompt[:n])

    def affine_target(self, request: Request) -> Optional[int]:
        """The replica id holding ``request``'s prompt-prefix pages, or
        ``None`` (affinity off / prefix unseen). The one affinity
        lookup — :meth:`order` promotes this replica and the fleet's
        admission reports a hit against it."""
        key = self._key(request)
        return self._affinity.get(key) if key is not None else None

    def order(self, replicas: Sequence["_Replica"],
              request: Request) -> List["_Replica"]:
        """Admitting replicas in preference order: the affine replica
        (if any, and still admitting) first, then ascending
        (load, occupancy, TTFT EWMA, id). The caller offers the request
        down this list — a refusal sheds to the next candidate."""
        ranked = sorted(
            (r for r in replicas if r.admitting),
            key=lambda r: (self.load(r), self.class_load(r, request),
                           self.occupancy(r),
                           self._ttft.get(r.id, 0.0), r.id))
        rid = self.affine_target(request)
        if rid is not None:
            for i, rep in enumerate(ranked):
                if rep.id == rid:
                    if i:
                        ranked.insert(0, ranked.pop(i))
                    break
        return ranked

    # ------------------------------------------------------ bookkeeping
    def note_admission(self, replica: "_Replica", request: Request,
                       load: int, affine: bool) -> None:
        """One routing decision committed: refresh the affinity map and
        record the decision histogram (how loaded the chosen replica
        was — a skewed histogram means the balancer is failing)."""
        self.decisions += 1
        if affine:
            self.affinity_hits += 1
        key = self._key(request)
        if key is not None:
            self._affinity.pop(key, None)
            self._affinity[key] = replica.id
            while len(self._affinity) > self.config.affinity_capacity:
                self._affinity.popitem(last=False)
        tel = self._tel
        if tel is not None:
            tel.event(EVENT_ROUTE, id=request.id, replica=replica.id,
                      load=load, affinity=affine)
            tel.metrics.histogram(
                HISTOGRAM_ROUTER_LOAD,
                help="chosen replica's load at each routing decision"
            ).observe(float(load))

    def record_ttft(self, replica_id: int, ttft: float) -> None:
        a = self.config.ttft_alpha
        prev = self._ttft.get(replica_id)
        self._ttft[replica_id] = (ttft if prev is None
                                  else (1.0 - a) * prev + a * ttft)

    def forget(self, replica_id: int) -> None:
        """Drop a dead/retired replica's affinity entries and TTFT state
        — new prefixes must not chase a ghost."""
        self._ttft.pop(replica_id, None)
        stale = [k for k, rid in self._affinity.items()
                 if rid == replica_id]
        for k in stale:
            del self._affinity[k]

    def shutdown(self) -> None:
        """Release routing state (affinity map, EWMA ledger)."""
        self._affinity.clear()
        self._ttft.clear()


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Supervision + autoscaling knobs, in the fleet's clock units
    (ticks by default, seconds under a wall clock).

    ``heartbeat_timeout``: how long a replica may go without completing
    a dispatch turn before the watchdog declares it hung and fails it
    over (``startup_grace``, ``None`` = same, covers a fresh replica's
    first compile-heavy dispatch). The ledger and verdicts reuse
    :class:`~ray_lightning_tpu.reliability.gang.GangMonitor` on the
    fleet clock, so hang detection is bounded-time AND deterministic in
    tick mode.

    Autoscaling (``autoscale=True``): scale OUT one replica when the
    fleet-wide queue depth exceeds ``scale_out_queue_depth`` per
    admitting replica — or the fleet TTFT EWMA exceeds ``ttft_slo`` —
    for ``hysteresis`` consecutive ticks (warm standby first, cold
    build otherwise, never past ``max_replicas``); scale IN by draining
    the newest admitting replica after ``hysteresis`` consecutive
    pressure-free ticks with an empty fleet queue, never below
    ``min_replicas``. ``min_replicas`` is also the failover floor: a
    failover that would drop the fleet below it cold-builds a
    replacement even with the standby pool empty.

    Failure containment (all OFF by default — a default config is
    decision-for-decision identical to a pre-containment fleet; see
    docs/reliability.md#failure-containment):

    ``max_request_failovers``: per-request failover budget. Every
    replica death implicates its co-batched in-flight requests
    (``Request.crash_implications``); a request re-admitting at the
    budget retires ``failed`` with its partial tokens instead of
    consuming another replica. Setting it also arms **probation**:
    a request implicated ``probation_after``+ times re-admits solo on a
    router-excluded replica, so a poison request stops taking innocent
    batchmates down with it — a clean probation run resets the count.

    ``flap_window`` / ``flap_threshold``: replica crash-loop
    quarantine. A seat accumulating ``flap_threshold`` deaths inside a
    sliding ``flap_window`` (fleet clock units) quarantines: catch-up
    rebuilds into it follow ``quarantine_backoff`` (a
    :class:`~ray_lightning_tpu.reliability.RetryPolicy`; a
    deterministic-jitter default when None) instead of hot-looping
    build→die→build. While quarantine holds the fleet below
    ``min_replicas`` it is *degraded*: survivors keep serving, sheds
    raise :class:`FleetDegraded`, and ``fleet.degraded`` /
    ``fleet.restored`` bracket the episode.
    """
    heartbeat_timeout: float = 8.0
    startup_grace: Optional[float] = None
    autoscale: bool = False
    min_replicas: int = 1
    max_replicas: int = 8
    scale_out_queue_depth: float = 4.0
    ttft_slo: Optional[float] = None
    hysteresis: int = 3
    max_request_failovers: Optional[int] = None
    probation_after: int = 2
    flap_window: Optional[float] = None
    flap_threshold: int = 3
    quarantine_backoff: Optional[Any] = None  # RetryPolicy

    def __post_init__(self):
        if self.heartbeat_timeout <= 0:
            raise ValueError(
                f"heartbeat_timeout must be > 0, got "
                f"{self.heartbeat_timeout}")
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) < min_replicas "
                f"({self.min_replicas})")
        if self.hysteresis < 1:
            raise ValueError(
                f"hysteresis must be >= 1, got {self.hysteresis}")
        if (self.max_request_failovers is not None
                and self.max_request_failovers < 1):
            raise ValueError(
                f"max_request_failovers must be >= 1 or None, got "
                f"{self.max_request_failovers}")
        if self.probation_after < 1:
            raise ValueError(
                f"probation_after must be >= 1, got "
                f"{self.probation_after}")
        if self.flap_window is not None and self.flap_window <= 0:
            raise ValueError(
                f"flap_window must be > 0 or None, got "
                f"{self.flap_window}")
        if self.flap_threshold < 1:
            raise ValueError(
                f"flap_threshold must be >= 1, got {self.flap_threshold}")
        if (self.quarantine_backoff is not None
                and self.flap_window is None):
            raise ValueError(
                "quarantine_backoff requires flap_window (the sliding "
                "death window is what arms quarantine)")


class _Replica:
    """One supervised replica seat: a ServeClient plus its lifecycle
    flags. ``id`` is stable for the replica's whole life (fault specs
    and affinity entries address it); list position is not."""

    __slots__ = ("id", "client", "draining", "stalled",
                 "last_beat", "last_step", "beats")

    def __init__(self, replica_id: int, client: ServeClient):
        self.id = replica_id
        self.client = client
        # per-replica gauge keying: every replica writes its occupancy
        # gauges into the ONE shared name-keyed registry, so without a
        # replica-id prefix they clobber each other last-writer-wins
        # (the old docs/observability.md caveat). The id is stable for
        # the replica's whole life, so `replica<id>_serve_*` series
        # stay coherent across failovers; a standby promoted here gets
        # its prefix at adoption time, before its first dispatch.
        client.gauge_prefix = f"replica{replica_id}_"
        # seat-tag engine spans so the stitched fleet Chrome trace
        # (obs/tracing.py) puts this replica on its own pid track; the
        # getattr guard keeps duck-typed clients (process-backend
        # proxies have no local engine) working
        engine = getattr(client, "engine", None)
        if engine is not None and hasattr(engine, "_span_extra"):
            engine._span_extra = {"seat": replica_id}
        self.draining = False   # scale-in: finish in-flight, admit nothing
        self.stalled = False    # latched wedge (serve.replica stall fault)
        # carried beat state: the monitor is rebuilt on membership
        # changes, and this is what re-seeds it so a surviving
        # replica's silence clock survives the rebuild
        self.last_beat: Optional[float] = None
        self.last_step = -1
        self.beats = 0

    @property
    def admitting(self) -> bool:
        return not self.draining and not self.stalled

    @property
    def busy(self) -> bool:
        engine = self.client.engine
        return bool(len(self.client.scheduler) or engine.active_count
                    or engine.chunk_pending)


class _ClientRay:
    """Duck-typed stand-in for the ray module a
    :class:`~ray_lightning_tpu.reliability.elastic.StandbyPool` drives:
    fleet standbys are warm in-process :class:`ServeClient` replicas
    (KV arena allocated, object graph built), not remote actors, so
    "kill" releases the engine and "get" resolves the (absent) warm-up
    future trivially. ``actor_alive``'s duck-probe reports a plain
    client alive, which is exactly right — an in-process standby dies
    with the fleet or not at all."""

    @staticmethod
    def kill(actor: Any, no_restart: bool = True) -> None:
        actor.shutdown()

    @staticmethod
    def get(ref: Any, timeout: Optional[float] = None) -> Any:
        return ref


class ReplicaFleet:
    """N supervised :class:`ServeClient` replicas behind a
    :class:`Router`, driven by one deterministic loop.

    ``ReplicaFleet(model, params, num_replicas=3, num_standby=1,
    num_slots=4, ...)`` — engine keyword arguments are forwarded to
    every replica (and to warm standbys), so the whole fleet compiles
    the same fixed-shape programs and any replica can seat any
    request; that includes the decode-bandwidth levers
    (``kv_dtype="int8"``, ``weight_dtype="int8"|"int4"``,
    ``page_native=True``, ``draft_model=``/``spec_k=``, and the two
    kernel selectors ``attention_kernel=``/``matmul_kernel=`` — each
    replica's engine clones the model config with the requested
    kernels, so the whole fleet re-selects identical programs) — every
    replica re-quantizes the shared raw params to bit-identical codes,
    so failover replay onto a sibling stays token-identical (pinned by
    ``tests/test_quant.py`` and ``tests/test_pallas_matmul.py``). ``submit()`` routes one request;
    ``serve_trace()`` / ``run_until_idle()`` mirror the single-client
    surface. Call :meth:`shutdown` when done — it releases every
    replica's KV pool/arena, the standby pool, and the router.

    ``backend="process"`` constructs the process-backed variant
    (:class:`~ray_lightning_tpu.serve.process_fleet.
    ProcessReplicaFleet`, same contract and ``isinstance`` identity):
    each replica dispatches in its own worker process, so N replicas
    actually deliver ~N× tokens/sec instead of time-slicing this
    class's single drive thread. The default ``"inproc"`` backend
    stays the deterministic tick-clock harness every pinned trace and
    chaos test replays against.

    Failure semantics: a replica that crashes (its dispatch raises —
    including ``serve.replica`` ``raise`` faults) or hangs (stops
    completing dispatch turns past ``heartbeat_timeout``) is torn down
    and its work — in-flight snapshot AND queued backlog — re-admits to
    surviving replicas via the PR 3 replay contract; requests keep
    their ids, arrival times, deadlines, accumulated tokens, and
    first-token stamps. With ``retry_policy=`` forwarded to the
    engines, each replica additionally self-heals engine-level dispatch
    crashes in place (:class:`ServeSupervisor`) and the fleet layer
    only sees whole-replica deaths.
    """

    def __new__(cls, *args: Any, **kwargs: Any) -> "ReplicaFleet":
        # the backend switch: ``ReplicaFleet(..., backend="process")``
        # constructs a ProcessReplicaFleet (same contract, replicas in
        # their own worker processes — see serve/process_fleet.py).
        # Dispatched here so callers hold ONE fleet type and
        # ``isinstance(fleet, ReplicaFleet)`` stays true either way.
        backend = kwargs.get("backend", "inproc")
        if backend not in ("inproc", "process"):
            raise ValueError(
                f"backend must be 'inproc' or 'process', got {backend!r}")
        if cls is ReplicaFleet and backend == "process":
            from ray_lightning_tpu.serve.process_fleet import \
                ProcessReplicaFleet
            return object.__new__(ProcessReplicaFleet)
        return object.__new__(cls)

    def __init__(self, model, params, *, backend: str = "inproc",
                 num_replicas: int = 2,
                 num_standby: int = 0,
                 fleet_config: Optional[FleetConfig] = None,
                 router_config: Optional[RouterConfig] = None,
                 telemetry: Any = None,
                 clock: Optional[Callable[[], float]] = None,
                 journal=None,
                 **engine_kwargs: Any):
        self.backend = "inproc"
        if num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {num_replicas}")
        if num_standby < 0:
            raise ValueError(
                f"num_standby must be >= 0, got {num_standby}")
        self._model = model
        self._params = params
        self._engine_kwargs = dict(engine_kwargs)
        self._cfg = fleet_config or FleetConfig()
        self._tel = telemetry
        self._clock = clock
        self._t0: Optional[float] = None
        self._ticks = 0
        self._next_id = 0
        self._next_replica_id = 0
        self.completions: Dict[int, Completion] = {}
        # write-ahead request journal (serve/journal.py): the FLEET owns
        # it — member clients are built with journal=None, so one record
        # stream covers every replica and failover re-admissions are
        # re-journaled with their replay binding. journal=None (the
        # default) is the repo-wide zero-cost contract.
        self._journal = journal

        rcfg = router_config or RouterConfig()
        affinity = rcfg.affinity_tokens
        if affinity is None:
            # auto: the chunk is the smallest unit the prefix cache
            # publishes, so prompts sharing one are the ones with pages
            # to adopt; without a prefix cache affinity buys nothing
            affinity = (engine_kwargs.get("prefill_chunk") or 0
                        if engine_kwargs.get("prefix_cache") else 0)
        self.router = Router(rcfg, affinity_tokens=affinity,
                             telemetry=telemetry)

        self._replicas: List[_Replica] = [
            self._new_replica() for _ in range(num_replicas)]

        if num_standby:
            from ray_lightning_tpu.reliability.elastic import StandbyPool
            self.standby = StandbyPool(_ClientRay, num_standby=num_standby,
                                       warmup=None, telemetry=telemetry)
            self.standby.fill(self._build_client)
        else:
            self.standby = None

        from ray_lightning_tpu.reliability.gang import GangConfig
        self._gang_cfg = GangConfig(
            heartbeat_timeout=self._cfg.heartbeat_timeout,
            startup_grace=self._cfg.startup_grace, clock=self.now)
        self._monitor = None
        self._rebuild_monitor()

        # autoscaler hysteresis state + fleet-wide TTFT EWMA
        self._pressure_ticks = 0
        self._idle_ticks = 0
        self._ttft_ewma: Optional[float] = None
        # how many replicas the fleet is SUPPOSED to run: failovers
        # restore toward it (a promotion that raced an in-flight
        # standby refill is caught up at tick time), scale events move
        # it
        self._target_replicas = num_replicas

        # reliability accounting (the bench's failover cost source)
        self.failovers = 0
        self.readmitted = 0
        self.readmit_failed = 0
        self.scale_outs = 0
        self.scale_ins = 0
        self.failover_s_total = 0.0

        # failure containment (docs/reliability.md#failure-containment).
        # All state below is inert under a default config: nothing
        # reads crash_implications without max_request_failovers, the
        # parked list only fills where the old code insta-failed, and
        # the seat table is None without flap_window.
        self.poison_failed = 0
        self._parked: List[Request] = []
        self._probation: List[Request] = []
        self._probation_rep: Optional[int] = None
        self._probation_obj: Optional[Request] = None
        self._degraded = False
        self._seats: Optional[SeatTable] = None
        if self._cfg.flap_window is not None:
            from ray_lightning_tpu.reliability.retry import RetryPolicy
            policy = self._cfg.quarantine_backoff or RetryPolicy(
                max_attempts=8, base_delay=1.0, max_delay=60.0,
                multiplier=2.0, jitter=0.1)
            self._seats = SeatTable(self._cfg.flap_window,
                                    self._cfg.flap_threshold, policy)
            for rep in self._replicas:
                self._seats.occupy(rep.id, self.now(), grow=True)

    # ------------------------------------------------------------ clock
    @property
    def ops(self) -> int:
        """Fleet ticks so far — the tick clock."""
        return self._ticks

    def now(self) -> float:
        if self._clock is None:
            return float(self._ticks)
        if self._t0 is None:
            self._t0 = self._clock()
        return self._clock() - self._t0

    # --------------------------------------------------------- replicas
    @property
    def replicas_live(self) -> int:
        return len(self._replicas)

    @property
    def replica_ids(self) -> List[int]:
        return [rep.id for rep in self._replicas]

    def _build_client(self) -> ServeClient:
        # clock_epoch=0.0 pins every replica — including ones built
        # mid-run for promotion/scale-out — to the fleet's own t=0
        client = ServeClient(self._model, self._params, clock=self.now,
                             clock_epoch=0.0, telemetry=self._tel,
                             **self._engine_kwargs)
        # a member client's tick is a replica turn (serve.replica
        # territory) — it must never fire the serve.driver site, whose
        # raise mode means "the DRIVER died", not "this replica died"
        client._fire_driver_site = False
        return client

    def _new_replica(self) -> _Replica:
        rep = _Replica(self._next_replica_id, self._build_client())
        self._next_replica_id += 1
        return rep

    def _adopt(self, client: ServeClient) -> _Replica:
        rep = _Replica(self._next_replica_id, client)
        self._next_replica_id += 1
        self._replicas.append(rep)
        return rep

    def _rebuild_monitor(self) -> None:
        """Membership changed: fresh ledger over the new replica list
        (indices are ranks), re-seeded with every surviving replica's
        carried beat state — a rebuild must NOT reset a wedged
        replica's silence clock (membership churn recurring faster
        than ``heartbeat_timeout`` would defer its hang verdict
        forever), and a second same-tick failover's postmortem keeps
        its real beat ages. Fresh promotions have no carried state and
        start at the stamp, under startup grace."""
        from ray_lightning_tpu.reliability.gang import GangMonitor
        self._monitor = GangMonitor(len(self._replicas), self._gang_cfg)
        self._monitor.start()
        for idx, rep in enumerate(self._replicas):
            if rep.last_beat is not None:
                self._monitor.seed(idx, last_beat=rep.last_beat,
                                   last_step=rep.last_step,
                                   beats=rep.beats)

    # ------------------------------------------------------- submission
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0, top_k: Optional[int] = None,
               eos_id: Optional[int] = None, seed: Optional[int] = None,
               deadline: Optional[float] = None,
               tenant: Optional[str] = None,
               adapter: Optional[str] = None) -> int:
        """Route + enqueue one request; returns its fleet-wide id.
        Raises ``ValueError`` for requests no replica could ever fit
        (or that name an undeclared tenant, or an ``adapter`` not
        resident fleet-wide) and
        :class:`FleetSaturated` when every replica refuses — a class at
        its per-replica quota sheds ``ClassQueueFull`` to the next
        candidate exactly like any other refusal."""
        req = Request(id=self._next_id, prompt=prompt,
                      max_new_tokens=max_new_tokens,
                      temperature=temperature, top_k=top_k, eos_id=eos_id,
                      seed=seed, deadline=deadline,
                      tenant=tenant or DEFAULT_TENANT,
                      adapter=adapter)
        self._admit(req)
        self._next_id += 1
        return req.id

    def _admit(self, req: Request) -> _Replica:
        """Offer ``req`` down the router's preference order; first
        replica whose admission control accepts wins. Raises
        :class:`FleetSaturated` (aggregated context) when all refuse."""
        ranked = self.router.order(self._replicas, req)
        if self._probation_rep is not None:
            # the probation replica is reserved for its solo suspect —
            # regular traffic routes around it until the run clears
            ranked = [r for r in ranked if r.id != self._probation_rep]
        affine_target = self.router.affine_target(req)
        for rep in ranked:
            load = self.router.load(rep)
            try:
                rep.client.submit_request(req)
            except QueueFull:
                continue
            self.router.note_admission(
                rep, req, load=load,
                affine=(affine_target is not None
                        and rep.id == affine_target))
            if self._journal is not None:
                self._journal.admit(req)
            return rep
        now = self.now()
        total = sum(len(r.client.scheduler) for r in self._replicas)
        oldest = [r.client.scheduler.oldest_age(now)
                  for r in self._replicas]
        oldest = [a for a in oldest if a is not None]
        # tenancy armed: aggregate the per-class breakdown across every
        # replica so the shed log names the saturated class
        class_depths: Dict[str, int] = {}
        class_oldest: Dict[str, float] = {}
        for r in self._replicas:
            sched = r.client.scheduler
            if getattr(sched, "class_depths", None) is None:
                continue
            for name, depth in sched.class_depths().items():
                class_depths[name] = class_depths.get(name, 0) + depth
            for name, age in sched.class_oldest(now).items():
                class_oldest[name] = max(class_oldest.get(name, age), age)
        if self._degraded and self._seats is not None:
            raise FleetDegraded(
                "fleet degraded (quarantined seats below min_replicas); "
                "every survivor's admission control refused the request",
                quarantined=self._seats.gated(now),
                live=len(self._replicas),
                queue_depth=total,
                oldest_age=max(oldest) if oldest else None,
                replicas=len(ranked),
                class_depths=class_depths or None,
                class_oldest=class_oldest or None)
        raise FleetSaturated(
            "every replica's admission control refused the request",
            queue_depth=total, oldest_age=max(oldest) if oldest else None,
            replicas=len(ranked),
            class_depths=class_depths or None,
            class_oldest=class_oldest or None)

    # ----------------------------------------------------- warm restart
    @classmethod
    def restore(cls, journal_path: str, model, params, *,
                journal_sync_every: int = 8,
                **build_kwargs: Any) -> "ReplicaFleet":
        """Rebuild a fleet from a dead driver's journal and re-admit
        every unretired request through the router's replay lane.

        ``build_kwargs`` are the same constructor arguments the dead
        fleet was built with (``backend="process"`` included — the
        ``__new__`` dispatch applies here too, so a process fleet
        restores as a process fleet). The journal is REOPENED with a
        bumped generation: on the process backend that generation is
        stamped into every fresh worker, and the driver's queue drains
        refuse messages still carrying the dead driver's generation
        (the split-brain fence), while the dead driver's orphaned
        workers self-reap within the grace window. Re-admissions ride
        :meth:`_readmit` — fit-checked against the replay window,
        parked when every replica is transiently full, failover
        budget/probation honored — with ``replay_tokens`` set from the
        journaled frontier, so token identity holds by the PR 3 replay
        argument and retired requests are never re-emitted.
        """
        from ray_lightning_tpu.serve.journal import (
            COUNTER_JOURNAL_REPLAYED, EVENT_JOURNAL_RESTORED, Journal,
            read_journal)
        state = read_journal(journal_path)
        journal = Journal(journal_path, sync_every=journal_sync_every,
                          generation=state.generation + 1,
                          telemetry=build_kwargs.get("telemetry"))
        fleet = cls(model, params, journal=journal, **build_kwargs)
        pending = state.pending()
        for req, toks in pending:
            fleet._readmit(req, list(toks) if toks else None)
        fleet._next_id = max(fleet._next_id, state.next_request_id)
        tel = fleet._tel
        if tel is not None:
            tel.event(EVENT_JOURNAL_RESTORED, path=str(journal_path),
                      generation=journal.generation,
                      replayed=len(pending), retired=len(state.retired),
                      torn_tail=state.torn_tail)
            tel.metrics.counter(
                COUNTER_JOURNAL_REPLAYED,
                help="unretired requests re-admitted by warm restart"
            ).inc(len(pending))
        return fleet

    # ---------------------------------------------------- hot adapters
    def load_adapter(self, name: str, adapter) -> Optional[str]:
        """Broadcast a hot adapter load to every live replica, keeping
        the whole fleet's resident set in lockstep (any replica can
        seat any request — including a failover re-admission bound to
        this adapter). Every replica holds the SAME resident names by
        construction (identical initial ``adapters=`` kwargs, then only
        lockstep broadcasts), so when the bank is full the fleet evicts
        ONE fleet-chosen victim — the oldest fleet-level load — via an
        explicit unload broadcast first; per-replica LRU eviction
        (which could diverge across replicas whose bind recencies
        differ with routing) never triggers under fleet ops. Returns
        the evicted name, or ``None``. Refuses
        (:class:`~ray_lightning_tpu.serve.request.OccupancyError`) when
        the would-be victim is pinned by in-flight rows anywhere."""
        resident = dict(self._engine_kwargs.get("adapters") or {})
        cap = self._engine_kwargs.get("max_resident_adapters")
        evicted: Optional[str] = None
        if (name not in resident and cap is not None
                and len(resident) >= int(cap)):
            evicted = next(iter(resident))
            self.unload_adapter(evicted)
            resident = dict(self._engine_kwargs.get("adapters") or {})
        for rep in self._replicas:
            rep.client.load_adapter(name, adapter)
        self._sweep_barrier_completions()
        resident[name] = adapter
        self._engine_kwargs["adapters"] = resident
        return evicted

    def unload_adapter(self, name: str) -> None:
        """Broadcast a hot unload. Atomic fleet-wide: every replica's
        pipeline is drained and its refcount checked BEFORE any replica
        unloads, so a pinned adapter refuses without leaving the fleet's
        resident sets diverged."""
        for rep in self._replicas:
            rep.client._drain_for_barrier()
            refs = rep.client.engine.adapter_refcount(name)
            if refs:
                self._sweep_barrier_completions()
                raise OccupancyError(
                    f"cannot unload adapter {name!r}: {refs} in-flight "
                    f"request(s) on replica {rep.id} still bound to it",
                    adapter=name, replica=rep.id, refcount=refs)
        for rep in self._replicas:
            rep.client.unload_adapter(name)
        self._sweep_barrier_completions()
        resident = dict(self._engine_kwargs.get("adapters") or {})
        resident.pop(name, None)
        self._engine_kwargs["adapters"] = resident

    def _sweep_barrier_completions(self) -> None:
        """Adapter barriers drain each replica's pipelined dispatch
        inside the client, so completions the drain retires land in the
        client's ledger without passing through a ``tick()`` return —
        sweep them into the fleet's (same contract as the failover
        ledger sweep)."""
        for rep in self._replicas:
            for rid, comp in rep.client.completions.items():
                if rid not in self.completions:
                    self._note_completion(rep, comp)

    # ------------------------------------------------------------- loop
    def tick(self) -> List[Completion]:
        """One fleet scheduling round: every live replica gets one
        dispatch turn (firing the ``serve.replica`` fault site with its
        id — runnable replicas first, idle ones after, stable
        replica-id tiebreak within each group, so pinned fault ticks
        must be aimed with that order in mind), then the watchdog
        applies its silence verdicts and the autoscaler runs. Returns
        the completions this round retired (failover casualties
        included)."""
        # the driver-death site: raise mode propagates out of the
        # fleet's own tick — the whole fleet state machine dies, which
        # is exactly what ReplicaFleet.restore exists to survive
        faults.fire(SITE_SERVE_DRIVER)
        done: List[Completion] = []
        # parked failover re-admissions (every survivor transiently
        # full at failover time) retry BEFORE the dispatch turns, so a
        # re-seated request joins this very tick's prefill action
        self._pump_parked(done)
        # drive order: replicas with a runnable action (a dispatch to
        # enqueue, or an async dispatch to reconcile) go FIRST, idle
        # replicas after — strict list order used to park queued work
        # on replica 2 behind replica 0's idle turn, and under async
        # dispatch the early enqueues now compute while the later
        # replicas' host work runs. Deterministic: stable (runnable,
        # replica-id) sort, pinned by tests/test_async_dispatch.py.
        order = sorted(self._replicas,
                       key=lambda rep: (not self._runnable(rep), rep.id))
        for rep in order:
            if rep not in self._replicas:
                continue  # removed by an earlier failover this round
            done.extend(self._tick_replica(rep))
        self._ticks += 1
        silent = [self._replicas[i]
                  for i in self._monitor.silent_ranks()
                  if i < len(self._replicas)]
        for rep in silent:
            if rep in self._replicas:
                done.extend(self._fail_replica(rep, dead=False))
        if len(self._replicas) < self._target_replicas and (
                self._seats is None
                or self._seats.allow_build(self.now())):
            # catch-up restoration: a failover that found the standby
            # pool empty (raced refill — or no pool at all) must not
            # leave the fleet serving short forever. Warm-promote if a
            # standby landed, cold-build otherwise: the construction
            # cost lands on THIS tick, off the failover critical path.
            # Quarantined seats gate this path: a crash-looping seat
            # rebuilds on its backoff schedule, not every tick.
            rep, source = self._adopt_standby_or_build(cold_ok=True)
            self._rebuild_monitor()
            if self._tel is not None:
                self._tel.event(EVENT_REPLICA_PROMOTED,
                                replica=rep.id, source=source,
                                replicas_live=len(self._replicas))
        if self._cfg.autoscale:
            self._autoscale()
        self._pump_probation(done)
        tel = self._tel
        if self._seats is not None:
            gated = self._seats.gated(self.now())
            deg = (gated > 0
                   and len(self._replicas) < self._cfg.min_replicas)
            if deg != self._degraded:
                self._degraded = deg
                if tel is not None:
                    tel.event(EVENT_DEGRADED if deg else EVENT_RESTORED,
                              quarantined=gated,
                              replicas_live=len(self._replicas))
            if tel is not None:
                tel.metrics.gauge(
                    GAUGE_QUARANTINED,
                    help="empty replica seats inside their quarantine "
                         "backoff window").set(gated)
        if tel is not None:
            tel.metrics.gauge(
                GAUGE_REPLICAS_LIVE,
                help="serving replicas currently live (draining "
                     "included)").set(len(self._replicas))
            tel.metrics.gauge(
                GAUGE_QUEUE_DEPTH,
                help="requests waiting across every replica's queue"
            ).set(sum(len(r.client.scheduler) for r in self._replicas))
        journal = self._journal
        if journal is not None:
            # journal every replica's synced frontier (the same
            # snapshot failover replays from) so a driver death loses
            # at most the records inside the fsync window
            for rep in self._replicas:
                for req, toks in rep.client.engine.snapshot_in_flight():
                    journal.note_frontier(req.id, toks,
                                          req.first_token_time)
        return done

    def _runnable(self, rep: _Replica) -> bool:
        """Will this replica's tick actually dispatch (or reconcile)
        something? Reads the scheduler's non-mutating lookahead against
        the replica's synced engine state — a wedged replica is not
        runnable (its turn is skipped anyway), an idle one only
        advances its clock."""
        if rep.stalled:
            return False
        client = rep.client
        if client.dispatch_in_flight:
            return True
        return client.scheduler.peek_action(client.engine) != ACTION_IDLE

    def _tick_replica(self, rep: _Replica) -> List[Completion]:
        if rep.stalled:
            # wedged dispatch loop: no dispatch, no beat — the silence
            # verdict fails it over within heartbeat_timeout
            return []
        try:
            verdict = faults.fire(SITE_SERVE_REPLICA, rank=rep.id)
        except InjectedFault as exc:
            log_suppressed("fleet.replica", exc,
                           f"replica {rep.id} killed; failing over")
            return self._fail_replica(rep, dead=True)
        if verdict == MODE_STALL:
            # a latched wedge, not a one-dispatch hiccup: a stalled
            # collective/host callback never comes back on its own —
            # the replica stops beating and supervision takes it out
            rep.stalled = True
            return []
        try:
            out = rep.client.tick()
        except Exception as exc:  # noqa: BLE001 — replica crash enters failover
            log_suppressed("fleet.replica", exc,
                           f"replica {rep.id} dispatch crashed; "
                           "failing over")
            return self._fail_replica(rep, dead=True)
        self._monitor.observe(self._replicas.index(rep), rep.client.ops)
        rep.last_beat = self.now()
        rep.last_step = rep.client.ops
        rep.beats += 1
        for comp in out:
            self._note_completion(rep, comp)
        return out

    def _note_completion(self, rep: _Replica, comp: Completion) -> None:
        self.completions[comp.request_id] = comp
        if self._journal is not None:
            self._journal.retire(comp)
        ttft = comp.time_to_first_token
        if ttft is not None:
            self.router.record_ttft(rep.id, ttft)
            a = self.router.config.ttft_alpha
            self._ttft_ewma = (ttft if self._ttft_ewma is None
                               else (1.0 - a) * self._ttft_ewma + a * ttft)

    # --------------------------------------------------------- failover
    def _fail_replica(self, rep: _Replica, *,
                      dead: bool) -> List[Completion]:
        """Drain a dead (``dead=True``) or hung replica: snapshot its
        work, tear it down, re-admit everything to survivors via
        replay, then promote a standby. Returns the FINISH_FAILED
        completions of requests nothing could re-seat."""
        t0 = time.perf_counter()
        self.failovers += 1
        tel = self._tel
        idx = self._replicas.index(rep)
        post = self._monitor.postmortems(
            silent=() if dead else (idx,),
            dead=(idx,) if dead else ()).get(idx)
        engine = rep.client.engine
        entries = engine.snapshot_in_flight()
        queued = rep.client.scheduler.waiting
        # every co-batched in-flight request is IMPLICATED by this
        # death (queued requests never touched the engine and are not);
        # the counter rides the request object through re-admission,
        # like replay_tokens. Implication is not proof — probation
        # sorts innocents from poison (docs/reliability.md).
        for _req, _toks in entries:
            _req.crash_implications += 1
        if self._probation_rep == rep.id:
            # the probation replica died — almost certainly the suspect
            # crashed it. Release the reservation; the suspect rides
            # the normal re-admission path below with its bumped count
            # (back to probation, or out at the budget).
            self._probation_rep = None
            self._probation_obj = None
        if tel is not None:
            tel.event(EVENT_FAILOVER, replica=rep.id, dead=dead,
                      in_flight=len(entries), queued=len(queued),
                      chunking=engine.chunk_pending,
                      last_dispatch=(post.last_step if post else -1),
                      beat_age=(round(post.last_beat_age_s, 3)
                                if post else None))
            tel.metrics.counter(
                COUNTER_FAILOVERS,
                help="replicas drained after death or hang").inc()
        # remove BEFORE re-admission: the router must never route the
        # dead replica's own work back onto it
        self._remove_replica(rep)
        if self._seats is not None:
            next_build = self._seats.record_death(rep.id, self.now())
            if next_build is not None and tel is not None:
                tel.event(EVENT_QUARANTINE, replica=rep.id,
                          next_build=round(next_build, 6))
        # sweep the dead client's completion ledger: a crashing tick
        # commits its already-collected expiry/cancel completions
        # client-side before unwinding (ServeClient._finalize) — they
        # never came back through a tick() return, and the requests are
        # in neither the snapshot nor the queue, so this is their only
        # way into the fleet's results
        done: List[Completion] = [
            comp for rid, comp in rep.client.completions.items()
            if rid not in self.completions]
        for comp in done:
            self.completions[comp.request_id] = comp
            if self._journal is not None:
                self._journal.retire(comp)
        promoted_early = False
        if not self._replicas:
            # sole-replica fleet: with no survivor to replay onto,
            # promotion must come first or every request would fail —
            # the pinned failover→replay→promoted order applies to
            # fleets with survivors
            self._promote()
            promoted_early = True
        for req, toks in entries:
            done.extend(self._readmit(req, toks))
        for req in queued:
            done.extend(self._readmit(req, None))
        if not promoted_early:
            self._promote()
        self._rebuild_monitor()
        self.failover_s_total += time.perf_counter() - t0
        return done

    def _readmit(self, req: Request,
                 toks: Optional[List[int]]) -> List[Completion]:
        """PR 3 replay re-admission of one displaced request: prompt +
        already-emitted tokens re-feed through a survivor's prefill, so
        its token stream continues at the same ``fold_in`` step —
        deadline, arrival time and any first-token stamp ride the
        request object unchanged.

        Containment armed (``max_request_failovers``), the request's
        implication count gates the path: at the budget it retires
        ``failed`` instead of consuming another replica; at
        ``probation_after`` it queues for a solo probation run. A
        *transient* refusal (every survivor QueueFull) parks the
        request for retry on later ticks — only a permanent misfit
        (outgrew the replay window, undeclared tenant/adapter) still
        fails it here."""
        tel = self._tel
        if toks is not None:
            req.replay_tokens = list(toks)
            if tel is not None:
                tel.event("recovery.replay", id=req.id,
                          replayed_tokens=len(toks))
        budget = self._cfg.max_request_failovers
        if budget is not None and req.crash_implications >= budget:
            return self._retire_poison(req)
        if (budget is not None
                and req.crash_implications >= self._cfg.probation_after):
            self._probation.append(req)
            if tel is not None:
                tel.event(EVENT_PROBATION, id=req.id, phase="queued",
                          implications=req.crash_implications)
            return []
        fed = req.prompt_len + len(req.replay_tokens or ())
        survivors = self._replicas
        if survivors:
            if fed <= survivors[0].client.engine.max_replay_len:
                try:
                    self._admit(req)
                except QueueFull as exc:
                    # transiently full, not unseatable: park for
                    # bounded re-admission (deadline still enforced,
                    # _pump_parked) instead of instant failure
                    log_suppressed("fleet.readmit", exc,
                                   f"request {req.id} refused by every "
                                   "survivor; parked for retry")
                    self._park(req)
                    return []
                except ValueError as exc:
                    log_suppressed("fleet.readmit", exc,
                                   f"request {req.id} unseatable after "
                                   "failover; retiring as failed")
                else:
                    self._count_readmitted()
                    return []
        elif self._seats is not None:
            # degraded: no survivor YET, but quarantine backoff will
            # rebuild one — park rather than insta-fail (the fit check
            # happens against the rebuilt replica at pump time)
            self._park(req)
            return []
        # outgrew the replay window / permanently unseatable / no
        # survivor and no rebuild coming: the request fails with the
        # tokens it already has — the fleet keeps serving everything
        # else
        return [self._fail_request(req)]

    def _count_readmitted(self) -> None:
        self.readmitted += 1
        if self._tel is not None:
            self._tel.metrics.counter(
                COUNTER_READMITTED,
                help="requests re-admitted to surviving "
                     "replicas after a failover").inc()

    def _fail_request(self, req: Request) -> Completion:
        from ray_lightning_tpu.reliability.supervisor import \
            failed_completion
        self.readmit_failed += 1
        comp = failed_completion(req, req.replay_tokens or ())
        comp.finish_time = self.now()
        self.completions[comp.request_id] = comp
        if self._journal is not None:
            self._journal.retire(comp)
        return comp

    def _retire_poison(self, req: Request) -> List[Completion]:
        """The request spent its failover budget: retire it ``failed``
        with its partial tokens instead of feeding it another replica."""
        self.poison_failed += 1
        tel = self._tel
        if tel is not None:
            tel.event(EVENT_POISON_FAILED, id=req.id,
                      implications=req.crash_implications,
                      tokens=len(req.replay_tokens or ()))
            tel.metrics.counter(
                COUNTER_POISON_FAILED,
                help="requests retired failed at their failover "
                     "budget (suspected poison)").inc()
        return [self._fail_request(req)]

    def _park(self, req: Request) -> None:
        self._parked.append(req)
        if self._tel is not None:
            self._tel.event(EVENT_READMIT_PARKED, id=req.id,
                            parked=len(self._parked))

    def _pump_parked(self, done: List[Completion]) -> None:
        """Retry every parked failover re-admission: deadline expiries
        retire ``timeout`` with their partial tokens (the client-side
        expiry contract), fits re-admit through the router, still-full
        stays parked for the next tick."""
        if not self._parked:
            return
        still: List[Request] = []
        now = self.now()
        for req in self._parked:
            if req.deadline is not None and now >= req.deadline:
                comp = Completion(
                    request_id=req.id, prompt=list(req.prompt),
                    tokens=list(req.replay_tokens or []),
                    finish_reason=FINISH_TIMEOUT,
                    arrival_time=req.arrival_time,
                    first_token_time=req.first_token_time,
                    finish_time=now,
                    prefix_hit_tokens=req.prefix_hit_tokens,
                    tenant=req.tenant, adapter=req.adapter)
                self.completions[comp.request_id] = comp
                if self._journal is not None:
                    self._journal.retire(comp)
                done.append(comp)
                continue
            survivors = self._replicas
            if not survivors:
                still.append(req)
                continue
            fed = req.prompt_len + len(req.replay_tokens or ())
            if fed > survivors[0].client.engine.max_replay_len:
                done.append(self._fail_request(req))
                continue
            try:
                self._admit(req)
            except QueueFull:
                still.append(req)
            except ValueError as exc:
                log_suppressed("fleet.readmit", exc,
                               f"parked request {req.id} permanently "
                               "unseatable; retiring as failed")
                done.append(self._fail_request(req))
            else:
                self._count_readmitted()
        self._parked = still

    def _pump_probation(self, done: List[Completion]) -> None:
        """Drive the probation lane: a retired suspect's clean run
        resets its implication count and releases the reserved
        replica; the next suspect seats solo once the reservation is
        idle. Reserving waits for a second admitting replica (unless
        the fleet's target IS one) so regular traffic keeps a lane."""
        obj = self._probation_obj
        if obj is not None:
            comp = self.completions.get(obj.id)
            if comp is None:
                return  # suspect still running solo
            # clean run: the "poison" evidence didn't reproduce —
            # exonerate (the implication-vs-proof caveat in
            # docs/reliability.md)
            obj.crash_implications = 0
            rep_id, self._probation_rep = self._probation_rep, None
            self._probation_obj = None
            if self._tel is not None:
                self._tel.event(EVENT_PROBATION_CLEARED, id=obj.id,
                                replica=rep_id,
                                finish_reason=comp.finish_reason)
        if not self._probation:
            return
        if self._probation_rep is None:
            admitting = sorted(
                (r for r in self._replicas if r.admitting),
                key=lambda r: r.id)
            if not admitting:
                return
            if len(admitting) < 2 and self._target_replicas > 1:
                return  # a second replica is coming; keep traffic moving
            self._probation_rep = admitting[0].id
        rep = next((r for r in self._replicas
                    if r.id == self._probation_rep), None)
        if rep is None or not rep.admitting:
            self._probation_rep = None
            return
        if rep.busy:
            return  # let the reserved replica drain its regular work
        req = self._probation[0]
        fed = req.prompt_len + len(req.replay_tokens or ())
        if fed > rep.client.engine.max_replay_len:
            self._probation.pop(0)
            done.append(self._fail_request(req))
            return
        try:
            rep.client.submit_request(req)
        except QueueFull:
            return  # idle replica refused (quota edge); retry next tick
        self._probation.pop(0)
        if self._journal is not None:
            # the probation seat is an admission too — a driver death
            # mid-probation must still replay the suspect
            self._journal.admit(req)
        self._probation_obj = req
        if self._tel is not None:
            self._tel.event(EVENT_PROBATION, id=req.id, phase="seated",
                            replica=rep.id,
                            implications=req.crash_implications)

    def _adopt_standby_or_build(self, *, cold_ok: bool,
                                grow: bool = False) \
            -> Tuple[Optional[_Replica], Optional[str]]:
        """The one add-a-replica sequence every growth path shares:
        take a warm standby (kicking the background refill behind it),
        else cold-build when ``cold_ok``. Returns ``(None, None)`` when
        the pool is empty and a cold build is not warranted. ``grow``
        marks deliberate new capacity (scale-out): quarantine armed, it
        seats a FRESH seat instead of filling a gated one."""
        client = self.standby.take() if self.standby is not None else None
        source = "standby" if client is not None else None
        if client is None:
            if not cold_ok:
                return None, None
            client = self._build_client()
            source = "cold"
        elif self._engine_kwargs.get("max_resident_adapters"):
            # a warm standby was built with the kwargs as of pool-fill
            # time; hot adapter churn since must be replayed onto it
            # BEFORE it serves — a stale bank would refuse re-admitted
            # adapter-bound requests as UnknownAdapter. (Cold builds
            # read the current kwargs and need nothing.) Loading every
            # wanted adapter unconditionally also repairs overwrites:
            # a resident name reuses its index, a slice write is cheap.
            want = dict(self._engine_kwargs.get("adapters") or {})
            for name in list(client.engine.resident_adapters):
                if name not in want:
                    client.unload_adapter(name)
            for name, tree in want.items():
                client.load_adapter(name, tree)
        rep = self._adopt(client)
        if self._seats is not None:
            self._seats.occupy(rep.id, self.now(), grow=grow)
        if self.standby is not None:
            self.standby.refill_async(self._build_client)
        return rep, source

    def _remove_replica(self, rep: _Replica) -> None:
        """The one remove-a-replica sequence failover and scale-in
        share: out of the routing set, affinity/EWMA state dropped,
        engine released."""
        self._replicas.remove(rep)
        self.router.forget(rep.id)
        try:
            rep.client.shutdown()
        except Exception as exc:  # noqa: BLE001 — teardown is best-effort
            log_suppressed("fleet.teardown", exc,
                           f"replica {rep.id} shutdown failed")

    def _promote(self) -> None:
        """Restore capacity after a failover: a warm standby when the
        pool has one (refilled in the background afterwards — spawn
        cost stays off the critical path), a cold build only when the
        fleet would otherwise sit below ``min_replicas``. When the pool
        is empty (a refill still building, or no pool at all), the
        tick-time catch-up (:meth:`tick`) restores toward
        ``_target_replicas`` on the next round — warm if a standby
        landed by then, cold otherwise — so a failover never leaves
        the fleet short forever."""
        if (self._seats is not None
                and not self._seats.allow_build(self.now())):
            # every empty seat is quarantined: the rebuild waits for
            # its backoff (tick-time catch-up performs it), even below
            # min_replicas — that's what degraded mode is for
            return
        rep, source = self._adopt_standby_or_build(
            cold_ok=len(self._replicas) < self._cfg.min_replicas)
        if rep is None:
            return
        if self._tel is not None:
            self._tel.event(EVENT_REPLICA_PROMOTED, replica=rep.id,
                            source=source,
                            replicas_live=len(self._replicas))

    # ------------------------------------------------------- autoscaler
    def _autoscale(self) -> None:
        cfg = self._cfg
        admitting = [r for r in self._replicas if r.admitting]
        total_q = sum(len(r.client.scheduler) for r in self._replicas)
        pressured = (
            total_q > cfg.scale_out_queue_depth * max(1, len(admitting))
            or (cfg.ttft_slo is not None and self._ttft_ewma is not None
                and self._ttft_ewma > cfg.ttft_slo))
        if pressured:
            self._pressure_ticks += 1
            self._idle_ticks = 0
        elif total_q == 0:
            self._idle_ticks += 1
            self._pressure_ticks = 0
        else:
            self._pressure_ticks = 0
            self._idle_ticks = 0
        if (self._pressure_ticks >= cfg.hysteresis
                and len(self._replicas) < cfg.max_replicas):
            self._scale_out()
            self._pressure_ticks = 0
        elif (self._idle_ticks >= cfg.hysteresis
                and len(admitting) > cfg.min_replicas):
            self._drain_one(admitting)
            self._idle_ticks = 0
        for rep in [r for r in self._replicas if r.draining]:
            if not rep.busy:
                self._retire_replica(rep)

    def _scale_out(self) -> None:
        rep, source = self._adopt_standby_or_build(cold_ok=True,
                                                   grow=True)
        self.scale_outs += 1
        self._target_replicas = len(self._replicas)
        self._rebuild_monitor()
        if self._tel is not None:
            self._tel.event(EVENT_SCALE_OUT, replica=rep.id,
                            source=source,
                            replicas_live=len(self._replicas))

    def _drain_one(self, admitting: List[_Replica]) -> None:
        """Scale-in is a drain, never a kill: the newest admitting
        replica stops taking requests; its in-flight work retires
        normally and only then is it shut down."""
        candidates = [r for r in admitting
                      if r.id != self._probation_rep] or admitting
        rep = max(candidates, key=lambda r: r.id)
        rep.draining = True
        if self._tel is not None:
            self._tel.event(EVENT_REPLICA_DRAINING, replica=rep.id,
                            in_flight=rep.client.engine.active_count,
                            queued=len(rep.client.scheduler))

    def _retire_replica(self, rep: _Replica) -> None:
        self._remove_replica(rep)
        if self._seats is not None:
            # a deliberate drain is not a death: the seat retires clean
            self._seats.vacate(rep.id)
        self.scale_ins += 1
        self._target_replicas = len(self._replicas)
        self._rebuild_monitor()
        if self._tel is not None:
            self._tel.event(EVENT_SCALE_IN, replica=rep.id,
                            replicas_live=len(self._replicas))

    # ---------------------------------------------------------- driving
    def _busy(self) -> bool:
        return (any(rep.busy for rep in self._replicas)
                or bool(self._parked) or bool(self._probation)
                or self._probation_obj is not None)

    def run_until_idle(self, max_ticks: int = 100_000) \
            -> Dict[int, Completion]:
        """Tick until every replica's queue and slots drain."""
        ticks = 0
        while self._busy():
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"fleet loop did not drain in {max_ticks} ticks")
        return dict(self.completions)

    def serve_trace(self, trace: Sequence[Tuple[float, dict]],
                    max_ticks: int = 100_000) -> Dict[int, Completion]:
        """Replay a scripted arrival trace fleet-wide — the same
        contract as :meth:`ServeClient.serve_trace`: entries the whole
        fleet refuses are SHED as ``finish_reason="rejected"``
        completions (with the aggregated occupancy context logged),
        never aborted."""
        tel = self._tel
        pending = sorted(trace, key=lambda item: item[0])
        idx = 0
        ticks = 0
        while idx < len(pending) or self._busy():
            now = self.now()
            while idx < len(pending) and pending[idx][0] <= now:
                kwargs = pending[idx][1]
                try:
                    self.submit(**kwargs)
                except (QueueFull, ValueError) as exc:
                    rid = self._next_id
                    self._next_id += 1
                    self.completions[rid] = Completion(
                        request_id=rid,
                        prompt=[int(t) for t in kwargs.get("prompt", [])],
                        tokens=[], finish_reason=FINISH_REJECTED,
                        arrival_time=now, finish_time=now,
                        tenant=kwargs.get("tenant") or DEFAULT_TENANT,
                        adapter=kwargs.get("adapter"))
                    if tel is not None:
                        tel.event(EVENT_SHED, id=rid,
                                  why=type(exc).__name__,
                                  context=str(exc))
                        tel.metrics.counter(
                            COUNTER_SHED,
                            help="requests shed fleet-wide at admission"
                        ).inc()
                idx += 1
            if idx < len(pending) and not self._busy():
                # idle gap before the next arrival: fast-forward (tick
                # mode) / yield (wall mode), and re-stamp the watchdog —
                # idle time is not silence, nobody dispatches while
                # there is nothing to do
                if self._clock is None:
                    self._ticks = max(self._ticks,
                                      math.ceil(pending[idx][0]))
                else:
                    time.sleep(  # tl-lint: allow-sleep — wall-clock mode's idle yield; tick mode (clock=None) never sleeps
                        min(1e-3, max(0.0, pending[idx][0] - now)))
                self._monitor.start()
                # mirror the restamp into the carried beat state, or a
                # later monitor rebuild would seed pre-gap beats and
                # declare everyone silent across the idle skip
                t = self.now()
                for rep in self._replicas:
                    if rep.last_beat is not None:
                        rep.last_beat = t
                continue
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"fleet trace did not drain in {max_ticks} ticks")
        return dict(self.completions)

    # ------------------------------------------------------ observability
    #: internal per-replica gauge prefix -> fleet-merged suffix form
    _REPLICA_GAUGE_RE = re.compile(r"^replica(\d+)_(serve_.+)$")

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Fleet-merged metrics view: the registry's internal
        ``replica<id>_serve_*`` gauge keying (which exists to stop
        per-replica gauges clobbering each other last-writer-wins) is
        renamed to the seat-suffixed operator form —
        ``serve_queue_depth_r0``, ``serve_slot_occupancy_r1``, … —
        alongside the untouched ``serve_fleet_*`` aggregates and every
        other metric. Same shape on both backends (process-backend
        replica gauges are forwarded into the same registry under the
        same prefix). ``{}`` when the fleet was built disarmed."""
        if self._tel is None:
            return {}
        out: Dict[str, Any] = {}
        for name, value in self._tel.metrics.snapshot().items():
            m = self._REPLICA_GAUGE_RE.match(name)
            out[f"{m.group(2)}_r{m.group(1)}" if m else name] = value
        return out

    def request_traces(self) -> Dict[int, Any]:
        """Assembled per-request traces for this fleet run — see
        :meth:`Telemetry.request_traces`. ``{}`` when disarmed."""
        if self._tel is None:
            return {}
        return self._tel.request_traces()

    def export_fleet_trace(self, path: str) -> str:
        """Stitch every replica's spans (in-process seat-tagged, or
        shipped over the process backend's ``MSG_SPAN`` leg) together
        with the per-request latency segments into ONE multi-track
        Chrome trace (``pid`` = replica seat, ``tid`` = KV slot) and
        atomically publish it at ``path``. Byte-identical across
        identical runs under the tick clock. Raises ``RuntimeError``
        when the fleet was built with ``telemetry=None`` — there is
        nothing to export, and silently writing an empty file would
        mask a mis-armed run."""
        if self._tel is None:
            raise RuntimeError(
                "export_fleet_trace on a disarmed fleet: pass "
                "telemetry= at construction to record a trace")
        from ray_lightning_tpu.obs.tracing import export_fleet_chrome_trace
        return export_fleet_chrome_trace(path, self._tel)

    # ---------------------------------------------------------- teardown
    def shutdown(self) -> None:
        """Release every replica's engine (KV pool/arena + prefix-cache
        refs), the warm standby pool, and the router. Idempotent; the
        fleet is unusable afterwards."""
        for rep in self._replicas:
            try:
                rep.client.shutdown()
            except Exception as exc:  # noqa: BLE001 — teardown is best-effort
                log_suppressed("fleet.teardown", exc,
                               f"replica {rep.id} shutdown failed")
        self._replicas = []
        if self.standby is not None:
            self.standby.shutdown()
        self.router.shutdown()
        self._monitor = None
        journal = self._journal
        if journal is not None:
            self._journal = None
            journal.shutdown()
