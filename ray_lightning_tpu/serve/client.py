"""Synchronous driver loop: engine + scheduler + clock.

:class:`ServeClient` is the single-threaded event loop the tests, the
example, and the bench all drive: submit requests (immediately or from an
arrival trace), then tick — each tick expires deadlines, asks the
scheduler for the next dispatch (prefill / step / idle), runs it, and
stamps completion timing.

Two clock modes:

- **tick clock** (default, ``clock=None``): time = number of engine
  dispatches so far. Fully deterministic — arrival traces expressed in
  ticks replay bit-identically, which is what the serving smoke tests
  pin ("request 3 arrives after the 5th engine dispatch, mid-flight").
- **wall clock** (``clock=time.perf_counter`` or any callable): real
  latencies for the bench; arrival times are seconds from ``run`` start.
"""
from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ray_lightning_tpu.serve.engine import ServeEngine
from ray_lightning_tpu.serve.request import (Completion, FINISH_REJECTED,
                                             FINISH_TIMEOUT, Request)
from ray_lightning_tpu.serve.scheduler import (ACTION_CHUNK, ACTION_PREFILL,
                                               ACTION_STEP, FifoScheduler,
                                               QueueFull, SchedulerConfig)


class ServeClient:
    """Synchronous continuous-batching front-end.

    ``ServeClient(model, params, num_slots=8, prefill_len=64)`` builds the
    engine and a FIFO scheduler; ``submit()`` returns a request id,
    ``run_until_idle()`` drives everything to completion, and
    ``serve_trace([(t, {...}), ...])`` replays a scripted arrival trace
    (requests join mid-flight whenever ``t`` falls between dispatches).
    """

    def __init__(self, model, params, *, num_slots: int = 8,
                 prefill_batch: Optional[int] = None,
                 prefill_len: int = 64, steps_per_dispatch: int = 1,
                 scheduler_config: Optional[SchedulerConfig] = None,
                 seed: int = 0,
                 clock: Optional[Callable[[], float]] = None,
                 clock_epoch: Optional[float] = None,
                 retry_policy=None, telemetry=None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = False,
                 kv_dtype: Optional[str] = None,
                 page_native: bool = False,
                 attention_kernel: Optional[str] = None,
                 weight_dtype: Optional[str] = None,
                 weight_group_size: Optional[int] = None,
                 draft_model=None, draft_params=None,
                 spec_k: Optional[int] = None,
                 draft_weight_dtype: Optional[str] = None):
        engine_kwargs = dict(
            num_slots=num_slots, prefill_batch=prefill_batch,
            prefill_len=prefill_len,
            steps_per_dispatch=steps_per_dispatch, seed=seed,
            telemetry=telemetry, page_size=page_size,
            num_pages=num_pages, prefill_chunk=prefill_chunk,
            prefix_cache=prefix_cache, kv_dtype=kv_dtype,
            page_native=page_native, attention_kernel=attention_kernel,
            weight_dtype=weight_dtype,
            weight_group_size=weight_group_size,
            draft_model=draft_model, draft_params=draft_params,
            spec_k=spec_k, draft_weight_dtype=draft_weight_dtype)
        if retry_policy is not None:
            # supervised engine: dispatch crashes rebuild + replay under
            # the policy instead of unwinding through the client loop;
            # exhausted requests retire as finish_reason="failed"
            from ray_lightning_tpu.reliability import ServeSupervisor
            self.engine = ServeSupervisor(model, params,
                                          policy=retry_policy,
                                          **engine_kwargs)
        else:
            self.engine = ServeEngine(model, params, **engine_kwargs)
        self.scheduler = FifoScheduler(scheduler_config)
        self._clock = clock
        # clock_epoch pins t=0 to an external origin instead of this
        # client's first now() call — how a ReplicaFleet keeps every
        # replica (including ones promoted mid-run) on ONE shared
        # timeline, so deadlines and TTFT stamps survive failover
        self._t0: Optional[float] = clock_epoch
        self._ops = 0  # engine dispatches so far = the tick clock
        self._next_id = 0
        self._seen_rebuilds = 0  # supervised: recovery TTFT sweep
        self.completions: Dict[int, Completion] = {}
        # telemetry is off by default: every armed emission below sits
        # behind `if tel is not None` — the disarmed loop pays one
        # attribute read + None check per tick, nothing else
        self._tel = telemetry
        self.num_slots = num_slots
        # name prefix for this client's occupancy gauges
        # (serve_queue_depth / serve_slot_occupancy / serve_pages_free /
        # serve_page_occupancy). "" for a standalone client keeps the
        # historical names; a ReplicaFleet stamps each replica's client
        # with a stable "replica<id>_" prefix so per-replica gauges
        # stop clobbering each other in the shared name-keyed registry
        # (docs/observability.md). Counters and histograms stay
        # unprefixed — they aggregate correctly across writers.
        self.gauge_prefix = ""

    # ------------------------------------------------------------ clock
    @property
    def ops(self) -> int:
        return self._ops

    def now(self) -> float:
        if self._clock is None:
            return float(self._ops)
        if self._t0 is None:
            self._t0 = self._clock()
        return self._clock() - self._t0

    # ----------------------------------------------------------- submit
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0, top_k: Optional[int] = None,
               eos_id: Optional[int] = None, seed: Optional[int] = None,
               deadline: Optional[float] = None) -> int:
        """Validate + enqueue one request; returns its id. Raises
        ``ValueError`` for requests that can never fit the compiled
        shapes and :class:`~...scheduler.QueueFull` at max queue depth."""
        req = Request(id=self._next_id, prompt=prompt,
                      max_new_tokens=max_new_tokens,
                      temperature=temperature, top_k=top_k, eos_id=eos_id,
                      seed=seed, deadline=deadline)
        rid = self.submit_request(req)
        self._next_id += 1
        return rid

    def submit_request(self, req: Request) -> int:
        """Validate + enqueue an externally built :class:`Request` — the
        router seat: a :class:`~ray_lightning_tpu.serve.fleet.ReplicaFleet`
        owns request ids fleet-wide and re-admits a dead replica's
        requests here, so arrival/deadline/first-token stamps (and
        ``replay_tokens``) must ride the request object untouched:
        ``arrival_time`` is only stamped when the request has never been
        admitted anywhere."""
        self.engine.validate(req)
        now = self.now()
        self.scheduler.submit(req, now)
        if req.arrival_time is None:
            req.arrival_time = now
        tel = self._tel
        if tel is not None:
            tel.event("serve.submit", id=req.id,
                      prompt_len=req.prompt_len,
                      max_new_tokens=req.max_new_tokens, t=now)
            tel.metrics.counter(
                "serve_requests_total",
                help="requests accepted by admission control").inc()
            tel.metrics.gauge(
                self.gauge_prefix + "serve_queue_depth",
                help="requests waiting in the scheduler queue"
            ).set(len(self.scheduler))
        return req.id

    def _stamp_first_token(self, req: Request, t: float) -> None:
        """First-token bookkeeping shared by every stamping path
        (batched admit, final chunk, post-recovery sweep)."""
        req.first_token_time = t
        if self._tel is not None:
            self._tel.event("serve.first_token", id=req.id,
                            ttft=t - req.arrival_time)

    def shutdown(self) -> None:
        """Release the engine's KV pool/arena (and prefix-cache page
        refs) — a retired client stops pinning device memory. Forwarded
        through a supervising wrapper when ``retry_policy`` is set."""
        self.engine.shutdown()

    # ------------------------------------------------------------- loop
    def tick(self) -> List[Completion]:
        """One scheduling decision + engine dispatch. Returns completions
        retired by this tick (including deadline expirations)."""
        now = self.now()
        done: List[Completion] = []
        # queued requests past deadline never touch the accelerator — but
        # a failover re-admission waiting here already streamed tokens on
        # its dead replica (replay_tokens) and keeps them, plus its
        # original first-token stamp (the PR 3 partial-tokens contract)
        for req in self.scheduler.expire(now):
            done.append(Completion(
                request_id=req.id, prompt=list(req.prompt),
                tokens=list(req.replay_tokens or []),
                finish_reason=FINISH_TIMEOUT,
                arrival_time=req.arrival_time,
                first_token_time=req.first_token_time,
                prefix_hit_tokens=req.prefix_hit_tokens))
        # in-flight requests past deadline free their slot mid-decode
        for req in list(self.engine.active_requests.values()):
            if req.deadline is not None and now >= req.deadline:
                comp = self.engine.cancel(req.id)
                if comp is not None:
                    done.append(comp)
        action, reqs = self.scheduler.next_action(self.engine)
        if action == ACTION_PREFILL:
            # defer (don't crash on) requests whose seed collides with an
            # in-flight sample stream — the pool would refuse them at
            # acquire; they rejoin the queue head and clear once the
            # conflicting request retires. Intra-batch duplicates keep
            # their first arrival, so at least one request always admits.
            seen = {r.seed for r in self.engine.active_requests.values()}
            admit: List[Request] = []
            deferred: List[Request] = []
            for req in reqs:
                (deferred if req.seed in seen else admit).append(req)
                seen.add(req.seed)
            if deferred:
                self.scheduler.requeue_front(deferred)
            if admit:
                tel = self._tel
                if tel is not None:
                    for req in admit:
                        tel.event("serve.admit", id=req.id,
                                  queue_wait=now - req.arrival_time)
                try:
                    done.extend(self.engine.prefill(admit))
                except Exception:
                    # a crashed dispatch must not strand the popped
                    # batch: a crash in the ADMISSION loop rolled its
                    # slots back (atomic), leaving the batch in neither
                    # snapshot_in_flight() nor the queue — a
                    # whole-replica failover (ReplicaFleet) would
                    # silently lose it. A crash in the jitted dispatch
                    # AFTER admission leaves the batch in pool.active
                    # instead, where the snapshot covers it — requeuing
                    # those too would re-admit every request twice. The
                    # engine's admission atomicity makes active
                    # membership the exact discriminator. The
                    # expiry/cancel completions this tick already
                    # collected must also be committed before the
                    # unwind discards `done` (those requests left the
                    # scheduler AND the engine — nothing else can ever
                    # retire them). (Requeue may land ahead of
                    # seed-deferred batch siblings — those were
                    # colliding anyway.)
                    seated = {r.id
                              for r in self.engine.active_requests.values()}
                    self.scheduler.requeue_front(
                        [r for r in admit if r.id not in seated])
                    self._finalize(done)
                    raise
                self._ops += 1  # count the dispatch before stamping TTFT
                t_first = self.now()
                chunking = getattr(self.engine, "chunk_pending_ids",
                                   frozenset())
                for req in admit:
                    if req.id in chunking:
                        # chunk-routed: still prefilling, no first token
                        # yet — stamped by _dispatch_chunk on its final
                        # chunk
                        continue
                    if req.first_token_time is not None:
                        # failover re-admission of a request that had
                        # already streamed tokens on its dead replica:
                        # its first token happened THERE — re-stamping
                        # would corrupt TTFT across the fleet's shared
                        # clock
                        continue
                    self._stamp_first_token(req, t_first)
            else:
                # every popped request was seed-deferred: the tick must
                # still advance the engine — the conflicting request may
                # itself be chunk-prefilling (holding a slot with nothing
                # decoding: livelock otherwise) — but under the SAME
                # chunk/decode alternation bound as any other dispatch,
                # so a persistent deferral can't starve in-flight decode;
                # the substitute action falls through to the shared
                # dispatch chain below
                action = self.scheduler.drain_action(self.engine)
        try:
            if action == ACTION_CHUNK:
                self._dispatch_chunk(done)
            elif action == ACTION_STEP:
                done.extend(self.engine.step())
                self._ops += 1
            elif action != ACTION_PREFILL:
                # idle: advance the tick clock so tick-mode traces
                # progress
                self._ops += 1
        except Exception:
            # same contract as the prefill unwind above: completions
            # already collected this tick must not vanish with the crash
            self._finalize(done)
            raise
        rebuilds = getattr(self.engine, "rebuilds", 0)
        if rebuilds != self._seen_rebuilds:
            # a recovery may drain chunk prefills internally (prefix
            # replay waves): a request it ACTIVATED got its first token
            # inside the recovery, where _dispatch_chunk never saw the
            # chunk_activated handoff — stamp TTFT now, not at retire
            self._seen_rebuilds = rebuilds
            t = self.now()
            # skip requests the recovery RE-QUEUED mid-chunk (non-prefix
            # replay): they have no token yet — _dispatch_chunk stamps
            # them on their final chunk
            chunking = getattr(self.engine, "chunk_pending_ids",
                               frozenset())
            for req in self.engine.active_requests.values():
                if req.first_token_time is None and req.id not in chunking:
                    self._stamp_first_token(req, t)
        self._finalize(done)
        return done

    def _finalize(self, done: List[Completion]) -> None:
        """Stamp finish times, record completions, and (armed) emit the
        retirement telemetry. Runs on the normal tick exit AND on a
        crashed dispatch's unwind: completions collected earlier in the
        tick (deadline expiries, mid-decode cancels) already left the
        scheduler and the engine, so discarding them with the stack
        would lose those requests forever — no failover can re-admit
        what neither the snapshot nor the queue contains."""
        t_done = self.now()
        for comp in done:
            comp.finish_time = t_done
            if comp.first_token_time is None and comp.tokens:
                # finished at its own prefill, before the post-dispatch
                # stamping loop ran for it
                comp.first_token_time = t_done
            self.completions[comp.request_id] = comp
        tel = self._tel
        if tel is not None:
            self._record_retirements(tel, done)

    def _dispatch_chunk(self, done: List[Completion]) -> None:
        """One chunk-prefill dispatch, plus TTFT stamping for the request
        (if any) whose final chunk just activated its decode row — the
        engine hands it over directly (``chunk_activated``), no scan of
        ``active_requests``."""
        done.extend(self.engine.prefill_chunk_step())
        self._ops += 1
        req = self.engine.chunk_activated
        if req is not None and req.first_token_time is None:
            self._stamp_first_token(req, self.now())

    def _record_retirements(self, tel, done: List[Completion]) -> None:
        """Armed-path bookkeeping for one tick: retire events + the
        vLLM-style request lifecycle metrics (TTFT / TPOT / end-to-end
        latency histograms, queue-depth and slot-occupancy gauges). All
        times are in the client's clock units (ticks or seconds)."""
        m = tel.metrics
        for comp in done:
            tel.event("serve.retire", id=comp.request_id,
                      finish_reason=comp.finish_reason,
                      tokens=len(comp.tokens))
            m.counter("serve_completions_total",
                      help="requests retired, any finish reason").inc()
            m.counter(f"serve_finish_{comp.finish_reason}_total",
                      help=f"requests retired with finish_reason="
                      f"{comp.finish_reason}").inc()
            m.counter("serve_tokens_total",
                      help="generated tokens across all requests"
                      ).inc(len(comp.tokens))
            if comp.latency is not None:
                m.histogram("serve_latency",
                            help="arrival -> completion (client clock "
                            "units)").observe(comp.latency)
            ttft = comp.time_to_first_token
            if ttft is not None:
                m.histogram("serve_ttft",
                            help="arrival -> first token (client clock "
                            "units)").observe(ttft)
                if (len(comp.tokens) > 1
                        and comp.finish_time is not None):
                    m.histogram(
                        "serve_tpot",
                        help="per-token decode time after the first "
                        "(client clock units)").observe(
                        (comp.finish_time - comp.first_token_time)
                        / (len(comp.tokens) - 1))
        m.gauge(self.gauge_prefix + "serve_queue_depth",
                help="requests waiting in the scheduler queue"
                ).set(len(self.scheduler))
        m.gauge(self.gauge_prefix + "serve_slot_occupancy",
                help="fraction of KV slots holding an in-flight request"
                ).set(self.engine.active_count / self.num_slots)
        pages_free = getattr(self.engine, "free_pages", None)
        if pages_free is not None:
            num_pages = self.engine.pool.num_pages
            m.gauge(self.gauge_prefix + "serve_pages_free",
                    help="free KV pages in the paged arena"
                    ).set(pages_free)
            m.gauge(self.gauge_prefix + "serve_page_occupancy",
                    help="fraction of arena pages held (slots + prefix "
                    "cache)").set(1.0 - pages_free / num_pages)

    def _engine_busy(self) -> bool:
        """Decode rows active OR prompts still streaming chunk prefill."""
        return bool(self.engine.active_count
                    or getattr(self.engine, "chunk_pending", 0))

    def run_until_idle(self, max_ticks: int = 100_000) \
            -> Dict[int, Completion]:
        """Tick until queue and slots drain; returns all completions."""
        ticks = 0
        while len(self.scheduler) or self._engine_busy():
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"serve loop did not drain in {max_ticks} ticks")
        return dict(self.completions)

    def serve_trace(self, trace: Sequence[Tuple[float, dict]],
                    max_ticks: int = 100_000) -> Dict[int, Completion]:
        """Replay a scripted arrival trace.

        ``trace`` is ``[(arrival_time, submit_kwargs), ...]`` in the
        client's clock units (ticks by default — deterministic; seconds
        under a wall clock). Requests are submitted the first tick at or
        after their arrival time, so later entries join mid-flight while
        earlier requests are still decoding. Returns ``{request_id:
        Completion}`` with ids assigned in trace order. An entry the
        admission layer refuses (queue at depth, prompt that can never
        fit) is SHED — recorded as a ``finish_reason="rejected"``
        completion — instead of aborting the replay and discarding every
        other request's work (overload sheds requests, not the server).
        """
        pending = sorted(trace, key=lambda item: item[0])
        idx = 0
        ticks = 0
        while (idx < len(pending) or len(self.scheduler)
               or self._engine_busy()):
            now = self.now()
            while idx < len(pending) and pending[idx][0] <= now:
                kwargs = pending[idx][1]
                try:
                    self.submit(**kwargs)
                except (QueueFull, ValueError) as exc:
                    rid = self._next_id
                    self._next_id += 1
                    self.completions[rid] = Completion(
                        request_id=rid,
                        prompt=[int(t) for t in kwargs.get("prompt", [])],
                        tokens=[], finish_reason=FINISH_REJECTED,
                        arrival_time=now, finish_time=now)
                    if self._tel is not None:
                        self._tel.event("serve.reject", id=rid,
                                        why=type(exc).__name__)
                        self._tel.metrics.counter(
                            "serve_rejected_total",
                            help="requests shed at admission").inc()
                idx += 1
            if (idx < len(pending) and not len(self.scheduler)
                    and not self._engine_busy()):
                # nothing in flight and the next arrival is in the
                # future: fast-forward the tick clock / yield the wall
                # clock instead of spinning
                if self._clock is None:
                    self._ops = max(self._ops,
                                    math.ceil(pending[idx][0]))
                else:
                    time.sleep(  # tl-lint: allow-sleep — wall-clock mode's idle yield; tick mode (clock=None) never sleeps
                        min(1e-3, max(0.0, pending[idx][0] - now)))
                continue
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"serve trace did not drain in {max_ticks} ticks")
        return dict(self.completions)
