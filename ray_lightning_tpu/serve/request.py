"""Request/Completion dataclasses for the continuous-batching engine.

A :class:`Request` is one user generation call: its prompt, a per-request
token budget, and per-request sampling params — each batch row of the
engine's step program carries its *own* temperature/top_k/eos/seed, so
heterogeneous requests share one compiled program. ``max_new_tokens`` is a
per-row countdown inside the engine step (not a static scan length like
one-shot :func:`~ray_lightning_tpu.models.generate.generate`): a row
retires the moment it hits eos or exhausts its budget, and its KV slot is
handed to the next queued request mid-flight.

A :class:`Completion` is the retired request: the generated tokens (eos
included when sampled), why it stopped, and the latency breakdown the
serving bench aggregates into p50/p99.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

#: the tenant class a request belongs to when none is named — engines
#: without tenancy configured only ever see this class, and a
#: TenantScheduler holding only this class is behaviorally identical
#: to the FIFO scheduler (see ray_lightning_tpu/serve/tenancy.py)
DEFAULT_TENANT = "default"

FINISH_EOS = "eos"            # sampled its eos id
FINISH_LENGTH = "length"      # exhausted max_new_tokens
FINISH_TIMEOUT = "timeout"    # deadline expired (queued or mid-decode)
FINISH_REJECTED = "rejected"  # shed at admission (trace replay only)
FINISH_FAILED = "failed"      # engine crash recovery exhausted its retries


class OccupancyError(RuntimeError):
    """Base for admission-control errors carrying occupancy context.

    Keyword context renders as a ``[k=v, ...]`` suffix on the message
    (None values omitted) and every key becomes an attribute, so
    shed-load callers can log actionable rejections instead of a bare
    "full" (:class:`~ray_lightning_tpu.serve.pages.SlotPoolFull`,
    :class:`~ray_lightning_tpu.serve.scheduler.QueueFull`)."""

    def __init__(self, message: str, **ctx):
        shown = [f"{k}={v}" for k, v in ctx.items() if v is not None]
        super().__init__(
            message + (f" [{', '.join(shown)}]" if shown else ""))
        for k, v in ctx.items():
            setattr(self, k, v)


@dataclasses.dataclass
class Request:
    """One generation request.

    ``seed`` defaults to the request id: the engine derives every sample
    key as ``fold_in(fold_in(engine_base, seed), step)``, so a request's
    token stream with ``temperature > 0`` is a pure function of
    ``(engine seed, request seed, step)`` — reproducible across arrival
    orders, slot assignments, and batch compositions. Distinct co-resident
    seeds are asserted at slot assignment (no key reuse across slots).

    ``deadline``: optional absolute clock value (in the driving client's
    clock units) after which the request is abandoned — dropped from the
    queue, or cancelled mid-decode with the tokens produced so far.
    """
    id: int
    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    eos_id: Optional[int] = None
    seed: Optional[int] = None
    deadline: Optional[float] = None
    # tenant class (multi-tenant scheduling, serve/tenancy.py): which
    # per-class queue/quota/fair-share bucket this request rides.
    # Scheduling is ordering-only — the tenant never changes the
    # request's tokens — and the class assignment rides the request
    # object through crash replay and fleet failover re-admission.
    tenant: str = DEFAULT_TENANT
    # timing bookkeeping, stamped by the driving client (clock units)
    arrival_time: Optional[float] = None
    first_token_time: Optional[float] = None
    # crash-recovery replay (set by ServeSupervisor, never by submit):
    # tokens this request had already emitted before its engine died.
    # Prefill re-feeds prompt + replay_tokens and resumes the sampling
    # key stream at step len(replay_tokens) — replay-exact, see
    # docs/reliability.md.
    replay_tokens: Optional[List[int]] = None
    # stamped by a paged engine at admission: how many prompt tokens'
    # KV was adopted from the shared-prefix cache instead of computed
    # (0 = no hit / dense engine); surfaced on the Completion
    prefix_hit_tokens: int = 0
    # failure-containment ledger (serve/containment.py): how many
    # replica deaths this request has been co-batched with. Incremented
    # by the fleet on every failover that displaces the request (and by
    # ServeSupervisor on engine-level recoveries) and, like
    # ``replay_tokens``, rides the request object through snapshot and
    # re-admission. At ``FleetConfig.max_request_failovers`` the request
    # retires ``failed`` with its partial tokens instead of consuming
    # another replica; a clean probation run resets it to 0. This is an
    # IMPLICATION count, not proof of guilt — innocents co-batched with
    # a poison request are implicated too, which is exactly what the
    # probation path exists to sort out (docs/reliability.md).
    crash_implications: int = 0
    # LoRA adapter name (multi-adapter serving, serve/adapters.py):
    # which resident adapter's (A, B) pair this request's batch rows
    # gather inside the shared programs. None = the base model
    # (bit-identical to an unadapted engine). Like ``tenant``, the
    # binding rides the request object through crash replay and fleet
    # failover re-admission; a TenantClass.adapter default is resolved
    # at engine admission, not here.
    adapter: Optional[str] = None

    def __post_init__(self):
        self.prompt = [int(t) for t in self.prompt]
        if not self.prompt:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if not self.tenant or not isinstance(self.tenant, str):
            raise ValueError(
                f"tenant must be a non-empty string, got {self.tenant!r}")
        if self.adapter is not None and (
                not self.adapter or not isinstance(self.adapter, str)):
            raise ValueError(
                f"adapter must be a non-empty string or None, "
                f"got {self.adapter!r}")
        if self.seed is None:
            self.seed = self.id

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)


@dataclasses.dataclass
class Completion:
    """A retired request: output tokens + stop reason + latency stamps."""
    request_id: int
    prompt: List[int]
    tokens: List[int]               # generated tokens, eos included
    finish_reason: str              # FINISH_EOS | FINISH_LENGTH | FINISH_TIMEOUT
    arrival_time: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    # prompt tokens served from the shared-prefix KV cache (paged
    # engines with prefix_cache=True; 0 otherwise)
    prefix_hit_tokens: int = 0
    # the retiring request's tenant class (per-tenant obs + bench
    # aggregation key; DEFAULT_TENANT without tenancy configured)
    tenant: str = DEFAULT_TENANT
    # the adapter this request actually decoded under (after any
    # TenantClass.adapter default resolution; None = base model)
    adapter: Optional[str] = None

    @property
    def latency(self) -> Optional[float]:
        """Arrival → completion, in the driving client's clock units."""
        if self.arrival_time is None or self.finish_time is None:
            return None
        return self.finish_time - self.arrival_time

    @property
    def time_to_first_token(self) -> Optional[float]:
        if self.arrival_time is None or self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time
