"""Continuous-batching serving on top of the prefill/decode split.

See ``docs/serving.md`` for the architecture. Quick start::

    from ray_lightning_tpu.serve import ServeClient

    client = ServeClient(decode_model, params, num_slots=8,
                         prefill_len=64)
    rid = client.submit(prompt_tokens, max_new_tokens=32, eos_id=50256)
    out = client.run_until_idle()[rid]
    print(out.tokens, out.finish_reason)
"""
from ray_lightning_tpu.serve.adapters import (AdapterBankFull,
                                              AdapterRegistry,
                                              UnknownAdapter)
from ray_lightning_tpu.serve.client import ServeClient
from ray_lightning_tpu.serve.containment import SeatTable
from ray_lightning_tpu.serve.engine import (KVSlotPool, PendingDispatch,
                                            ServeEngine, SlotPoolFull)
from ray_lightning_tpu.serve.fleet import (FleetConfig, FleetDegraded,
                                           FleetSaturated, ReplicaFleet,
                                           Router, RouterConfig)
from ray_lightning_tpu.serve.journal import (Journal, JournalCorrupt,
                                             JournalState, read_journal)
from ray_lightning_tpu.serve.pages import PagePool, PrefixCache
from ray_lightning_tpu.serve.process_fleet import ProcessReplicaFleet
from ray_lightning_tpu.serve.request import (Completion, DEFAULT_TENANT,
                                             FINISH_EOS,
                                             FINISH_FAILED, FINISH_LENGTH,
                                             FINISH_REJECTED,
                                             FINISH_TIMEOUT, Request)
from ray_lightning_tpu.serve.scheduler import (FifoScheduler, QueueFull,
                                               SchedulerConfig)
from ray_lightning_tpu.serve.spec import SpecDecoder
from ray_lightning_tpu.serve.tenancy import (ClassQueueFull, TenantClass,
                                             TenantScheduler)

__all__ = [
    "ServeClient", "ServeEngine", "KVSlotPool", "PagePool", "PrefixCache",
    "PendingDispatch", "SlotPoolFull", "SpecDecoder", "Request",
    "Completion",
    "FifoScheduler", "QueueFull", "SchedulerConfig", "ReplicaFleet",
    "ProcessReplicaFleet",
    "Router", "RouterConfig", "FleetConfig", "FleetSaturated",
    "FleetDegraded", "SeatTable",
    "TenantClass", "TenantScheduler", "ClassQueueFull", "DEFAULT_TENANT",
    "AdapterRegistry", "AdapterBankFull", "UnknownAdapter",
    "Journal", "JournalCorrupt", "JournalState", "read_journal",
    "FINISH_EOS", "FINISH_FAILED", "FINISH_LENGTH", "FINISH_REJECTED",
    "FINISH_TIMEOUT",
]
