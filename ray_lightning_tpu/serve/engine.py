"""Continuous-batching serving engine: paged (or slot-pooled) KV cache +
fixed-shape compiled programs for all in-flight requests.

Iteration-level scheduling (Orca, OSDI '22) on XLA's terms: the engine
owns a fixed batch of **KV slots** (rows of the decode step program) and
a small set of pre-compiled fixed-shape programs, reusing the
prefill/decode split from :mod:`ray_lightning_tpu.models.generate`:

1. **prefill+inject** (``(B_pf, P)`` static shape): batch up to ``B_pf``
   waiting prompts, run the existing single-pass
   :func:`~ray_lightning_tpu.models.generate._prefill_impl` forward,
   sample each row's first token with its own key/params, and write each
   prefilled KV row into its assigned slot (dense path) or scatter its
   pages into the arena (paged path).
2. **step** (``(B, 1)`` static shape): ONE cached decode step for all B
   slots at their own ``kv_positions`` — the factored
   :func:`~ray_lightning_tpu.models.generate.decode_step` that
   ``generate()``'s ragged scan also runs, so engine decode cannot drift
   from one-shot decode. Each row samples with its request's own
   temperature/top_k/key, counts down its own ``max_new_tokens`` budget,
   and latches its own eos — finished rows retire *mid-flight* and their
   slots are handed to the next queued request without recompiling
   anything (all shapes static).
3. **chunk prefill** (``(1, C)`` static shape, paged engines with
   ``prefill_chunk`` set): ONE ``C``-token piece of one prompt, written
   at that request's current offset with chunk-causal masking over its
   already-filled pages — long prompts stream in chunk-sized dispatches
   the scheduler interleaves with decode, so a 4k-token prompt stalls
   in-flight decodes by one chunk, not one prompt (Sarathi-style chunked
   prefill). Prefix-cache hits enter here too: adopted pages skip
   straight to the first un-cached offset.
4. **spec round** (``draft_model=`` engines,
   :mod:`ray_lightning_tpu.serve.spec`): ``step()`` swaps the decode
   step for ONE fused program per dispatch — k+1 cheap draft-model
   steps plus a widened ``(B, k+1)`` target verify whose accept rule
   commits 1..k+1 tokens per row (greedy token-identical to the plain
   step by construction; rejected drafts roll back by position
   decrement). ``steps_per_dispatch`` scans spec ROUNDS here.

``kv_dtype="int8"`` additionally stores KV at rest as absmax int8 +
f32 scales (per-page-per-head paged, per-position-per-head dense) —
dequantized on the way into every program and re-quantized on the way
out, fused into the dispatch; compute stays at ``cfg.dtype``.
``weight_dtype="int8"|"int4"`` applies the same storage-only contract
to the *parameters* (:mod:`ray_lightning_tpu.models.quant`):
per-output-channel int8 or group-wise packed int4 codes + f32 scales,
dequantized ONCE at each program's entry — the at-rest param stream
(what every decode pass reads) shrinks to the codes.
``page_native=True`` (paged engines) swaps the step/verify programs'
dense-view gather/scatter for attention that reads and writes K/V
straight through the page table inside the model — dispatch bytes
scale with *occupied* pages, token-identical to the dense-gather path
(see ``docs/serving.md``). ``attention_kernel="pallas"`` further swaps
that read side for the hand-tiled pallas paged-attention kernel
(``models/pallas_attention.py``): page loads, int8 dequant, masked
blockwise scores, exact tiled softmax and f32 output accumulation all
fused in one kernel — interpret mode off-TPU, identical tokens.

KV layout is split from the programs (the refactor ROADMAP item 1 calls
healthy): the *logical* per-slot ``(max_seq_len, H, D)`` KV each program
computes against is materialized from physical storage at dispatch time.
Dense storage (``page_size=None``) IS the logical layout — one
``(num_slots, max_seq_len, H, D)`` pool, the original static-slot
design. Paged storage (:class:`~ray_lightning_tpu.serve.pages.PagePool`)
is a ``(num_pages, page_size, H, D)`` arena per KV leaf plus a per-slot
page table; the programs stay the same fixed-shape jits — the page
table is just a gather index applied on the way in and a scatter index
on the way out, fused into the dispatch. See ``docs/serving.md`` for
the memory/bandwidth trade; the old "pallas kernel endgame" there is
landed as ``attention_kernel="pallas"``.

Inactive slots still flow through the step program (the batch is
static); they are masked out of sampling/bookkeeping and their parked
KV rewrite is idempotent (dense) or dropped by the scatter (paged), so
they cost FLOPs but never correctness. Keep ``num_slots`` near your
live-traffic working set — paged engines can afford a generous batch
because slots no longer reserve memory.

The step/spec dispatch additionally splits into an **async seat**
(``step_enqueue()`` → :class:`PendingDispatch` → ``step_sync()``):
the device carry chains dispatch-to-dispatch without touching the
host, so ``ServeClient(async_dispatch=True)`` overlaps all host work
with the in-flight dispatch — see the class docs and
``docs/serving.md#async-dispatch``.
"""
from __future__ import annotations

import dataclasses
import time
import warnings
from collections import deque
from dataclasses import dataclass
from functools import partial
from typing import Any, Deque, FrozenSet, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.models.generate import (_adapter_kw, _logits_only,
                                               _prefill_impl,
                                               decode_step,
                                               decode_step_paged,
                                               sample_logits_rows)
from ray_lightning_tpu.models.lora import (LoraConfig, adapter_bytes,
                                           install_adapter,
                                           install_lora_bank,
                                           zero_adapter)
from ray_lightning_tpu.models.quant import (DEFAULT_GROUP_SIZE,
                                            check_weight_dtype,
                                            materialize_for_program,
                                            param_bytes, quantize_params)
from ray_lightning_tpu.models.transformer import latch_eos
from ray_lightning_tpu.obs.spans import NULL_SPAN
from ray_lightning_tpu.reliability import faults
from ray_lightning_tpu.serve.adapters import (AdapterRegistry,
                                              UnknownAdapter)
from ray_lightning_tpu.serve.pages import (PagePool, PrefixCache,
                                           SlotPoolFull, check_kv_dtype,
                                           check_seed_free,
                                           dense_storage_commit,
                                           dense_storage_values, fold_rows,
                                           gather_pages, pick_donated,
                                           quantize_dense_cache,
                                           scatter_pages)
from ray_lightning_tpu.serve.spec import (SpecDecoder,
                                          _spec_page_native_donated,
                                          _spec_page_native_plain,
                                          _spec_paged_donated,
                                          _spec_paged_plain,
                                          _spec_rounds_donated,
                                          _spec_rounds_plain)
from ray_lightning_tpu.serve.request import (Completion, DEFAULT_TENANT,
                                             FINISH_EOS, FINISH_LENGTH,
                                             FINISH_TIMEOUT, Request)
from ray_lightning_tpu.serve.tenancy import resolve_tenant_classes

__all__ = ["ServeEngine", "KVSlotPool", "SlotPoolFull", "PendingDispatch"]


@dataclass
class PendingDispatch:
    """Deferred-sync handle for one enqueued step / spec-round dispatch.

    :meth:`ServeEngine.step_enqueue` returns one of these instead of
    blocking on the host copies: ``emitted``/``finished`` (and the spec
    accept ledgers) are still device arrays — futures under JAX's async
    dispatch — and ``carry`` is the device-side engine state
    (cur/pos/active/remaining/stepno) the NEXT enqueue chains on, so a
    second STEP dispatch can launch before this one's tokens ever touch
    the host. :meth:`ServeEngine.step_sync` materializes the handle:
    the host copy (THE blocking point), the retire loop, counters and
    telemetry. Handles must sync in enqueue order; an engine rebuild
    (crash recovery, fleet failover) DISCARDS outstanding handles — the
    synced frontier is the replay truth, an in-flight speculative
    dispatch is regenerated by replay, never committed twice
    (``docs/serving.md#async-dispatch``).
    """
    kind: str          # "step" | "spec"
    dispatch: int      # engine.steps at enqueue (1-based)
    rounds: int        # steps_per_dispatch scanned inside the program
    emitted: object    # (rounds, B) or (rounds, B, k+1) device array
    finished: object   # (rounds, B) device array
    carry: tuple       # (cur, pos, active, remaining, stepno) on device
    owner: object = None       # identity nonce of the issuing engine —
    #                            a rebuilt engine refuses foreign
    #                            handles even when dispatch indices
    #                            realign (e.g. both at 1)
    accepted: object = None    # spec only: (rounds, B) draft credits
    rejected: object = None    # spec only: (rounds, B) real divergences
    asynchronous: bool = True  # False: the sync step() round-trip
    enqueued_at: float = 0.0   # host perf_counter stamp (overlap metric)
    # client-clock enqueue stamp (ticks or seconds), set by an ARMED
    # ServeClient only — read back at step_sync to split decode time
    # from reconciliation in request traces (serve.retire `sync`)
    enqueued_tick: Optional[float] = None


# shared serve-program plumbing (one copy for engine + spec programs)
_fold_rows = fold_rows
_pick = pick_donated


def _advance_rows(model, last, cur, pos, active, remaining, temp, top_k,
                  eos, keys, stepno):
    """Per-row sampling + bookkeeping for one decode step's logits — the
    ONE copy of the sample/latch/budget math, shared by the dense-view
    step core and the page-native step body so the two storage paths
    cannot drift.

    Per-row semantics (matching the ragged decode scan): ``cur`` is the
    token sampled last step, ``pos`` its absolute position. Inactive rows
    run the same math (static shapes) but their state is frozen: emitted
    is masked to −1 and ``pos``/``stepno`` don't advance.
    """
    step_keys = _fold_rows(keys, stepno)
    nxt = sample_logits_rows(last, step_keys, temp, top_k)
    # per-row eos (−1 = disabled); done=False — finished rows leave the
    # batch instead of repeating eos, the pool hands their slot on
    _, eos_hit = latch_eos(nxt, jnp.zeros_like(active), eos)
    act_i = active.astype(jnp.int32)
    remaining = remaining - act_i
    finished = active & (eos_hit | (remaining <= 0))
    emitted = jnp.where(active, nxt, -1)
    max_pos = model.cfg.max_seq_len - 1
    cur = jnp.where(active[:, None], nxt[:, None], cur)
    pos = jnp.minimum(pos + act_i[:, None], max_pos)
    stepno = stepno + act_i
    active = active & ~finished
    return (cur, pos, active, remaining, stepno, emitted, finished)


def _engine_step_core(model, params, cache, cur, pos, active, remaining,
                      temp, top_k, eos, keys, stepno, adapter_ids=None):
    """One decode step for all B slots. Pure function of the engine state
    arrays; (B, 1) model step shared with generate() via decode_step,
    row bookkeeping shared with the page-native path via
    :func:`_advance_rows`. Re-writing a frozen row's K/V at its frozen
    position is idempotent. ``adapter_ids`` (B,) routes each row through
    its own resident LoRA pair (−1 = base model); ``None`` on engines
    without an adapter bank — the model never sees the kwarg, so
    unadapted programs are byte-for-byte the pre-LoRA ones.
    """
    last, cache = decode_step(model, params, cache, cur, pos, adapter_ids)
    (cur, pos, active, remaining, stepno, emitted, finished) = \
        _advance_rows(model, last, cur, pos, active, remaining, temp,
                      top_k, eos, keys, stepno)
    return (cache, cur, pos, active, remaining, stepno, emitted, finished)


def _engine_step_impl(model, params, cache, cur, pos, active, remaining,
                      temp, top_k, eos, keys, stepno, adapter_ids=None,
                      *, steps):
    """``steps`` decode steps in ONE dispatch (multi-step scheduling).

    Token-granularity dispatch pays the fixed per-call overhead once per
    token — measured at ~108 ms on the axon tunnel vs a ~0.6 ms device
    step (docs/performance.md), which would hand the fused one-shot scan
    an unbeatable advantage. Scanning ``steps`` iterations of the SAME
    per-row step inside the program amortizes the dispatch 1/steps while
    keeping the math identical (rows that finish mid-block park
    idempotently; emitted is −1-masked per sub-step). The trade is
    scheduling granularity: joins/retires happen every ``steps`` tokens.

    ``cache`` may be int8 dense storage (a ``(q, s)`` tuple): the body
    runs on the dequantized compute-dtype view and the result re-commits
    through the same storage — both fused into this one dispatch.

    Returns the carried state plus ``emitted``/``finished`` stacked
    ``(steps, B)`` — the host replays sub-steps in order.
    """
    # weight-quantized params dequantize ONCE per dispatch, here at the
    # program top (outside the step scan) — storage-only, same contract
    # as the int8 KV storage below
    params = materialize_for_program(params, model.cfg)
    storage = cache
    cache = dense_storage_values(model, storage)

    def body(carry, _):
        cache, cur, pos, active, remaining, stepno = carry
        (cache, cur, pos, active, remaining, stepno, emitted,
         finished) = _engine_step_core(
            model, params, cache, cur, pos, active, remaining, temp,
            top_k, eos, keys, stepno, adapter_ids)
        return ((cache, cur, pos, active, remaining, stepno),
                (emitted, finished))

    (cache, cur, pos, active, remaining, stepno), (emitted, finished) = \
        jax.lax.scan(body, (cache, cur, pos, active, remaining, stepno),
                     None, length=steps)
    cache = dense_storage_commit(model, storage, cache)
    return (cache, cur, pos, active, remaining, stepno, emitted, finished)


def _prefill_inject_impl(model, params, pool_cache, prompts, lengths,
                         slots, valid, keys, temp, top_k, startno,
                         adapter_ids=None):
    """Batched prompt fill + first-token sample + KV injection (dense).

    Runs the standard single-pass prefill at the engine's fixed
    ``(B_pf, P)`` shape (rows left-aligned, ``lengths`` raggedness — the
    same contract as generate()'s ragged prefill), samples each row's
    first token with its own key/params, then writes each valid row's
    whole KV row into its assigned pool slot. Invalid (padding) rows are
    computed but written nowhere — the pool row is read back and kept, so
    one compiled program covers every fill level of the prefill batch.

    ``startno`` (B,) is each row's sampling-step offset: 0 for a fresh
    request (fold_in(key, 0), the original behavior), k for a
    crash-recovery replay whose row re-feeds the prompt + k emitted
    tokens — the sampled token then continues the request's key stream
    exactly where the dead engine left it (same array shapes, so replay
    reuses the compiled program).

    ``pool_cache`` may be int8 dense storage (a ``(q, s)`` tuple): the
    injection runs on the dequantized view and re-commits, fused.
    """
    storage = pool_cache
    pool_cache = dense_storage_values(model, storage)
    B_pf = prompts.shape[0]
    pf_cache, last = _prefill_impl(model, params, prompts, lengths,
                                   adapter_ids)
    first_keys = _fold_rows(keys, startno)
    first = sample_logits_rows(last, first_keys, temp, top_k)

    # cache leaves: cached_key/cached_value are (B, L, H, D) unrolled or
    # (n_layers, B, L, H, D) scanned — the batch axis follows the layout.
    # Sub-4d leaves (cache_index scalars/stacks) are shared-index
    # bookkeeping the per-row kv_positions path never reads: keep pool's.
    batch_axis = 1 if model.cfg.scan_layers else 0
    num_slots = next(leaf.shape[batch_axis]
                     for leaf in jax.tree_util.tree_leaves(pool_cache)
                     if leaf.ndim >= 4)

    # slot_map[s] = the pf row writing pool slot s, or -1 to keep the
    # pool row. Invalid (padding) rows scatter to a dropped out-of-range
    # index; valid slots are unique (pool invariant), so one gather +
    # select per leaf does the whole injection — no per-row update chain.
    scatter_idx = jnp.where(valid, slots, num_slots)
    slot_map = jnp.full((num_slots,), -1, jnp.int32).at[scatter_idx].set(
        jnp.arange(B_pf, dtype=jnp.int32), mode="drop")
    keep = slot_map < 0

    def inject(pool, pf):
        if pool.ndim < 4:
            return pool
        gathered = jnp.take(pf, jnp.maximum(slot_map, 0), axis=batch_axis)
        mask_shape = [1] * pool.ndim
        mask_shape[batch_axis] = num_slots
        return jnp.where(keep.reshape(mask_shape), pool, gathered)

    pool_cache = jax.tree_util.tree_map(inject, pool_cache, pf_cache)
    return dense_storage_commit(model, storage, pool_cache), first


# --------------------------------------------------------------- paged
# the arena gather/scatter (and its int8 dequant/quant handling) lives
# with the allocator in serve/pages.py — these aliases keep the program
# impls below readable
_gather_pages = gather_pages
_scatter_pages = scatter_pages


def _paged_step_impl(model, params, arena, page_table, cur, pos, active,
                     remaining, temp, top_k, eos, keys, stepno,
                     adapter_ids=None, *, steps):
    """The decode step program on paged storage: gather the dense view,
    run the IDENTICAL multi-step body (:func:`_engine_step_impl` — token
    identity with the dense engine is by construction), scatter mapped
    pages back. One dispatch, fused by XLA; the view is dispatch-scoped
    scratch, the arena is the only persistent KV allocation.

    Only rows active at dispatch entry scatter back. Inactive rows run
    the same math (static shapes) and "write" their frozen K/V at a
    stale position — dead storage on the dense path, but here the slot's
    pages may belong to a request still streaming chunk prefill (a
    mid-chunking slot is allocated but not yet decoding), so their
    writes must be dropped, not parked. Rows that retire mid-block
    started active and still scatter: their post-retirement sub-step
    rewrites are frozen-idempotent.
    """
    view = _gather_pages(model, arena, page_table)
    write_pt = jnp.where(active[:, None], page_table, -1)
    (view, cur, pos, active, remaining, stepno, emitted, finished) = \
        _engine_step_impl(model, params, view, cur, pos, active,
                          remaining, temp, top_k, eos, keys, stepno,
                          adapter_ids, steps=steps)
    arena = _scatter_pages(model, arena, view, write_pt)
    return (arena, cur, pos, active, remaining, stepno, emitted, finished)


def _prefill_inject_paged_impl(model, params, arena, prompts, lengths,
                               inject_pt, keys, temp, top_k, startno,
                               adapter_ids=None):
    """Paged sibling of :func:`_prefill_inject_impl`: same prefill
    forward and first-token sample, but the injection is a page scatter —
    ``inject_pt`` (B_pf, pages_per_slot) maps each prefill row's pages to
    arena pages (−1 = drop: padding rows, and the unmapped tail of a
    short request's slot). The prefill cache covers the full
    ``max_seq_len`` row (positions ≥ P are zeros), so every mapped page
    is overwritten — stale KV from the pages' previous tenants never
    leaks (the paged analog of the dense whole-row inject)."""
    pf_cache, last = _prefill_impl(model, params, prompts, lengths,
                                   adapter_ids)
    first_keys = _fold_rows(keys, startno)
    first = sample_logits_rows(last, first_keys, temp, top_k)
    # the prefill cache rows are already the dense per-slot view
    # (B_pf, max_seq_len, …) = (S, pp * page_size, …)
    arena = _scatter_pages(model, arena, pf_cache, inject_pt)
    return arena, first


def _chunk_prefill_impl(model, params, arena, row_pages, tokens, offset,
                        valid_len, keys, temp, top_k, startno,
                        adapter_ids=None):
    """One ``(1, C)`` chunk of one prompt, at absolute ``offset``.

    Gathers the request's dense row view from its pages, points the
    shared ``cache_index`` bookkeeping at ``offset`` (the block-write
    mode of ``_decode_cache`` then writes this chunk's K/V there and
    masks keys past ``offset + q`` per intra-chunk query — chunk-causal
    attention over everything already filled, including adopted prefix
    pages), runs the forward, scatters mapped pages back, and samples a
    candidate first token from the logits at ``valid_len - 1``. The host
    uses that sample only on the final chunk; earlier chunks discard it
    (one program covers every chunk). ``startno`` continues a replayed
    request's key stream, exactly as the batched prefill does.
    """
    params = materialize_for_program(params, model.cfg)
    pt = row_pages[None, :]
    view = _gather_pages(model, arena, pt)
    view = jax.tree_util.tree_map(
        lambda leaf: (jnp.full(leaf.shape, offset, leaf.dtype)
                      if leaf.ndim < 4 else leaf), view)
    C = tokens.shape[1]
    positions = offset + jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)
    outputs, updated = model.apply(
        {"params": params, "cache": view}, tokens, positions=positions,
        deterministic=True, mutable=["cache"], **_adapter_kw(adapter_ids))
    logits = _logits_only(outputs)                      # (1, C, V)
    last = jnp.take_along_axis(
        logits, jnp.reshape(valid_len - 1, (1, 1, 1)).astype(jnp.int32),
        axis=1)[:, 0]
    first = sample_logits_rows(last, _fold_rows(keys, startno), temp,
                               top_k)
    arena = _scatter_pages(model, arena, updated["cache"], pt)
    return arena, first


def _page_native_step_impl(model, params, arena, page_table, cur, pos,
                           active, remaining, temp, top_k, eos, keys,
                           stepno, adapter_ids=None, *, steps):
    """The decode step program in **page-native** mode: K/V reads and
    writes go straight through the page table inside the model's
    attention (``decode_step_paged`` →
    ``MultiHeadAttention._page_native_attention``) — the dense
    ``(num_slots, max_seq_len)`` view of :func:`_paged_step_impl` never
    materializes, so the bytes a dispatch touches scale with *occupied*
    pages instead of ``num_slots x max_seq_len``. Row bookkeeping is
    the shared :func:`_advance_rows`, so sampling/eos/budget math is
    identical to the dense-view paths by construction.

    ``page_table`` arrives write-masked (inactive rows' entries −1):
    their parked writes drop inside the attention scatter and their
    reads clamp to page 0 (finite junk the position mask never lets
    into an ACTIVE row — inactive rows' logits are discarded by the
    emitted mask). Rows that retire mid-block keep their mapped entries
    and re-write frozen K/V idempotently, exactly like the dense paths.
    """
    params = materialize_for_program(params, model.cfg)

    def body(carry, _):
        arena, cur, pos, active, remaining, stepno = carry
        last, arena = decode_step_paged(model, params, arena, cur, pos,
                                        page_table, adapter_ids)
        (cur, pos, active, remaining, stepno, emitted, finished) = \
            _advance_rows(model, last, cur, pos, active, remaining,
                          temp, top_k, eos, keys, stepno)
        return ((arena, cur, pos, active, remaining, stepno),
                (emitted, finished))

    (arena, cur, pos, active, remaining, stepno), (emitted, finished) = \
        jax.lax.scan(body, (arena, cur, pos, active, remaining, stepno),
                     None, length=steps)
    return (arena, cur, pos, active, remaining, stepno, emitted, finished)


_engine_step_donated = partial(
    jax.jit, static_argnames=("model", "steps"), donate_argnums=(2,))(
        _engine_step_impl)
_engine_step_plain = partial(
    jax.jit, static_argnames=("model", "steps"))(_engine_step_impl)
_prefill_inject_donated = partial(
    jax.jit, static_argnames=("model",), donate_argnums=(2,))(
        _prefill_inject_impl)
_prefill_inject_plain = partial(
    jax.jit, static_argnames=("model",))(_prefill_inject_impl)
_paged_step_donated = partial(
    jax.jit, static_argnames=("model", "steps"), donate_argnums=(2,))(
        _paged_step_impl)
_paged_step_plain = partial(
    jax.jit, static_argnames=("model", "steps"))(_paged_step_impl)
_prefill_paged_donated = partial(
    jax.jit, static_argnames=("model",), donate_argnums=(2,))(
        _prefill_inject_paged_impl)
_prefill_paged_plain = partial(
    jax.jit, static_argnames=("model",))(_prefill_inject_paged_impl)
_chunk_prefill_donated = partial(
    jax.jit, static_argnames=("model",), donate_argnums=(2,))(
        _chunk_prefill_impl)
_chunk_prefill_plain = partial(
    jax.jit, static_argnames=("model",))(_chunk_prefill_impl)
_page_native_step_donated = partial(
    jax.jit, static_argnames=("model", "steps"), donate_argnums=(2,))(
        _page_native_step_impl)
_page_native_step_plain = partial(
    jax.jit, static_argnames=("model", "steps"))(_page_native_step_impl)




class KVSlotPool:
    """Dense storage: owns the (B, max_seq_len) KV cache and the
    request → slot map (the original static-slot layout; the paged
    sibling is :class:`~ray_lightning_tpu.serve.pages.PagePool`).

    Slots are acquired at prefill injection and released on
    eos/max-token/timeout; lowest-index-first allocation keeps traces
    deterministic. The pool also enforces the no-key-reuse invariant: two
    co-resident slots may never carry the same sampling seed (their
    per-step keys would collide stream-for-stream).
    """

    def __init__(self, model, num_slots: int,
                 kv_dtype: Optional[str] = None):
        self.num_slots = num_slots
        self.kv_dtype = kv_dtype
        cache = model.init(
            jax.random.PRNGKey(0), jnp.zeros((num_slots, 1), jnp.int32),
            positions=jnp.zeros((num_slots, 1), jnp.int32))["cache"]
        if check_kv_dtype(kv_dtype):
            # int8 storage: the (q, s) tuple the dense programs
            # dequantize/re-quantize inside each dispatch
            cache = quantize_dense_cache(model, cache)
        self.cache = cache
        self._free: List[int] = list(range(num_slots))
        self._requests: Dict[int, Request] = {}  # slot -> request

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def active(self) -> Dict[int, Request]:
        return dict(self._requests)

    def slot_of(self, request_id: int) -> Optional[int]:
        for slot, req in self._requests.items():
            if req.id == request_id:
                return slot
        return None

    def acquire(self, request: Request) -> int:
        if not self._free:
            raise SlotPoolFull(
                f"all {self.num_slots} KV slots in use",
                slots_free=0, active=len(self._requests))
        check_seed_free(self._requests, request)
        slot = self._free.pop(0)
        self._requests[slot] = request
        return slot

    def release(self, slot: int) -> Request:
        req = self._requests.pop(slot)
        self._free.append(slot)
        self._free.sort()
        return req


@dataclass
class _ChunkState:
    """One mid-chunking prompt: the slot is held, pages are allocated,
    and ``fed[next_off:]`` still has to stream through the chunk
    program."""
    request: Request
    slot: int
    fed: List[int]       # prompt + replayed tokens
    next_off: int        # first position not yet written (admission
    #                      seeds it past any adopted prefix pages)


class ServeEngine:
    """In-flight batching over a fixed slot batch with dense or paged KV.

    ``model`` must be a decode-mode LM (``cfg.decode=True``; for serving
    throughput build it ``scan_layers=False`` and convert training weights
    with ``unstack_scan_params`` — see ``docs/performance.md``). The
    engine compiles its programs on first use and never again:
    prefill+inject at ``(prefill_batch, prefill_len)``, the decode step
    at ``(num_slots, 1)``, and (chunked engines) the chunk prefill at
    ``(1, prefill_chunk)``.

    Paged mode (``page_size=``): KV lives in a
    ``(num_pages, page_size, H, D)`` arena behind a per-slot page table
    (:class:`~ray_lightning_tpu.serve.pages.PagePool`) — short requests
    hold pages for their own prompt+budget instead of a ``max_seq_len``
    row, so concurrency (``num_slots``) decouples from KV memory
    (``num_pages``). ``prefill_chunk=`` streams long prompts in
    chunk-sized dispatches the scheduler interleaves with decode;
    ``prefix_cache=True`` adds refcounted read-only reuse of
    shared-prompt KV pages (requires ``prefill_chunk`` — adopted chains
    resume at the first un-cached offset, which is a chunk dispatch).

    Speculative decoding (``draft_model=``, ``draft_params=``,
    ``spec_k=4``): ``step()`` runs fused spec rounds instead of decode
    steps — see :mod:`ray_lightning_tpu.serve.spec` and
    ``docs/serving.md``. ``kv_dtype="int8"`` halves at-rest KV bytes
    on either storage layout (``docs/serving.md#int8-kv-storage``);
    ``weight_dtype=`` / ``draft_weight_dtype=`` quantize the weights
    (``weight_group_size=`` sizes the int4 groups) and
    ``page_native=True`` drops the paged dispatch's dense-view
    round-trip — all four compose, with each other and with spec.
    ``attention_kernel="pallas"`` (requires ``page_native=True``) runs
    the page-native read side as one hand-tiled pallas kernel per
    layer instead of blockwise XLA — same tokens, fewer temporaries.
    ``matmul_kernel="pallas"`` (requires ``weight_dtype=`` or
    ``draft_weight_dtype=``, and unrolled layers) streams the
    quantized weight codes straight into a fused dequant-matmul
    kernel per projection (``models/pallas_matmul.py``) instead of
    materializing a dequantized parameter tree once per dispatch —
    the per-dispatch param byte stream drops to the codes+scales
    floor ``param_bytes()`` accounts, and tokens stay identical to
    the materialized path (interpret-mode bitwise on the CPU tier).

    Drive it with :class:`~ray_lightning_tpu.serve.client.ServeClient`
    (scheduler + admission control + clocks) or directly:
    ``prefill([reqs])`` to start requests (chunk-routed prompts advance
    via ``prefill_chunk_step()``), ``step()`` to advance every in-flight
    request; each returns newly finished :class:`Completion`\\ s.
    """

    def __init__(self, model, params, *, num_slots: int = 8,
                 prefill_batch: Optional[int] = None,
                 prefill_len: int = 64, steps_per_dispatch: int = 1,
                 seed: int = 0, telemetry=None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = False,
                 kv_dtype: Optional[str] = None,
                 page_native: bool = False,
                 attention_kernel: Optional[str] = None,
                 weight_dtype: Optional[str] = None,
                 weight_group_size: Optional[int] = None,
                 matmul_kernel: Optional[str] = None,
                 draft_model=None, draft_params=None,
                 spec_k: Optional[int] = None,
                 draft_weight_dtype: Optional[str] = None,
                 tenant_classes=None,
                 adapters=None,
                 max_resident_adapters: Optional[int] = None,
                 lora_rank: Optional[int] = None):
        cfg = model.cfg
        if not cfg.decode:
            raise ValueError(
                "ServeEngine needs a decode-mode model: rebuild the "
                "config with decode=True (params are compatible)")
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if page_native and page_size is None:
            raise ValueError(
                "page_native=True is a paged-KV mode (attention reads "
                "K/V through the page table): pass page_size= too")
        # attention_kernel selects the page-native read-side kernel
        # (models/pallas_attention.py): None inherits the model config
        # (default "xla"); "pallas" swaps in the hand-tiled paged
        # kernel. A config mismatch rebuilds the model with the
        # requested kernel — the cfg field is the single source of
        # truth the attention dispatches on, so supervisor rebuilds and
        # fleet replicas (which re-enter this ctor with the same
        # kwargs) select the identical programs.
        if attention_kernel not in (None, "xla", "pallas"):
            raise ValueError(
                f"attention_kernel must be None, 'xla' or 'pallas', "
                f"got {attention_kernel!r}")
        if attention_kernel is not None \
                and attention_kernel != cfg.attention_kernel:
            model = model.clone(cfg=dataclasses.replace(
                cfg, attention_kernel=attention_kernel))
            cfg = model.cfg
        self.attention_kernel = cfg.attention_kernel
        if self.attention_kernel == "pallas" and not page_native:
            raise ValueError(
                "attention_kernel='pallas' is the page-native paged-"
                "attention kernel (K/V stream through the page table "
                "inside one pallas_call): pass page_native=True (and "
                "page_size=) too")
        check_weight_dtype(weight_dtype)  # unknown dtypes refused here
        check_weight_dtype(draft_weight_dtype)
        # matmul_kernel selects the weight-quantized matmul path
        # (models/pallas_matmul.py), the attention_kernel pattern: None
        # inherits the model config (default "xla" = materialized
        # per-dispatch dequant); "pallas" streams the QTensor codes
        # into a fused dequant-matmul kernel — no dense dequantized
        # weight arena exists in any program. A config mismatch clones
        # the model (and the draft model) with the requested kernel, so
        # supervisor rebuilds and fleet replicas — which re-enter this
        # ctor with the same kwargs — re-select identical programs.
        if matmul_kernel not in (None, "xla", "pallas"):
            raise ValueError(
                f"matmul_kernel must be None, 'xla' or 'pallas', got "
                f"{matmul_kernel!r}")
        if matmul_kernel is not None \
                and matmul_kernel != cfg.matmul_kernel:
            model = model.clone(cfg=dataclasses.replace(
                cfg, matmul_kernel=matmul_kernel))
            cfg = model.cfg
        self.matmul_kernel = cfg.matmul_kernel
        if self.matmul_kernel == "pallas":
            if weight_dtype is None and draft_weight_dtype is None:
                raise ValueError(
                    "matmul_kernel='pallas' is the fused dequant-matmul "
                    "kernel for QUANTIZED weights (QTensor leaves): "
                    "pass weight_dtype='int8'|'int4' (or "
                    "draft_weight_dtype=) too, or drop the kernel — a "
                    "silently inert knob is a bug magnet")
            if cfg.scan_layers and weight_dtype is not None:
                raise ValueError(
                    "matmul_kernel='pallas' needs scan_layers=False: "
                    "nn.scan slices every param leaf along the layer "
                    "axis and QTensor scales have no such axis (serving "
                    "wants unrolled layers anyway — unstack_scan_params "
                    "the weights; docs/performance.md decode section)")
        if draft_model is not None \
                and draft_model.cfg.matmul_kernel != cfg.matmul_kernel:
            draft_model = draft_model.clone(cfg=dataclasses.replace(
                draft_model.cfg, matmul_kernel=cfg.matmul_kernel))
        if draft_model is not None and draft_weight_dtype is not None \
                and cfg.matmul_kernel == "pallas" \
                and draft_model.cfg.scan_layers:
            raise ValueError(
                "matmul_kernel='pallas' needs the draft model unrolled "
                "too (scan_layers=False) when its weights are "
                "quantized")
        if weight_group_size is not None \
                and "int4" not in (weight_dtype, draft_weight_dtype):
            raise ValueError(
                "weight_group_size is an int4 grouping option: pass "
                "weight_dtype='int4' (or draft_weight_dtype='int4') to "
                "enable it — int8 scales are per-output-channel")
        if prefill_len > cfg.max_seq_len:
            raise ValueError(
                f"prefill_len ({prefill_len}) exceeds max_seq_len "
                f"({cfg.max_seq_len})")
        if steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got "
                f"{steps_per_dispatch}")
        if page_size is None and (num_pages is not None
                                  or prefill_chunk is not None
                                  or prefix_cache):
            raise ValueError(
                "num_pages / prefill_chunk / prefix_cache are paged-KV "
                "features: pass page_size= to enable the page arena")
        if prefill_chunk is not None:
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
            if prefill_chunk % page_size != 0:
                raise ValueError(
                    f"prefill_chunk ({prefill_chunk}) must be a multiple "
                    f"of page_size ({page_size})")
            if cfg.max_seq_len % prefill_chunk != 0:
                raise ValueError(
                    f"prefill_chunk ({prefill_chunk}) must divide "
                    f"max_seq_len ({cfg.max_seq_len}) so chunk offsets "
                    "can never overflow the sequence axis")
        if prefix_cache and prefill_chunk is None:
            raise ValueError(
                "prefix_cache=True needs prefill_chunk= too: an adopted "
                "prefix resumes prefill at its first un-cached offset, "
                "which is a chunk-program dispatch")
        if (spec_k is not None or draft_params is not None) \
                and draft_model is None:
            raise ValueError(
                "spec_k / draft_params are speculative-decoding options: "
                "pass draft_model= (a small decode-mode LM sharing the "
                "target's vocab and max_seq_len) to enable them")
        if draft_model is not None and draft_params is None:
            raise ValueError("draft_model needs draft_params too")
        if draft_weight_dtype is not None and draft_model is None:
            raise ValueError(
                "draft_weight_dtype is a speculative-decoding option: "
                "pass draft_model=/draft_params= to enable it")
        # multi-tenant scheduling (serve/tenancy.py): the engine keeps
        # the resolved class map so validate() refuses unknown tenants
        # and prefill() enforces per-class max_active_slots even for
        # direct (non-ServeClient) callers. The map rides engine_kwargs
        # through supervisor rebuilds and fleet replicas, so recovery
        # re-admission keeps every request's class enforceable.
        # Scheduling policy itself lives in the TenantScheduler — the
        # engine only enforces quotas, it never reorders anything.
        self.tenant_classes = (resolve_tenant_classes(tenant_classes)
                               if tenant_classes else None)
        # batched multi-LoRA serving (models/lora.py + serve/adapters.py):
        # max_resident_adapters= sizes a resident (N, ...) adapter bank
        # on every LoRA-target projection — the bank axis is part of the
        # compiled programs, so hot load/unload/eviction is a data write,
        # never a recompile, and rows bound to different adapters batch
        # in one dispatch. The model is cloned with the LoraConfig here
        # (the attention_kernel/matmul_kernel pattern): supervisor
        # rebuilds and fleet replicas re-enter this ctor with the same
        # kwargs and re-arm the identical bank.
        self.max_resident_adapters = max_resident_adapters
        self.lora_rank = lora_rank
        if max_resident_adapters is None:
            if adapters:
                raise ValueError(
                    "adapters= needs max_resident_adapters= too: the "
                    "bank's num_adapters axis is part of the compiled "
                    "programs and must be sized up front")
            if lora_rank is not None:
                raise ValueError(
                    "lora_rank is a multi-LoRA serving option: pass "
                    "max_resident_adapters= to arm the adapter bank")
        else:
            if max_resident_adapters < 1:
                raise ValueError(
                    f"max_resident_adapters must be >= 1, got "
                    f"{max_resident_adapters}")
            if lora_rank is None or lora_rank < 1:
                raise ValueError(
                    "multi-LoRA serving needs lora_rank >= 1 (the bank's "
                    f"low-rank dimension), got {lora_rank!r}")
            if adapters and len(adapters) > max_resident_adapters:
                raise ValueError(
                    f"{len(adapters)} initial adapters exceed "
                    f"max_resident_adapters={max_resident_adapters}")
            if cfg.scan_layers:
                raise ValueError(
                    "multi-LoRA serving needs scan_layers=False: the "
                    "bank graft walks unrolled layer scopes (serving "
                    "wants unrolled layers anyway — unstack_scan_params "
                    "the weights; docs/performance.md decode section)")
            lora_cfg = LoraConfig(rank=lora_rank,
                                  num_adapters=max_resident_adapters)
            if cfg.lora != lora_cfg:
                model = model.clone(
                    cfg=dataclasses.replace(cfg, lora=lora_cfg))
                cfg = model.cfg
        self.model = model
        # weight-only quantization (models/quant.py): storage-only —
        # the programs dequantize once per dispatch, compute stays at
        # cfg.dtype. Quantizing here (not at the call site) keeps
        # supervisor rebuilds deterministic: the raw params re-quantize
        # to bit-identical codes, so crash replay stays token-identical.
        self.weight_dtype = weight_dtype
        self._weights_quantized_events = []
        # weight_group_size feeds whichever models quantize as int4
        # (int8 is per-output-channel — quantize_params refuses a group)
        if weight_dtype is not None:
            params = self._quantize_weights(
                "target", params, weight_dtype,
                weight_group_size if weight_dtype == "int4" else None)
        if draft_weight_dtype is not None:
            draft_params = self._quantize_weights(
                "draft", draft_params, draft_weight_dtype,
                weight_group_size if draft_weight_dtype == "int4"
                else None)
        self.params = params
        # adapter bank graft AFTER weight quantization: the zero-filled
        # (N, ...) lora_A/lora_B banks ride next to the (possibly
        # QTensor) base kernels at full precision — quantize_params
        # skips lora_* leaves by name, and grafting here keeps them out
        # of the quantizer entirely. The LoRA delta therefore rides
        # OUTSIDE the quantized base matmul (pallas fused kernels
        # included), which is what makes the null-adapter row bitwise
        # the unadapted engine.
        self._registry: Optional[AdapterRegistry] = None
        self._adapter_ids: Optional[np.ndarray] = None
        self._adapter_of: Dict[int, str] = {}
        self._adapter_events: List[dict] = []
        if max_resident_adapters is not None:
            self.params = install_lora_bank(self.params, cfg.lora)
            self._registry = AdapterRegistry(
                max_resident_adapters,
                bytes_per_adapter=adapter_bytes(self.params))
            self._adapter_ids = np.full((num_slots,), -1, np.int32)
            for name, tree in dict(adapters or {}).items():
                index, _ = self._registry.admit(name)
                self.params = install_adapter(self.params, tree, index)
                self._adapter_events.append(
                    dict(adapter=name, index=index, evicted=None))
        self.num_slots = num_slots
        if prefill_batch is not None and prefill_batch < 1:
            raise ValueError(
                f"prefill_batch must be >= 1, got {prefill_batch}")
        self.prefill_batch = min(prefill_batch or num_slots, num_slots)
        if prefill_batch is not None and self.prefill_batch != prefill_batch:
            # the silent clamp bit people: a caller asking for a bigger
            # batch than the engine can inject deserves to know the
            # compiled shape they actually got
            warnings.warn(
                f"prefill_batch={prefill_batch} clamped to "
                f"{self.prefill_batch} (valid range 1..num_slots="
                f"{num_slots}); the prefill program compiles at the "
                "clamped shape", stacklevel=2)
            if telemetry is not None:
                telemetry.event("engine.config_clamped",
                                field="prefill_batch",
                                requested=prefill_batch,
                                effective=self.prefill_batch)
        self.prefill_len = prefill_len
        # >1 = multi-step scheduling: K decode steps per program dispatch
        # (amortizes the fixed per-call overhead; requests join/retire at
        # K-token granularity) — see _engine_step_impl
        self.steps_per_dispatch = steps_per_dispatch
        self.prefill_chunk = prefill_chunk
        # off by default; one attribute read + None check per dispatch
        # when disarmed (docs/observability.md)
        self._tel = telemetry
        # extra args splatted into every engine span — a ReplicaFleet
        # stamps {"seat": replica_id} here so the stitched fleet trace
        # (obs/tracing.py) can put each replica on its own pid track;
        # empty for a standalone engine (span args unchanged)
        self._span_extra: Dict[str, Any] = {}
        self.kv_dtype = kv_dtype
        check_kv_dtype(kv_dtype)
        self.paged = page_size is not None
        self.page_native = page_native
        if self.paged:
            self.pool = PagePool(model, num_slots, page_size,
                                 num_pages=num_pages, kv_dtype=kv_dtype)
        else:
            self.pool = KVSlotPool(model, num_slots, kv_dtype=kv_dtype)
        # speculative decoding: draft proposals verified k+1 tokens per
        # target dispatch (serve/spec.py); steps_per_dispatch scans spec
        # ROUNDS instead of single decode steps when armed
        if draft_model is not None:
            self.spec_k = spec_k if spec_k is not None else 4
            self.spec = SpecDecoder(draft_model, draft_params,
                                    num_slots=num_slots, k=self.spec_k,
                                    target_cfg=cfg)
        else:
            self.spec_k = None
            self.spec = None
        if prefix_cache:
            self.prefix = PrefixCache(self.pool)
        else:
            self.prefix = None
        self._chunk_queue: Deque[_ChunkState] = deque()
        # the request whose FINAL chunk the last prefill_chunk_step
        # dispatch activated into decode (None otherwise) — the driving
        # client stamps TTFT off this without scanning active_requests
        self.chunk_activated: Optional[Request] = None
        self._base_key = jax.random.PRNGKey(seed)

        B = num_slots
        self._cur = np.zeros((B, 1), np.int32)
        self._pos = np.zeros((B, 1), np.int32)
        self._active = np.zeros((B,), bool)
        self._remaining = np.zeros((B,), np.int32)
        self._temp = np.zeros((B,), np.float32)
        self._top_k = np.zeros((B,), np.int32)
        self._eos = np.full((B,), -1, np.int32)
        self._keys = np.zeros((B, 2), np.uint32)
        self._stepno = np.zeros((B,), np.int32)
        self._tokens: Dict[int, List[int]] = {}
        # deferred-carry seat (async dispatch): the numpy fields above
        # always hold the SYNCED frontier — the newest dispatch whose
        # tokens the host has seen. When a dispatch is enqueued but not
        # yet synced, its device-side outputs live here and the next
        # enqueue chains on them; step_sync catches the frontier up and
        # clears it. None = fully synced, barrier dispatches allowed.
        self._carry: Optional[tuple] = None
        # highest step-dispatch index step_sync has committed — the
        # in-order guard: handles sync exactly once, in enqueue order,
        # and a rebuilt-away engine's handle (its index can't be the
        # fresh engine's next) fails loudly instead of corrupting
        self._synced_dispatch = 0
        # sync step()'s retry seat: a handle whose host copy failed
        # after its dispatch launched (step_sync left the engine
        # untouched, so the next step() retries the SAME sync instead
        # of wedging behind _require_synced with the handle lost)
        self._retry_sync: Optional[PendingDispatch] = None
        # identity nonce stamped into every handle: step_sync refuses a
        # handle another engine issued — the dispatch-index guard alone
        # has a realignment hole (a dead engine's dispatch-1 handle
        # matches a fresh engine's expected 1)
        self._engine_token = object()

        # counters for the bench / scheduler policy (steps counts
        # dispatches; decode_substeps counts target-model param-read
        # passes: decode token-steps, or spec rounds — one verify reads
        # the params once however many tokens it commits)
        self.steps = 0
        self.decode_substeps = 0
        self.prefills = 0
        self.chunk_dispatches = 0
        self.tokens_generated = 0
        # speculative-decoding accounting (all zero on non-spec engines)
        self.spec_rounds = 0
        self.spec_accepted_tokens = 0
        self.spec_rejected_tokens = 0
        self.spec_draft_steps = 0

        if telemetry is not None:
            for payload in self._weights_quantized_events:
                telemetry.event("engine.weights_quantized", **payload)
            telemetry.metrics.gauge(
                "serve_param_bytes",
                help="at-rest parameter bytes this engine streams per "
                "decode pass (target + draft; quantized codes + scales "
                "when weight_dtype is set)"
            ).set(param_bytes(self.params)
                  + (param_bytes(self.spec.params)
                     if self.spec is not None else 0))
            if self._registry is not None:
                for payload in self._adapter_events:
                    telemetry.event("engine.adapter_loaded", **payload)
                self._set_adapter_gauge()
        self._weights_quantized_events = []
        self._adapter_events = []

    def _quantize_weights(self, which: str, params, weight_dtype: str,
                          group_size: Optional[int]):
        """Quantize one model's params, recording the before/after byte
        accounting for the armed-telemetry event (emitted at the end of
        ``__init__`` — quantization must run before the telemetry handle
        is even assigned)."""
        before = param_bytes(params)
        quantized = quantize_params(params, weight_dtype,
                                    group_size=group_size)
        self._weights_quantized_events.append(dict(
            model=which, dtype=weight_dtype,
            group_size=(None if weight_dtype == "int8"
                        else group_size or DEFAULT_GROUP_SIZE),
            bytes_before=before, bytes_after=param_bytes(quantized)))
        return quantized

    # ----------------------------------------------------- multi-LoRA
    @property
    def resident_adapters(self) -> List[str]:
        """Resident adapter names, least-recently-bound first (the
        deterministic eviction order); empty without a bank."""
        return (self._registry.residents
                if self._registry is not None else [])

    def adapter_bank_bytes(self) -> int:
        """Exact at-rest device bytes of the full adapter bank
        (``capacity * per-adapter slice`` from
        :func:`~ray_lightning_tpu.models.lora.adapter_bytes`) — the
        bench's enforced accounting floor."""
        if self._registry is None:
            return 0
        return self._registry.capacity * self._registry.bytes_per_adapter

    def adapter_refcount(self, name: str) -> int:
        """In-flight rows currently pinned to ``name`` (0 when disarmed
        or not resident) — the fleet's pre-unload broadcast check."""
        return (self._registry.refcount(name)
                if self._registry is not None else 0)

    def _set_adapter_gauge(self) -> None:
        self._tel.metrics.gauge(
            "serve_adapter_resident",
            help="LoRA adapters currently resident in the engine's "
            "adapter bank"
        ).set(len(self._registry.residents))

    def load_adapter(self, name: str, adapter) -> Optional[str]:
        """Hot-load (or overwrite) adapter ``name`` into the resident
        bank: claim a bank index (reusing ``name``'s own, else a free
        slot, else deterministically evicting the LRU unpinned
        resident), write the ``(A, B)`` slices in place, no recompile.
        Returns the evicted adapter's name (its future submits shed
        with :class:`~ray_lightning_tpu.serve.adapters.UnknownAdapter`,
        like a :class:`~ray_lightning_tpu.serve.tenancy.ClassQueueFull`
        shed) or ``None``. Needs the synced frontier, like every other
        barrier — the async client drains its pipeline first."""
        if self._registry is None:
            raise ValueError(
                "this engine has no adapter bank — pass "
                "max_resident_adapters=/lora_rank= to arm multi-LoRA "
                "serving")
        self._require_synced("load_adapter")
        index, evicted = self._registry.admit(name)
        self.params = install_adapter(self.params, adapter, index)
        tel = self._tel
        if tel is not None:
            if evicted is not None:
                tel.event("engine.adapter_evicted", adapter=evicted,
                          index=index, by=name)
            tel.event("engine.adapter_loaded", adapter=name,
                      index=index, evicted=evicted)
            self._set_adapter_gauge()
        return evicted

    def unload_adapter(self, name: str) -> None:
        """Release ``name``'s bank slot (refused while in-flight rows
        pin it) and zero its slices — the freed index serves the next
        load with no stale low-rank residue."""
        if self._registry is None:
            raise ValueError(
                "this engine has no adapter bank — pass "
                "max_resident_adapters=/lora_rank= to arm multi-LoRA "
                "serving")
        self._require_synced("unload_adapter")
        index = self._registry.unload(name)
        self.params = zero_adapter(self.params, index)
        tel = self._tel
        if tel is not None:
            tel.event("engine.adapter_unloaded", adapter=name,
                      index=index)
            self._set_adapter_gauge()

    def _effective_adapter(self, request: Request) -> Optional[str]:
        """The adapter this request decodes under: its own binding,
        else its tenant class's default (``TenantClass.adapter=``),
        else ``None`` (the base model)."""
        name = getattr(request, "adapter", None)
        if name is None and self.tenant_classes is not None:
            cls = self.tenant_classes.get(request.tenant)
            if cls is not None:
                name = getattr(cls, "adapter", None)
        return name

    def _bind_adapter(self, req: Request, slot: int) -> int:
        """Pin the request's adapter at admission (inside the atomic
        try block — a mid-batch reject unbinds via
        :meth:`_unbind_adapter`): bumps the registry refcount so
        eviction can never pull a bank slot out from under an in-flight
        row, arms the slot's row id, and stamps the resolved name onto
        the request so crash replay and fleet failover re-bind the
        identical adapter. Returns the bank index (−1 = base model)."""
        name = self._effective_adapter(req)
        if name is None or self._registry is None:
            return -1
        index = self._registry.bind(name)   # UnknownAdapter if evicted
        self._adapter_ids[slot] = index
        self._adapter_of[slot] = name
        req.adapter = name
        tel = self._tel
        if tel is not None:
            tel.event("engine.adapter_bound", id=req.id, adapter=name,
                      slot=slot, index=index)
            tel.metrics.counter(
                f"serve_adapter_requests_total_{name}",
                help="requests admitted under this LoRA adapter"
            ).inc()
        return index

    def _unbind_adapter(self, slot: int) -> None:
        """Drop a slot's adapter pin (retire/cancel/admission
        rollback); no-op for base-model rows and disarmed engines."""
        if self._registry is None:
            return
        name = self._adapter_of.pop(slot, None)
        if name is not None:
            self._registry.unbind(name)
        self._adapter_ids[slot] = -1

    # ------------------------------------------------------------- state
    @property
    def free_slots(self) -> int:
        return self.pool.free_slots

    @property
    def free_pages(self) -> Optional[int]:
        """Free arena pages, or None on the dense path (the client's
        occupancy gauges key off this)."""
        return self.pool.free_pages if self.paged else None

    @property
    def active_count(self) -> int:
        return int(self._active.sum())

    @property
    def active_requests(self) -> Dict[int, Request]:
        return self.pool.active

    @property
    def chunk_pending(self) -> int:
        """Prompts admitted but still streaming through chunk prefill."""
        return len(self._chunk_queue)

    @property
    def carry_deferred(self) -> bool:
        """True while an enqueued dispatch's device carry has not been
        synced back to the host (an outstanding
        :class:`PendingDispatch` must be ``step_sync``-ed)."""
        return self._carry is not None

    @property
    def retry_pending(self) -> bool:
        """True while a failed sync step's handle waits in the retry
        seat — the next :meth:`step` drains it before dispatching anew
        (the sync driver's tick does this ahead of any barrier, so a
        transient host-copy error cannot wedge deadline cancels or
        admissions)."""
        return self._retry_sync is not None

    @property
    def spec_needs_refill(self) -> bool:
        """True when the next spec dispatch must rebuild draft KV from
        host-side token streams (stale slots — fresh admits, final
        chunks, crash replays). The async client drains its pipeline
        first, so the refill always reads the synced stream."""
        return self.spec is not None and bool(self.spec.stale)

    def _require_synced(self, op: str) -> None:
        """Barrier dispatches (admission, chunk, cancel) mutate the
        host-side row state in place — they need the synced frontier,
        or the next enqueue would chain on stale device carry and drop
        the mutation. The async client drains before every barrier;
        this guard makes direct misuse loud instead of corrupting."""
        if self._carry is not None:
            raise RuntimeError(
                f"{op} needs the synced frontier but an enqueued "
                "dispatch is still pending — step_sync() the "
                "outstanding PendingDispatch first (ServeClient"
                "(async_dispatch=True) drains its pipeline before "
                "admission/chunk/cancel dispatches)")

    def _carry_in(self) -> tuple:
        """The row-state arrays the next dispatch consumes: the device
        carry of the newest enqueued dispatch when one is outstanding
        (pipelined chaining), else the synced numpy frontier."""
        if self._carry is not None:
            return self._carry
        return (self._cur, self._pos, self._active, self._remaining,
                self._stepno)

    @property
    def chunk_pending_ids(self) -> FrozenSet[int]:
        return frozenset(st.request.id for st in self._chunk_queue)

    def occupancy(self) -> Dict[str, Any]:
        """Host-side occupancy snapshot: the engine half of the fleet
        router's scoring signals (``ServeClient.load_stats`` adds the
        scheduler half). Plain ints/None only — this dict crosses the
        process-backend queue transport verbatim."""
        return {
            "active": self.active_count,
            "chunk_pending": self.chunk_pending,
            "free_slots": self.free_slots,
            "free_pages": self.free_pages,
            "num_pages": self.pool.num_pages if self.paged else None,
            "resident_adapters": (len(self._registry.residents)
                                  if self._registry is not None
                                  else None),
        }

    @property
    def max_replay_len(self) -> int:
        """Longest prompt + already-emitted-tokens sequence a crash
        recovery can re-feed: one batched prefill pass without chunking,
        the whole sequence axis with it (chunked replay streams any
        admissible request back in — see docs/reliability.md)."""
        if self.prefill_chunk is not None:
            return self.model.cfg.max_seq_len
        return self.prefill_len

    def validate(self, request: Request) -> None:
        """Admission check: the request must fit the compiled shapes
        (and, tenancy configured, name a declared tenant class)."""
        cfg = self.model.cfg
        tenant = getattr(request, "tenant", DEFAULT_TENANT)
        if self.tenant_classes is not None:
            if tenant not in self.tenant_classes:
                raise ValueError(
                    f"unknown tenant {tenant!r}: this engine's declared "
                    f"classes are {list(self.tenant_classes)}")
        elif tenant != DEFAULT_TENANT:
            raise ValueError(
                f"request names tenant {tenant!r} but the engine has no "
                "tenant classes configured — pass tenant_classes= to "
                "arm multi-tenant scheduling")
        # adapter refusal belongs HERE, at submit — an undeclared or
        # evicted adapter must shed with registry context (the
        # ClassQueueFull pattern), never reach a dispatch as a garbage
        # bank gather
        adapter = self._effective_adapter(request)
        if adapter is not None:
            if self._registry is None:
                raise UnknownAdapter(
                    f"request names adapter {adapter!r} but the engine "
                    "has no adapter bank — pass max_resident_adapters=/"
                    "lora_rank= to arm multi-LoRA serving",
                    adapter=adapter, resident=[], capacity=0)
            self._registry.index_of(adapter)  # UnknownAdapter + context
        if self.prefill_chunk is None \
                and request.prompt_len > self.prefill_len:
            raise ValueError(
                f"prompt length {request.prompt_len} exceeds the engine's "
                f"prefill_len ({self.prefill_len})")
        if request.prompt_len + request.max_new_tokens > cfg.max_seq_len:
            raise ValueError(
                f"prompt ({request.prompt_len}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds max_seq_len "
                f"({cfg.max_seq_len})")
        if self.spec is not None and (request.prompt_len
                                      + request.max_new_tokens
                                      + self.spec_k - 1) > cfg.max_seq_len:
            raise ValueError(
                f"prompt ({request.prompt_len}) + max_new_tokens "
                f"({request.max_new_tokens}) needs spec_k-1 = "
                f"{self.spec_k - 1} positions of verify headroom beyond "
                f"it (the widened dispatch block-writes k draft "
                f"positions past the last budgeted token) — "
                f"max_seq_len ({cfg.max_seq_len}) is too small")
        if self.paged:
            need = self.pool.pages_needed(request)
            if need > self.pool.num_pages:
                raise ValueError(
                    f"request needs {need} KV pages (prompt "
                    f"{request.prompt_len} + max_new_tokens "
                    f"{request.max_new_tokens} at page_size "
                    f"{self.pool.page_size}) but the arena only has "
                    f"{self.pool.num_pages} — it can never be admitted")

    # ------------------------------------------------------- admission
    def _check_slot_quota(self, request: Request) -> None:
        """Per-class ``max_active_slots`` enforcement at admission
        (tenancy configured): the class may not hold more concurrent KV
        slots than its quota. The TenantScheduler's selection already
        respects this, so the scheduler-driven path never trips it —
        this is the loud defense for direct ``engine.prefill`` callers,
        raising inside the atomic-admission try block so the batch
        rolls back cleanly."""
        if self.tenant_classes is None:
            return
        cls = self.tenant_classes[request.tenant]
        if cls.max_active_slots is None:
            return
        held = sum(1 for r in self.pool.active.values()
                   if r.tenant == request.tenant)
        if held >= cls.max_active_slots:
            raise SlotPoolFull(
                f"tenant {request.tenant!r} at max_active_slots="
                f"{cls.max_active_slots}", tenant=request.tenant,
                slots_free=self.free_slots, active=len(self.pool.active))

    def _routes_chunked(self, request: Request) -> bool:
        """Chunk-prefill routing: everything when the prefix cache is on
        (published pages must all come from the one chunk program), else
        prompts longer than a chunk (bounded decode stall) or longer
        than the batched program can take at all."""
        if self.prefill_chunk is None:
            return False
        if self.prefix is not None:
            return True
        fed = request.prompt_len + len(request.replay_tokens or ())
        return fed > self.prefill_chunk or fed > self.prefill_len

    def _chunk_floor(self, pages: int) -> int:
        """Round a page count down to a whole number of chunks — the ONE
        place the chunk-alignment cap lives, shared by adoption and the
        hit-rate denominator so they can't drift apart."""
        per_chunk = self.prefill_chunk // self.pool.page_size
        return (pages // per_chunk) * per_chunk

    def _adoptable_prefix(self, fed: List[int]) -> List[int]:
        """Cached pages this admission may adopt: the matched chain
        capped to a whole number of chunks, so the resumed prefill
        starts on a chunk boundary and chunk writes can never touch a
        shared page (offsets stay multiples of prefill_chunk, which the
        sequence axis is a multiple of — no clamped-write rebasing)."""
        if self.prefix is None:
            return []
        matched = self.prefix.match(fed)
        return matched[:self._chunk_floor(len(matched))]

    def admissible_prefix(self, requests: List[Request]) -> int:
        """How many of the queue-head ``requests`` this engine can admit
        in one prefill call (FIFO — the count is a prefix, never a
        skip-ahead): slots, the batched program's width, and (paged)
        cumulative page demand against free + cache-evictable pages.
        Page accounting is conservative: prefix hits are counted as
        consuming their pages (adoption pins them un-evictable), never
        as a discount."""
        limit = min(len(requests), self.free_slots)
        if not self.paged:
            return min(limit, self.prefill_batch)
        budget = self.pool.free_pages + (self.prefix.evictable()
                                         if self.prefix is not None else 0)
        n = batched = 0
        for req in requests[:limit]:
            if not self._routes_chunked(req):
                if batched == self.prefill_batch:
                    break
            need = self.pool.pages_needed(req)
            if need > budget:
                break
            budget -= need
            batched += not self._routes_chunked(req)
            n += 1
        return n

    def _admit_paged(self, request: Request, adopt: List[int]) -> int:
        """Acquire slot + pages for one paged admission, evicting
        cache-only pages (protecting the chain being adopted) when the
        free list runs short."""
        fresh_need = self.pool.pages_needed(request) - len(adopt)
        short = fresh_need - self.pool.free_pages
        if short > 0 and self.prefix is not None:
            self.prefix.evict(short, protect=adopt)
        return self.pool.acquire(request, adopt)

    # ---------------------------------------------------------- programs
    def prefill(self, requests: List[Request]) -> List[Completion]:
        """Start ``requests``: slots (and pages) are acquired atomically
        for the whole batch, then prompts short enough for the batched
        program run one fixed-shape prefill pass (first tokens sampled,
        KV injected); chunk-routed prompts (longer than ``prefill_chunk``
        or any prompt under a prefix cache) are queued for
        :meth:`prefill_chunk_step` dispatches instead. Returns
        completions for requests that finish ON their first token
        (eos-on-first or an exhausted budget).

        A request carrying ``replay_tokens`` (crash recovery, see
        :class:`~ray_lightning_tpu.reliability.ServeSupervisor`) re-feeds
        its prompt + those tokens: the prefill rebuilds exactly the KV
        the dead engine held and the sampled token continues the
        request's key stream at step ``len(replay_tokens)``.
        """
        if not requests:
            return []
        self._require_synced("prefill")
        faults.fire("serve.dispatch")
        n_batched = sum(not self._routes_chunked(r) for r in requests)
        if n_batched > self.prefill_batch \
                or len(requests) > self.free_slots:
            raise SlotPoolFull(
                f"{len(requests)} requests ({n_batched} batched) > "
                f"min(free_slots={self.free_slots}, prefill_batch="
                f"{self.prefill_batch})",
                slots_free=self.free_slots,
                pages_free=self.free_pages,
                active=len(self.pool.active))
        B_pf, P = self.prefill_batch, self.prefill_len
        prompts = np.zeros((B_pf, P), np.int32)
        lengths = np.ones((B_pf,), np.int32)
        valid = np.zeros((B_pf,), bool)
        slots = np.zeros((B_pf,), np.int32)
        inject_pt = np.full(
            (B_pf, self.pool.pages_per_slot if self.paged else 1), -1,
            np.int32)
        keys = np.zeros((B_pf, 2), np.uint32)
        temp = np.zeros((B_pf,), np.float32)
        top_k = np.zeros((B_pf,), np.int32)
        startno = np.zeros((B_pf,), np.int32)
        # per-row adapter bank ids (−1 = base model, padding rows too —
        # their delta is masked to exact zero, so they stay bitwise the
        # unadapted computation)
        adapter_row = np.full((B_pf,), -1, np.int32)
        acquired: List[int] = []
        batched: List[Request] = []
        adoptions: List[Tuple[int, int, Request]] = []
        n_chunked = 0
        try:
            for req in requests:
                self.validate(req)
                self._check_slot_quota(req)
                replay = list(req.replay_tokens or ())
                fed = list(req.prompt) + replay
                if self._routes_chunked(req):
                    # chunk routing requires the page arena (__init__
                    # refuses prefill_chunk without page_size)
                    adopt = self._adoptable_prefix(fed)
                    slot = self._admit_paged(req, adopt)
                    acquired.append(slot)
                    self._bind_adapter(req, slot)
                    hit = len(adopt) * self.pool.page_size
                    req.prefix_hit_tokens = hit
                    self._chunk_queue.append(_ChunkState(
                        request=req, slot=slot, fed=fed, next_off=hit))
                    n_chunked += 1
                    if self.prefix is not None:
                        # eligible = what a fully warm cache could have
                        # served under the same chunk-alignment cap
                        eligible = self._chunk_floor(
                            (len(fed) - 1) // self.pool.page_size)
                        adoptions.append((eligible, len(adopt), req))
                    continue
                L = len(fed)
                if L > self.prefill_len:
                    raise ValueError(
                        f"request {req.id}: prompt ({req.prompt_len}) + "
                        f"replayed tokens ({len(replay)}) exceed "
                        f"prefill_len ({self.prefill_len}) — not "
                        "resumable in one prefill pass")
                slot = (self._admit_paged(req, [])
                        if self.paged else self.pool.acquire(req))
                acquired.append(slot)
                r = len(batched)
                adapter_row[r] = self._bind_adapter(req, slot)
                batched.append(req)
                prompts[r, :L] = fed
                lengths[r] = L
                valid[r] = True
                slots[r] = slot
                if self.paged:
                    inject_pt[r] = self.pool.page_table[slot]
                keys[r] = np.asarray(
                    jax.random.fold_in(self._base_key, req.seed))
                temp[r] = req.temperature
                top_k[r] = req.top_k or 0
                startno[r] = len(replay)
        except Exception:
            # atomic admission: a mid-batch reject (seed collision, bad
            # shape, page shortage) must not leak the slots/pages/chunk
            # seats already acquired. Resources only: prefix-cache
            # entries evicted to seat earlier batch members stay evicted
            # (their pages may already be re-acquired) — a retried batch
            # loses some cache warmth, never tokens
            for slot in acquired:
                self.pool.release(slot)
                self._unbind_adapter(slot)
            for _ in range(n_chunked):
                self._chunk_queue.pop()
            raise
        # poison fires AFTER the batch is seated (not with the dispatch
        # fire above, which precedes slot acquisition): a poison crash
        # must leave its request in-flight so snapshot_in_flight() —
        # and therefore the fleet's implication ledger — sees it.
        # Crashing pre-admission would bounce the poison back to the
        # client queue forever, invisible to containment.
        faults.poison_check(requests)
        # stats/telemetry only once the whole batch's admission held —
        # rolled-back admissions never count as hits or misses
        for eligible, adopted, req in adoptions:
            self.prefix.record_admission(eligible, adopted)
            if self._tel is not None and adopted:
                self._tel.event(
                    "engine.prefix_hit", id=req.id, pages=adopted,
                    tokens=adopted * self.pool.page_size)
                self._tel.metrics.counter(
                    "serve_prefix_pages_reused_total",
                    help="KV pages adopted from the prefix cache"
                ).inc(adopted)

        if not batched:
            return []
        # padding rows of the dense path target a real slot but carry
        # valid=False — the inject keeps the pool row, so they write
        # nowhere (paged padding rows are all-(−1) scatter drops)
        for r in range(len(batched), B_pf):
            slots[r] = acquired[0]

        tel = self._tel
        # None when disarmed: the kwargs guard (_adapter_kw) then keeps
        # the traced programs byte-for-byte the pre-LoRA ones, and model
        # families without the adapter_ids kwarg never see it
        adapter_arg = adapter_row if self._registry is not None else None
        with (tel.span("engine.prefill", n=len(batched),
                       **self._span_extra)
              if tel is not None else NULL_SPAN):
            if self.paged:
                fn = _pick(_prefill_paged_donated, _prefill_paged_plain)
                self.pool.arena, first = fn(
                    self.model, self.params, self.pool.arena, prompts,
                    lengths, inject_pt, keys, temp, top_k, startno,
                    adapter_arg)
            else:
                fn = _pick(_prefill_inject_donated, _prefill_inject_plain)
                self.pool.cache, first = fn(
                    self.model, self.params, self.pool.cache, prompts,
                    lengths, slots, valid, keys, temp, top_k, startno,
                    adapter_arg)
            first = np.asarray(first)
        if tel is not None:
            tel.event("engine.prefill", n=len(batched),
                      ids=[r.id for r in batched],
                      slots=[int(slots[r]) for r in range(len(batched))])

        done: List[Completion] = []
        for r, req in enumerate(batched):
            comp = self._activate(req, int(slots[r]), int(first[r]),
                                  keys[r])
            if comp is not None:
                done.append(comp)
        self.prefills += 1
        return done

    def prefill_chunk_step(self) -> List[Completion]:
        """One chunk-program dispatch for the head of the chunk queue:
        feed the next ``prefill_chunk`` tokens at the request's offset.
        On the final chunk the sampled first token activates the decode
        row (or retires the request, eos-on-first/budget-of-one), and —
        prefix cache armed — the finished prompt's full pages are
        published for future adopters."""
        self.chunk_activated = None
        if not self._chunk_queue:
            return []
        self._require_synced("prefill_chunk_step")
        faults.fire("serve.dispatch")
        st = self._chunk_queue[0]
        faults.poison_check((st.request,))
        req = st.request
        C = self.prefill_chunk
        L = len(st.fed)
        off = st.next_off
        valid = min(C, L - off)
        final = off + valid >= L
        tokens = np.zeros((1, C), np.int32)
        tokens[0, :valid] = st.fed[off:off + valid]
        keys = np.asarray(
            jax.random.fold_in(self._base_key, req.seed))[None]
        temp = np.array([req.temperature], np.float32)
        top_k = np.array([req.top_k or 0], np.int32)
        startno = np.array([len(req.replay_tokens or ())], np.int32)
        row_pages = np.array(self.pool.page_table[st.slot])
        tel = self._tel
        adapter_arg = (np.array([self._adapter_ids[st.slot]], np.int32)
                       if self._registry is not None else None)
        fn = _pick(_chunk_prefill_donated, _chunk_prefill_plain)
        with (tel.span("engine.chunk", id=req.id, off=off, n=valid,
                       slot=st.slot, **self._span_extra)
              if tel is not None else NULL_SPAN):
            self.pool.arena, first = fn(
                self.model, self.params, self.pool.arena, row_pages,
                tokens, np.int32(off), np.int32(valid), keys, temp,
                top_k, startno, adapter_arg)
            first = np.asarray(first)
        st.next_off = off + valid
        self.chunk_dispatches += 1
        if tel is not None:
            tel.event("engine.chunk", id=req.id, off=off, n=valid,
                      final=final)
        if not final:
            return []
        self._chunk_queue.popleft()
        if self.prefix is not None:
            # publish before activation: eos-on-first retires the slot,
            # but the cache's own refs keep the prefix pages warm
            self.prefix.publish(list(req.prompt), st.slot)
        comp = self._activate(req, st.slot, int(first[0]), keys[0])
        if comp is None:
            self.chunk_activated = req
            return []
        return [comp]

    def _activate(self, req: Request, slot: int, tok: int,
                  key: np.ndarray) -> Optional[Completion]:
        """Shared first-token bookkeeping for the batched prefill and the
        final chunk: record the token, retire on eos-on-first/exhausted
        budget, otherwise arm the slot's decode row."""
        toks = list(req.replay_tokens or ())
        if self.spec is None or not toks:
            toks.append(tok)
            self.tokens_generated += 1
        # else: spec-engine replay — the prefill's plain categorical
        # draw is NOT the token the uninterrupted spec stream produced
        # at this step (that one came through the rejection-resampling
        # composition). Discard it and arm the row one step earlier:
        # the next spec round regenerates step len(replay) through the
        # same accept rule, off the same (seed, step) keys — sampled
        # streams stay replay-exact (greedy is indifferent: both paths
        # commit the target argmax). Non-spec engines keep the original
        # contract: the prefill draw IS the stream's next token.
        self._tokens[slot] = toks
        hit_eos = req.eos_id is not None and toks[-1] == req.eos_id
        if hit_eos or len(toks) >= req.max_new_tokens:
            return self._retire(
                slot, FINISH_EOS if hit_eos else FINISH_LENGTH)
        self._cur[slot, 0] = toks[-1]
        self._pos[slot, 0] = req.prompt_len + len(toks) - 1
        self._active[slot] = True
        self._remaining[slot] = req.max_new_tokens - len(toks)
        self._temp[slot] = req.temperature
        self._top_k[slot] = req.top_k or 0
        self._eos[slot] = -1 if req.eos_id is None else req.eos_id
        self._keys[slot] = key
        self._stepno[slot] = len(toks)
        if self.spec is not None:
            # whatever path armed the row (fresh admit, final chunk,
            # crash replay), the draft KV must be rebuilt from the full
            # context before the next spec dispatch
            self.spec.mark_stale(slot)
        return None

    def step(self) -> List[Completion]:
        """Advance every in-flight request up to ``steps_per_dispatch``
        tokens in one program dispatch; returns the completions of rows
        that finished inside the block (eos or budget — rows finishing at
        sub-step k park idempotently for the remaining sub-steps).

        Speculative engines (``draft_model=``) route here too: each of
        the ``steps_per_dispatch`` scanned units is then one spec ROUND
        (k draft steps + one widened verify) committing 1..k+1 tokens
        per row instead of exactly one.

        Internally this is :meth:`step_enqueue` + :meth:`step_sync`
        back-to-back — the sync driver pays the host round-trip between
        every dispatch; ``ServeClient(async_dispatch=True)`` splits the
        halves across ticks so the device never waits on it. With a
        handle still outstanding this refuses loudly (same misuse class
        as the barrier guards): chaining a sync step past an un-synced
        enqueue would advance the carry while silently dropping the
        outstanding dispatch's tokens. A transient device error at the
        host copy is retryable: the failed sync leaves the engine
        untouched and the handle parks in a retry seat, so the next
        ``step()`` syncs the SAME dispatch before launching anew."""
        if self._retry_sync is not None:
            # a prior step()'s sync failed after its dispatch launched —
            # drain it first (the carry is deliberately still deferred)
            pending, self._retry_sync = self._retry_sync, None
        else:
            self._require_synced("step")
            pending = self._enqueue(asynchronous=False)
            if pending is None:
                return []
        try:
            return self.step_sync(pending)
        except Exception:
            self._retry_sync = pending
            raise

    def step_enqueue(self) -> Optional[PendingDispatch]:
        """Enqueue one step/spec dispatch against the device carry and
        return WITHOUT syncing its outputs (depth-2 pipelining: the
        returned :class:`PendingDispatch` is reconciled by
        :meth:`step_sync` while the NEXT dispatch computes). Rows that
        retire inside an un-synced dispatch are handled by the
        in-program latches the next dispatch already carries — parked
        rows emit −1 and write nothing — so chained enqueues commit
        exactly the sync driver's tokens. Returns ``None`` when nothing
        is in flight at the synced frontier and no carry is deferred."""
        return self._enqueue(asynchronous=True)

    def _enqueue(self, *, asynchronous: bool) -> Optional[PendingDispatch]:
        if self._carry is None and not self._active.any():
            return None
        if self.spec is not None:
            return self._spec_enqueue(asynchronous)
        faults.fire("serve.dispatch")
        faults.poison_check(self.pool.active.values())
        tel = self._tel
        cur, pos, active, remaining, stepno = self._carry_in()
        with (tel.span("engine.step", active=int(self._active.sum()),
                       **self._span_extra)
              if tel is not None else NULL_SPAN):
            if self.paged and self.page_native:
                # page-native: attention reads/writes K/V through the
                # (write-masked) page table inside the model — no dense
                # view gather/scatter per dispatch. Token-identical to
                # the dense-gather path up to reduction-order rounding
                # (int8 arenas: plus per-token page requant rounding —
                # docs/serving.md caveat); pinned by tests/test_paged.py
                # and the bench's enforced 0-mismatch gate. The write
                # mask comes from the SYNCED frontier: a row that
                # retired inside a still-pending dispatch keeps its
                # entries one extra dispatch and re-writes its frozen
                # K/V idempotently — its pages are only released (and
                # only reusable) at sync, behind the admission barrier.
                fn = _pick(_page_native_step_donated,
                           _page_native_step_plain)
                (self.pool.arena, cur, pos, active, remaining, stepno,
                 emitted, finished) = fn(
                    self.model, self.params, self.pool.arena,
                    self._write_masked_table(), cur, pos,
                    active, remaining, self._temp,
                    self._top_k, self._eos, self._keys, stepno,
                    self._adapter_ids,
                    steps=self.steps_per_dispatch)
            elif self.paged:
                fn = _pick(_paged_step_donated, _paged_step_plain)
                # the table copy re-uploads H2D every dispatch though it
                # only changes at admit/retire — known headroom for the
                # pallas-kernel round (docs/performance.md), kept simple
                # while the dispatch overhead dominates
                (self.pool.arena, cur, pos, active, remaining, stepno,
                 emitted, finished) = fn(
                    self.model, self.params, self.pool.arena,
                    np.array(self.pool.page_table), cur, pos,
                    active, remaining, self._temp,
                    self._top_k, self._eos, self._keys, stepno,
                    self._adapter_ids,
                    steps=self.steps_per_dispatch)
            else:
                fn = _pick(_engine_step_donated, _engine_step_plain)
                (self.pool.cache, cur, pos, active, remaining, stepno,
                 emitted, finished) = fn(
                    self.model, self.params, self.pool.cache, cur,
                    pos, active, remaining, self._temp,
                    self._top_k, self._eos, self._keys, stepno,
                    self._adapter_ids,
                    steps=self.steps_per_dispatch)
        self._carry = (cur, pos, active, remaining, stepno)
        self.steps += 1
        self.decode_substeps += self.steps_per_dispatch
        if tel is not None and asynchronous:
            tel.event("engine.dispatch_enqueued", dispatch=self.steps,
                      kind="step")
        return PendingDispatch(
            kind="step", dispatch=self.steps,
            rounds=self.steps_per_dispatch, emitted=emitted,
            finished=finished, carry=self._carry,
            owner=self._engine_token,
            asynchronous=asynchronous, enqueued_at=time.perf_counter())

    def step_sync(self, pending: PendingDispatch) -> List[Completion]:
        """Materialize one enqueued dispatch: copy its outputs to the
        host (THE blocking point — everything the caller did since
        :meth:`step_enqueue` overlapped the device), catch the synced
        frontier up to its carry, and run the retire loop. Handles must
        sync in enqueue order; a handle from a rebuilt-away engine must
        be DISCARDED, never synced (its tokens were regenerated by
        replay)."""
        if pending.owner is not self._engine_token:
            raise RuntimeError(
                "step_sync on a foreign handle: this PendingDispatch "
                "was issued by another (likely rebuilt-away) engine — "
                "it must be discarded, never synced; its tokens were "
                "regenerated by the replay")
        if pending.dispatch != self._synced_dispatch + 1:
            # same loud-misuse policy as _require_synced: a double sync
            # would duplicate every emitted token (and could retire a
            # slot's NEW tenant on the old row's verdict), and a handle
            # from a rebuilt-away engine must be discarded, never
            # synced — both show up here as an out-of-order index
            raise RuntimeError(
                f"step_sync out of order: handle is dispatch "
                f"{pending.dispatch}, engine expects "
                f"{self._synced_dispatch + 1} — handles sync exactly "
                "once, in enqueue order, and a rebuilt engine's "
                "outstanding handle must be discarded, not synced")
        tel = self._tel
        overlap_ms = 1e3 * (time.perf_counter() - pending.enqueued_at)
        # materialize EVERY fallible host copy into locals first: a
        # device error surfacing here must leave the engine untouched —
        # the caller keeps the handle and can retry this same sync (or
        # hit the loud out-of-order guard), instead of resuming past a
        # dispatch whose tokens were silently skipped
        cur, pos, active, remaining, stepno = pending.carry
        # np.array (copy): jax outputs view as read-only buffers, and
        # the next prefill writes these rows in place
        cur = np.array(cur)
        pos = np.array(pos)
        active = np.array(active)
        remaining = np.array(remaining)
        stepno = np.array(stepno)
        emitted = np.asarray(pending.emitted)  # (steps, B), −1 = parked
        finished = np.asarray(pending.finished)  # (steps, B)
        if pending.kind == "spec":
            accepted = np.asarray(pending.accepted)   # (rounds, B)
            rejected = np.asarray(pending.rejected)   # (rounds, B)
        # ---- commit point: everything below is host-side bookkeeping
        self._synced_dispatch = pending.dispatch
        self._cur, self._pos, self._active = cur, pos, active
        self._remaining, self._stepno = remaining, stepno
        if self._carry is pending.carry:
            # frontier caught up with the newest enqueue — barrier
            # dispatches may run again
            self._carry = None
        if pending.kind == "spec":
            return self._sync_spec(pending, emitted, accepted, rejected,
                                   finished, overlap_ms)

        done: List[Completion] = []
        for slot in range(self.num_slots):
            toks = [int(t) for t in emitted[:, slot] if t >= 0]
            if not toks:
                continue
            self._tokens[slot].extend(toks)
            self.tokens_generated += len(toks)
            if finished[:, slot].any():
                req = self.pool.active[slot]
                hit_eos = req.eos_id is not None and toks[-1] == req.eos_id
                done.append(self._retire(
                    slot, FINISH_EOS if hit_eos else FINISH_LENGTH))
        if tel is not None:
            if pending.asynchronous:
                tel.event("engine.dispatch_synced",
                          dispatch=pending.dispatch, kind="step",
                          retired=len(done))
                tel.metrics.histogram(
                    "serve_dispatch_overlap_ms",
                    help="host work overlapped with an in-flight "
                    "dispatch: enqueue return -> sync start, wall ms"
                ).observe(overlap_ms)
            else:
                tel.event("engine.step", dispatch=pending.dispatch,
                          active=self.active_count, retired=len(done))
        return done

    def _write_masked_table(self) -> np.ndarray:
        """The page table with inactive rows' entries masked to −1 —
        what every page-native program receives: a mid-chunking slot's
        pages (allocated, not yet decoding) must never see a parked
        decode write, and retired rows' reads may clamp harmlessly."""
        return np.where(self._active[:, None], self.pool.page_table,
                        -1).astype(np.int32)

    def _spec_enqueue(self, asynchronous: bool) -> PendingDispatch:
        """Enqueue one speculative dispatch: refill stale draft rows
        (host-side, reading the SYNCED token streams — the refill
        ledger is why the async client drains its pipeline before any
        dispatch that marks a slot stale), then launch
        ``steps_per_dispatch`` spec rounds (k+1 draft feeds + one
        ``(B, k+1)`` verify each) in one fused program. Greedy commits
        are token-identical to the plain step path by the accept rule
        (see serve/spec.py); the host-side retire loop
        (:meth:`_sync_spec`) is shared shape-for-shape with
        :meth:`step_sync` at (rounds, k+1)-token granularity."""
        faults.fire("serve.dispatch")
        faults.poison_check(self.pool.active.values())
        spec = self.spec
        active_req = self.pool.active
        for slot in spec.stale:
            req = active_req.get(slot)
            if req is None or not self._active[slot]:
                spec.discard(slot)
                continue
            # draft KV must cover 0..pos-1: full context minus the
            # current token (which the first draft feed supplies)
            spec.refill(slot, list(req.prompt) + self._tokens[slot][:-1])
        faults.fire("serve.verify")
        tel = self._tel
        k, rounds = spec.k, self.steps_per_dispatch
        cur, pos, act, remaining, stepno = self._carry_in()
        with (tel.span("engine.spec_round", active=int(self._active.sum()),
                       k=k, **self._span_extra)
              if tel is not None else NULL_SPAN):
            if self.paged and self.page_native:
                # the widened verify reads/writes target K/V through
                # the page table too — spec and page-native compose on
                # one engine (the draft cache stays dense either way)
                fn = _pick(_spec_page_native_donated,
                           _spec_page_native_plain)
                (self.pool.arena, spec.cache, cur, pos, act, remaining,
                 stepno, emitted, accepted, rejected, finished) = fn(
                    self.model, spec.model, self.params, spec.params,
                    self.pool.arena, self._write_masked_table(),
                    spec.cache, cur, pos, act,
                    remaining, self._temp, self._top_k, self._eos,
                    self._keys, stepno, self._adapter_ids,
                    k=k, rounds=rounds)
            elif self.paged:
                fn = _pick(_spec_paged_donated, _spec_paged_plain)
                (self.pool.arena, spec.cache, cur, pos, act, remaining,
                 stepno, emitted, accepted, rejected, finished) = fn(
                    self.model, spec.model, self.params, spec.params,
                    self.pool.arena, np.array(self.pool.page_table),
                    spec.cache, cur, pos, act,
                    remaining, self._temp, self._top_k, self._eos,
                    self._keys, stepno, self._adapter_ids,
                    k=k, rounds=rounds)
            else:
                fn = _pick(_spec_rounds_donated, _spec_rounds_plain)
                (self.pool.cache, spec.cache, cur, pos, act, remaining,
                 stepno, emitted, accepted, rejected, finished) = fn(
                    self.model, spec.model, self.params, spec.params,
                    self.pool.cache, spec.cache, cur, pos,
                    act, remaining, self._temp,
                    self._top_k, self._eos, self._keys, stepno,
                    self._adapter_ids, k=k, rounds=rounds)
        self._carry = (cur, pos, act, remaining, stepno)
        self.steps += 1
        # one verify = one target param read, however many tokens it
        # committed — the honesty-floor unit stays "target passes"
        self.decode_substeps += rounds
        self.spec_rounds += rounds
        self.spec_draft_steps += (k + 1) * rounds
        if tel is not None and asynchronous:
            tel.event("engine.dispatch_enqueued", dispatch=self.steps,
                      kind="spec")
        return PendingDispatch(
            kind="spec", dispatch=self.steps, rounds=rounds,
            emitted=emitted, finished=finished, carry=self._carry,
            owner=self._engine_token,
            accepted=accepted, rejected=rejected,
            asynchronous=asynchronous, enqueued_at=time.perf_counter())

    def _sync_spec(self, pending: PendingDispatch, emitted, accepted,
                   rejected, finished,
                   overlap_ms: float) -> List[Completion]:
        """The spec half of :meth:`step_sync`: (rounds, B, k+1) retire
        loop + acceptance accounting. The arrays arrive already
        materialized — every fallible host copy happens before the
        caller's commit point."""
        tel = self._tel
        rounds = pending.rounds

        done: List[Completion] = []
        committed = 0
        for slot in range(self.num_slots):
            toks = [int(t) for t in emitted[:, slot, :].reshape(-1)
                    if t >= 0]
            if not toks:
                continue
            self._tokens[slot].extend(toks)
            committed += len(toks)
            self.tokens_generated += len(toks)
            if finished[:, slot].any():
                req = self.pool.active[slot]
                hit_eos = req.eos_id is not None and toks[-1] == req.eos_id
                done.append(self._retire(
                    slot, FINISH_EOS if hit_eos else FINISH_LENGTH))
        acc_total = int(accepted.sum())
        rej_total = int(rejected.sum())
        # judged = drafts the verify actually ruled on in the committed
        # stream (accepted + contradicted); agreements cut by a
        # budget/eos clamp count toward neither side, so the rate reads
        # the draft's true quality — 1.0 for a perfectly-agreeing draft
        # even on its final, budget-clamped round
        judged = acc_total + rej_total
        self.spec_accepted_tokens += acc_total
        self.spec_rejected_tokens += rej_total
        if tel is not None:
            if pending.asynchronous:
                tel.event("engine.dispatch_synced",
                          dispatch=pending.dispatch, kind="spec",
                          judged=judged, accepted=acc_total,
                          committed=committed, retired=len(done))
                tel.metrics.histogram(
                    "serve_dispatch_overlap_ms",
                    help="host work overlapped with an in-flight "
                    "dispatch: enqueue return -> sync start, wall ms"
                ).observe(overlap_ms)
            else:
                tel.event("engine.spec_round", dispatch=pending.dispatch,
                          rounds=rounds, judged=judged,
                          accepted=acc_total, committed=committed,
                          retired=len(done))
            m = tel.metrics
            m.counter("serve_spec_accepted_tokens_total",
                      help="draft tokens accepted by the verify step"
                      ).inc(acc_total)
            m.counter("serve_spec_rejected_tokens_total",
                      help="draft tokens contradicted by the verify "
                      "step").inc(rej_total)
            if judged:
                m.histogram(
                    "serve_spec_accept_rate",
                    help="per-dispatch draft acceptance rate "
                    "(accepted / judged)"
                ).observe(acc_total / judged)
        return done

    # -------------------------------------------------------- lifecycle
    def snapshot_in_flight(self) -> List:
        """``[(request, tokens_emitted_so_far)]`` for every in-flight
        slot, in slot order — what a supervisor needs to re-admit this
        engine's work after a crash (copies, never live buffers).
        Mid-chunking prompts have no ``_tokens`` entry (decode hasn't
        started — or, for a replay-of-a-replay, hasn't REstarted), so
        they fall back to their ``replay_tokens``: a second crash during
        a replay's chunk re-feed must not drop the first crash's
        emissions."""
        active = self.pool.active
        return [(active[slot],
                 list(self._tokens.get(
                     slot, active[slot].replay_tokens or ())))
                for slot in sorted(active)]

    def cancel(self, request_id: int,
               reason: str = FINISH_TIMEOUT) -> Optional[Completion]:
        """Abort an in-flight request (deadline expiry): frees its slot,
        returns a completion with the tokens produced so far."""
        slot = self.pool.slot_of(request_id)
        if slot is None:
            return None
        self._require_synced("cancel")
        return self._retire(slot, reason)

    def shutdown(self) -> None:
        """Release the engine's device state: drop prefix-cache refs and
        the KV pool/arena so a retired engine stops pinning HBM. The
        engine is unusable afterwards."""
        if self.prefix is not None:
            self.prefix.drop()
        self.prefix = None
        self.pool = None
        if self.spec is not None:
            self.spec.shutdown()
        self.spec = None
        self._chunk_queue.clear()
        self._tokens.clear()
        self._adapter_of.clear()
        # an in-flight enqueued dispatch is DISCARDED with the carry —
        # the sync-frontier contract: its tokens were never committed,
        # and (failover) a replay regenerates them elsewhere
        self._carry = None
        self._retry_sync = None
        self._active[:] = False

    def _retire(self, slot: int, reason: str) -> Completion:
        # only cancel() can retire a mid-chunking slot — don't rebuild
        # the deque on every normal retirement while chunks stream
        if any(st.slot == slot for st in self._chunk_queue):
            self._chunk_queue = deque(
                st for st in self._chunk_queue if st.slot != slot)
        req = self.pool.release(slot)
        self._active[slot] = False
        self._unbind_adapter(slot)
        if self.spec is not None:
            # a cancel between activation and the next spec dispatch
            # must not refill a slot that no longer holds the request
            self.spec.discard(slot)
        # a mid-chunking REPLAY has no _tokens entry yet: its pre-crash
        # emissions live in replay_tokens and a cancel/deadline must
        # still surface them (PR 3's partial-tokens contract)
        tokens = self._tokens.pop(slot, None)
        if tokens is None:
            tokens = list(req.replay_tokens or ())
        return Completion(
            request_id=req.id, prompt=list(req.prompt), tokens=tokens,
            finish_reason=reason, arrival_time=req.arrival_time,
            first_token_time=req.first_token_time,
            prefix_hit_tokens=req.prefix_hit_tokens,
            tenant=req.tenant, adapter=getattr(req, "adapter", None))
