"""Continuous-batching serving engine: slot-pooled KV cache + one step
program for all in-flight requests.

Iteration-level scheduling (Orca, OSDI '22) on XLA's terms: instead of a
static batch that waits for its slowest member, the engine owns a fixed
pool of **KV slots** — rows of one pre-allocated ``(B, max_seq_len, H, D)``
cache — and exactly TWO pre-compiled fixed-shape programs, reusing the
prefill/decode split from :mod:`ray_lightning_tpu.models.generate`:

1. **prefill+inject** (``(B_pf, P)`` static shape): batch up to ``B_pf``
   waiting prompts, run the existing single-pass
   :func:`~ray_lightning_tpu.models.generate._prefill_impl` forward,
   sample each row's first token with its own key/params, and write each
   prefilled KV row into its assigned pool slot (a per-row
   ``dynamic_update_slice`` along the cache's batch axis).
2. **step** (``(B, 1)`` static shape): ONE cached decode step for all B
   slots at their own ``kv_positions`` — the factored
   :func:`~ray_lightning_tpu.models.generate.decode_step` that
   ``generate()``'s ragged scan also runs, so engine decode cannot drift
   from one-shot decode. Each row samples with its request's own
   temperature/top_k/key, counts down its own ``max_new_tokens`` budget,
   and latches its own eos — finished rows retire *mid-flight* and their
   slots are handed to the next queued request without recompiling
   anything (all shapes static).

This is vLLM-style paged KV management simplified to whole-sequence slots:
XLA wants static shapes, so the page size is "one request's max context"
and the pool is the batch dimension. See ``docs/serving.md`` for the slot
lifecycle and the rationale vs. finer-grained paging.

Inactive slots still flow through the step program (the batch is static);
they are masked out of sampling/bookkeeping and their parked KV rewrite is
idempotent, so they cost FLOPs but never correctness. Keep ``num_slots``
near your live-traffic working set.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.models.generate import (_prefill_impl, decode_step,
                                               sample_logits_rows)
from ray_lightning_tpu.models.transformer import latch_eos
from ray_lightning_tpu.obs.spans import NULL_SPAN
from ray_lightning_tpu.reliability import faults
from ray_lightning_tpu.serve.request import (Completion, FINISH_EOS,
                                             FINISH_LENGTH, FINISH_TIMEOUT,
                                             Request)


def _fold_rows(keys: jax.Array, data: jax.Array) -> jax.Array:
    """Per-row ``fold_in``: (B, 2) raw uint32 keys x (B,) ints."""
    return jax.vmap(jax.random.fold_in)(keys, data)


def _engine_step_core(model, params, cache, cur, pos, active, remaining,
                      temp, top_k, eos, keys, stepno):
    """One decode step for all B slots. Pure function of the engine state
    arrays; (B, 1) model step shared with generate() via decode_step.

    Per-row semantics (matching the ragged decode scan): ``cur`` is the
    token sampled last step, ``pos`` its absolute position — the step
    writes its K/V there, masks keys beyond it, samples the next token at
    ``pos + 1``. Inactive rows run the same math (static shapes) but their
    state is frozen: emitted is masked to −1, ``pos``/``stepno`` don't
    advance, and re-writing the same K/V at the same position is
    idempotent.
    """
    last, cache = decode_step(model, params, cache, cur, pos)
    step_keys = _fold_rows(keys, stepno)
    nxt = sample_logits_rows(last, step_keys, temp, top_k)
    # per-row eos (−1 = disabled); done=False — finished rows leave the
    # batch instead of repeating eos, the pool hands their slot on
    _, eos_hit = latch_eos(nxt, jnp.zeros_like(active), eos)
    act_i = active.astype(jnp.int32)
    remaining = remaining - act_i
    finished = active & (eos_hit | (remaining <= 0))
    emitted = jnp.where(active, nxt, -1)
    max_pos = model.cfg.max_seq_len - 1
    cur = jnp.where(active[:, None], nxt[:, None], cur)
    pos = jnp.minimum(pos + act_i[:, None], max_pos)
    stepno = stepno + act_i
    active = active & ~finished
    return (cache, cur, pos, active, remaining, stepno, emitted, finished)


def _engine_step_impl(model, params, cache, cur, pos, active, remaining,
                      temp, top_k, eos, keys, stepno, *, steps):
    """``steps`` decode steps in ONE dispatch (multi-step scheduling).

    Token-granularity dispatch pays the fixed per-call overhead once per
    token — measured at ~55 ms on the axon tunnel vs a ~0.6 ms device
    step (docs/performance.md), which would hand the fused one-shot scan
    an unbeatable advantage. Scanning ``steps`` iterations of the SAME
    per-row step inside the program amortizes the dispatch 1/steps while
    keeping the math identical (rows that finish mid-block park
    idempotently; emitted is −1-masked per sub-step). The trade is
    scheduling granularity: joins/retires happen every ``steps`` tokens.

    Returns the carried state plus ``emitted``/``finished`` stacked
    ``(steps, B)`` — the host replays sub-steps in order.
    """
    def body(carry, _):
        cache, cur, pos, active, remaining, stepno = carry
        (cache, cur, pos, active, remaining, stepno, emitted,
         finished) = _engine_step_core(
            model, params, cache, cur, pos, active, remaining, temp,
            top_k, eos, keys, stepno)
        return ((cache, cur, pos, active, remaining, stepno),
                (emitted, finished))

    (cache, cur, pos, active, remaining, stepno), (emitted, finished) = \
        jax.lax.scan(body, (cache, cur, pos, active, remaining, stepno),
                     None, length=steps)
    return (cache, cur, pos, active, remaining, stepno, emitted, finished)


def _prefill_inject_impl(model, params, pool_cache, prompts, lengths,
                         slots, valid, keys, temp, top_k, startno):
    """Batched prompt fill + first-token sample + KV injection.

    Runs the standard single-pass prefill at the engine's fixed
    ``(B_pf, P)`` shape (rows left-aligned, ``lengths`` raggedness — the
    same contract as generate()'s ragged prefill), samples each row's
    first token with its own key/params, then writes each valid row's
    whole KV row into its assigned pool slot. Invalid (padding) rows are
    computed but written nowhere — the pool row is read back and kept, so
    one compiled program covers every fill level of the prefill batch.

    ``startno`` (B,) is each row's sampling-step offset: 0 for a fresh
    request (fold_in(key, 0), the original behavior), k for a
    crash-recovery replay whose row re-feeds the prompt + k emitted
    tokens — the sampled token then continues the request's key stream
    exactly where the dead engine left it (same array shapes, so replay
    reuses the compiled program).
    """
    B_pf = prompts.shape[0]
    pf_cache, last = _prefill_impl(model, params, prompts, lengths)
    first_keys = _fold_rows(keys, startno)
    first = sample_logits_rows(last, first_keys, temp, top_k)

    # cache leaves: cached_key/cached_value are (B, L, H, D) unrolled or
    # (n_layers, B, L, H, D) scanned — the batch axis follows the layout.
    # Sub-4d leaves (cache_index scalars/stacks) are shared-index
    # bookkeeping the per-row kv_positions path never reads: keep pool's.
    batch_axis = 1 if model.cfg.scan_layers else 0
    num_slots = next(leaf.shape[batch_axis]
                     for leaf in jax.tree_util.tree_leaves(pool_cache)
                     if leaf.ndim >= 4)

    # slot_map[s] = the pf row writing pool slot s, or -1 to keep the
    # pool row. Invalid (padding) rows scatter to a dropped out-of-range
    # index; valid slots are unique (pool invariant), so one gather +
    # select per leaf does the whole injection — no per-row update chain.
    scatter_idx = jnp.where(valid, slots, num_slots)
    slot_map = jnp.full((num_slots,), -1, jnp.int32).at[scatter_idx].set(
        jnp.arange(B_pf, dtype=jnp.int32), mode="drop")
    keep = slot_map < 0

    def inject(pool, pf):
        if pool.ndim < 4:
            return pool
        gathered = jnp.take(pf, jnp.maximum(slot_map, 0), axis=batch_axis)
        mask_shape = [1] * pool.ndim
        mask_shape[batch_axis] = num_slots
        return jnp.where(keep.reshape(mask_shape), pool, gathered)

    pool_cache = jax.tree_util.tree_map(inject, pool_cache, pf_cache)
    return pool_cache, first


_engine_step_donated = partial(
    jax.jit, static_argnames=("model", "steps"), donate_argnums=(2,))(
        _engine_step_impl)
_engine_step_plain = partial(
    jax.jit, static_argnames=("model", "steps"))(_engine_step_impl)
_prefill_inject_donated = partial(
    jax.jit, static_argnames=("model",), donate_argnums=(2,))(
        _prefill_inject_impl)
_prefill_inject_plain = partial(
    jax.jit, static_argnames=("model",))(_prefill_inject_impl)


def _pick(donated, plain):
    """Donate the pool cache wherever the backend honors it (same CPU
    gating as generate()'s decode scan — CPU ignores donation loudly)."""
    return plain if jax.default_backend() == "cpu" else donated


class SlotPoolFull(RuntimeError):
    """No free KV slot — admission control should have prevented this."""


class KVSlotPool:
    """Owns the (B, max_seq_len) KV cache and the request → slot map.

    Slots are acquired at prefill injection and released on
    eos/max-token/timeout; lowest-index-first allocation keeps traces
    deterministic. The pool also enforces the no-key-reuse invariant: two
    co-resident slots may never carry the same sampling seed (their
    per-step keys would collide stream-for-stream).
    """

    def __init__(self, model, num_slots: int):
        self.num_slots = num_slots
        self.cache = model.init(
            jax.random.PRNGKey(0), jnp.zeros((num_slots, 1), jnp.int32),
            positions=jnp.zeros((num_slots, 1), jnp.int32))["cache"]
        self._free: List[int] = list(range(num_slots))
        self._requests: Dict[int, Request] = {}  # slot -> request

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def active(self) -> Dict[int, Request]:
        return dict(self._requests)

    def slot_of(self, request_id: int) -> Optional[int]:
        for slot, req in self._requests.items():
            if req.id == request_id:
                return slot
        return None

    def acquire(self, request: Request) -> int:
        if not self._free:
            raise SlotPoolFull(
                f"all {self.num_slots} KV slots in use")
        for req in self._requests.values():
            if req.seed == request.seed:
                raise ValueError(
                    f"PRNG key reuse across slots: request {request.id} "
                    f"and in-flight request {req.id} share seed "
                    f"{request.seed} — co-resident sample streams would "
                    "collide; give one an explicit distinct seed")
        slot = self._free.pop(0)
        self._requests[slot] = request
        return slot

    def release(self, slot: int) -> Request:
        req = self._requests.pop(slot)
        self._free.append(slot)
        self._free.sort()
        return req


class ServeEngine:
    """In-flight batching over a fixed KV slot pool.

    ``model`` must be a decode-mode LM (``cfg.decode=True``; for serving
    throughput build it ``scan_layers=False`` and convert training weights
    with ``unstack_scan_params`` — see ``docs/performance.md``). The
    engine compiles two programs on first use and never again:
    prefill+inject at ``(prefill_batch, prefill_len)`` and the decode step
    at ``(num_slots, 1)``.

    Drive it with :class:`~ray_lightning_tpu.serve.client.ServeClient`
    (scheduler + admission control + clocks) or directly:
    ``prefill([reqs])`` to start requests, ``step()`` to advance every
    in-flight request one token; both return newly finished
    :class:`Completion`\\ s.
    """

    def __init__(self, model, params, *, num_slots: int = 8,
                 prefill_batch: Optional[int] = None,
                 prefill_len: int = 64, steps_per_dispatch: int = 1,
                 seed: int = 0, telemetry=None):
        cfg = model.cfg
        if not cfg.decode:
            raise ValueError(
                "ServeEngine needs a decode-mode model: rebuild the "
                "config with decode=True (params are compatible)")
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if prefill_len > cfg.max_seq_len:
            raise ValueError(
                f"prefill_len ({prefill_len}) exceeds max_seq_len "
                f"({cfg.max_seq_len})")
        if steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got "
                f"{steps_per_dispatch}")
        self.model = model
        self.params = params
        self.num_slots = num_slots
        self.prefill_batch = max(1, min(prefill_batch or num_slots,
                                        num_slots))
        self.prefill_len = prefill_len
        # >1 = multi-step scheduling: K decode steps per program dispatch
        # (amortizes the fixed per-call overhead; requests join/retire at
        # K-token granularity) — see _engine_step_impl
        self.steps_per_dispatch = steps_per_dispatch
        # off by default; one attribute read + None check per dispatch
        # when disarmed (docs/observability.md)
        self._tel = telemetry
        self.pool = KVSlotPool(model, num_slots)
        self._base_key = jax.random.PRNGKey(seed)

        B = num_slots
        self._cur = np.zeros((B, 1), np.int32)
        self._pos = np.zeros((B, 1), np.int32)
        self._active = np.zeros((B,), bool)
        self._remaining = np.zeros((B,), np.int32)
        self._temp = np.zeros((B,), np.float32)
        self._top_k = np.zeros((B,), np.int32)
        self._eos = np.full((B,), -1, np.int32)
        self._keys = np.zeros((B, 2), np.uint32)
        self._stepno = np.zeros((B,), np.int32)
        self._tokens: Dict[int, List[int]] = {}

        # counters for the bench / scheduler policy (steps counts
        # dispatches; decode_substeps counts model token-steps)
        self.steps = 0
        self.decode_substeps = 0
        self.prefills = 0
        self.tokens_generated = 0

    # ------------------------------------------------------------- state
    @property
    def free_slots(self) -> int:
        return self.pool.free_slots

    @property
    def active_count(self) -> int:
        return int(self._active.sum())

    @property
    def active_requests(self) -> Dict[int, Request]:
        return self.pool.active

    def validate(self, request: Request) -> None:
        """Admission check: the request must fit the compiled shapes."""
        cfg = self.model.cfg
        if request.prompt_len > self.prefill_len:
            raise ValueError(
                f"prompt length {request.prompt_len} exceeds the engine's "
                f"prefill_len ({self.prefill_len})")
        if request.prompt_len + request.max_new_tokens > cfg.max_seq_len:
            raise ValueError(
                f"prompt ({request.prompt_len}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds max_seq_len "
                f"({cfg.max_seq_len})")

    # ---------------------------------------------------------- programs
    def prefill(self, requests: List[Request]) -> List[Completion]:
        """Start ``requests``: one fixed-shape prefill pass, first tokens
        sampled, KV rows injected into freshly acquired slots. Returns
        completions for requests that finish ON their first token
        (eos-on-first or an exhausted budget).

        A request carrying ``replay_tokens`` (crash recovery, see
        :class:`~ray_lightning_tpu.reliability.ServeSupervisor`) re-feeds
        its prompt + those tokens: the prefill rebuilds exactly the KV
        the dead engine held and the sampled token continues the
        request's key stream at step ``len(replay_tokens)``.
        """
        if not requests:
            return []
        faults.fire("serve.dispatch")
        if len(requests) > min(self.free_slots, self.prefill_batch):
            raise SlotPoolFull(
                f"{len(requests)} requests > min(free_slots="
                f"{self.free_slots}, prefill_batch={self.prefill_batch})")
        B_pf, P = self.prefill_batch, self.prefill_len
        prompts = np.zeros((B_pf, P), np.int32)
        lengths = np.ones((B_pf,), np.int32)
        valid = np.zeros((B_pf,), bool)
        slots = np.zeros((B_pf,), np.int32)
        keys = np.zeros((B_pf, 2), np.uint32)
        temp = np.zeros((B_pf,), np.float32)
        top_k = np.zeros((B_pf,), np.int32)
        startno = np.zeros((B_pf,), np.int32)
        acquired = []
        try:
            for r, req in enumerate(requests):
                self.validate(req)
                replay = list(req.replay_tokens or ())
                L = req.prompt_len + len(replay)
                if L > self.prefill_len:
                    raise ValueError(
                        f"request {req.id}: prompt ({req.prompt_len}) + "
                        f"replayed tokens ({len(replay)}) exceed "
                        f"prefill_len ({self.prefill_len}) — not "
                        "resumable in one prefill pass")
                slot = self.pool.acquire(req)
                acquired.append(slot)
                prompts[r, :L] = list(req.prompt) + replay
                lengths[r] = L
                valid[r] = True
                slots[r] = slot
                keys[r] = np.asarray(
                    jax.random.fold_in(self._base_key, req.seed))
                temp[r] = req.temperature
                top_k[r] = req.top_k or 0
                startno[r] = len(replay)
        except Exception:
            # atomic admission: a mid-batch reject (seed collision, bad
            # shape) must not leak the slots already acquired
            for slot in acquired:
                self.pool.release(slot)
            raise
        # padding rows target a real slot but carry valid=False — the
        # inject keeps the pool row, so they write nowhere
        for r in range(len(requests), B_pf):
            slots[r] = acquired[0]

        tel = self._tel
        fn = _pick(_prefill_inject_donated, _prefill_inject_plain)
        with (tel.span("engine.prefill", n=len(requests))
              if tel is not None else NULL_SPAN):
            self.pool.cache, first = fn(
                self.model, self.params, self.pool.cache, prompts,
                lengths, slots, valid, keys, temp, top_k, startno)
            first = np.asarray(first)
        if tel is not None:
            tel.event("engine.prefill", n=len(requests),
                      ids=[r.id for r in requests],
                      slots=[int(s) for s in acquired])

        done: List[Completion] = []
        for r, req in enumerate(requests):
            slot = acquired[r]
            tok = int(first[r])
            toks = list(req.replay_tokens or ()) + [tok]
            self._tokens[slot] = toks
            self.tokens_generated += 1
            hit_eos = req.eos_id is not None and tok == req.eos_id
            if hit_eos or len(toks) >= req.max_new_tokens:
                done.append(self._retire(
                    slot, FINISH_EOS if hit_eos else FINISH_LENGTH))
                continue
            self._cur[slot, 0] = tok
            self._pos[slot, 0] = req.prompt_len + len(toks) - 1
            self._active[slot] = True
            self._remaining[slot] = req.max_new_tokens - len(toks)
            self._temp[slot] = req.temperature
            self._top_k[slot] = req.top_k or 0
            self._eos[slot] = -1 if req.eos_id is None else req.eos_id
            self._keys[slot] = keys[r]
            self._stepno[slot] = len(toks)
        self.prefills += 1
        return done

    def step(self) -> List[Completion]:
        """Advance every in-flight request up to ``steps_per_dispatch``
        tokens in one program dispatch; returns the completions of rows
        that finished inside the block (eos or budget — rows finishing at
        sub-step k park idempotently for the remaining sub-steps)."""
        if not self._active.any():
            return []
        faults.fire("serve.dispatch")
        tel = self._tel
        fn = _pick(_engine_step_donated, _engine_step_plain)
        with (tel.span("engine.step", active=int(self._active.sum()))
              if tel is not None else NULL_SPAN):
            (self.pool.cache, cur, pos, active, remaining, stepno,
             emitted, finished) = fn(
                self.model, self.params, self.pool.cache, self._cur,
                self._pos, self._active, self._remaining, self._temp,
                self._top_k, self._eos, self._keys, self._stepno,
                steps=self.steps_per_dispatch)
        # np.array (copy): jax outputs view as read-only buffers, and the
        # next prefill writes these rows in place
        self._cur = np.array(cur)
        self._pos = np.array(pos)
        self._active = np.array(active)
        self._remaining = np.array(remaining)
        self._stepno = np.array(stepno)
        emitted = np.asarray(emitted)      # (steps, B), −1 = parked row
        finished = np.asarray(finished)    # (steps, B)

        done: List[Completion] = []
        for slot in range(self.num_slots):
            toks = [int(t) for t in emitted[:, slot] if t >= 0]
            if not toks:
                continue
            self._tokens[slot].extend(toks)
            self.tokens_generated += len(toks)
            if finished[:, slot].any():
                req = self.pool.active[slot]
                hit_eos = req.eos_id is not None and toks[-1] == req.eos_id
                done.append(self._retire(
                    slot, FINISH_EOS if hit_eos else FINISH_LENGTH))
        self.steps += 1
        self.decode_substeps += self.steps_per_dispatch
        if tel is not None:
            tel.event("engine.step", dispatch=self.steps,
                      active=self.active_count, retired=len(done))
        return done

    # -------------------------------------------------------- lifecycle
    def snapshot_in_flight(self) -> List:
        """``[(request, tokens_emitted_so_far)]`` for every in-flight
        slot, in slot order — what a supervisor needs to re-admit this
        engine's work after a crash (copies, never live buffers)."""
        return [(self.pool.active[slot],
                 list(self._tokens.get(slot, [])))
                for slot in sorted(self.pool.active)]

    def cancel(self, request_id: int,
               reason: str = FINISH_TIMEOUT) -> Optional[Completion]:
        """Abort an in-flight request (deadline expiry): frees its slot,
        returns a completion with the tokens produced so far."""
        slot = self.pool.slot_of(request_id)
        if slot is None:
            return None
        return self._retire(slot, reason)

    def _retire(self, slot: int, reason: str) -> Completion:
        req = self.pool.release(slot)
        self._active[slot] = False
        tokens = self._tokens.pop(slot, [])
        return Completion(
            request_id=req.id, prompt=list(req.prompt), tokens=tokens,
            finish_reason=reason, arrival_time=req.arrival_time,
            first_token_time=req.first_token_time)
