"""Adapter residency registry for batched multi-LoRA serving.

S-LoRA (Sheng et al., 2023) and Punica (Chen et al., 2023) serve many
LoRA fine-tunes from ONE base model by keeping a resident **adapter
bank** — a ``(num_adapters, ...)`` leading axis on every low-rank pair —
inside the shared batch programs, with each batch row gathering its own
``(A, B)`` slice by integer id. The bank's shape is part of the compiled
program, so adapter churn (hot load, unload, eviction) is a *data*
write, never a recompile; rows bound to different adapters batch in one
dispatch.

This module is the **host-side bookkeeping half** of that design: which
adapter *name* owns which bank *index*, LRU residency with deterministic
eviction, per-adapter refcounts (an adapter pinned by in-flight rows is
never evicted under it), and exact byte accounting. It is deliberately
pure — no jax, no telemetry, no device state. The
:class:`~ray_lightning_tpu.serve.engine.ServeEngine` owns the device
half (grafting banks with :func:`~ray_lightning_tpu.models.lora.
install_lora_bank`, writing slots with :func:`~ray_lightning_tpu.models.
lora.install_adapter`) and emits the ``engine.adapter_*`` events; the
registry just answers "what lives where".

Shedding model (mirrors :class:`~ray_lightning_tpu.serve.tenancy.
ClassQueueFull`): naming an unknown/evicted adapter at submit raises
:class:`UnknownAdapter` — a ``ValueError`` subclass, so every existing
admission-refusal path (client trace shed → ``FINISH_REJECTED``,
supervisor refusal re-raise) handles it without new plumbing — and
loading into a bank whose every slot is pinned raises
:class:`AdapterBankFull`. Both carry registry context as ``[k=v]``
attributes via the shared :class:`~ray_lightning_tpu.serve.request.
OccupancyError` base.

Eviction is **deterministic**: least-recently-*bound* resident with a
zero refcount, ties broken by load order (an :class:`collections.
OrderedDict` walk). Same load/bind sequence → same evictee, always —
pinned by the bench's eviction-under-pressure check.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ray_lightning_tpu.serve.request import OccupancyError

__all__ = ["AdapterRegistry", "AdapterBankFull", "UnknownAdapter"]


class AdapterBankFull(OccupancyError):
    """Every bank slot is resident AND pinned by in-flight rows — the
    load cannot evict anything. Carries ``capacity``/``pinned``
    context; retry after the pinning requests retire, or size the bank
    with a larger ``max_resident_adapters``."""


class UnknownAdapter(OccupancyError, ValueError):
    """A request named an adapter that is not resident (never loaded,
    or evicted since). ``ValueError`` by inheritance so the existing
    shed/refusal paths (client ``(QueueFull, ValueError)`` catch,
    supervisor refusal re-raise) treat it as the admission refusal it
    is. Carries ``adapter``/``resident`` context."""


class AdapterRegistry:
    """Name → bank-index map with LRU residency and refcounts.

    ``capacity`` is the bank's ``num_adapters`` (fixed at engine build —
    the compiled programs' shapes depend on it). ``bytes_per_adapter``
    is the exact per-slot device footprint (one adapter's slices across
    every bank, from :func:`~ray_lightning_tpu.models.lora.
    adapter_bytes`) so :meth:`resident_bytes` is accounting, not
    estimate.
    """

    def __init__(self, capacity: int, bytes_per_adapter: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.bytes_per_adapter = int(bytes_per_adapter)
        # name -> index, maintained in LRU order (oldest first): admit
        # and bind both move the touched name to the end
        self._resident: "OrderedDict[str, int]" = OrderedDict()
        self._refcount: Dict[str, int] = {}
        self._free: List[int] = list(range(self.capacity))
        self.loads = 0
        self.evictions = 0

    # ------------------------------------------------------------ views
    @property
    def residents(self) -> List[str]:
        """Resident names, least-recently-bound first (eviction order)."""
        return list(self._resident)

    def resident(self, name: str) -> bool:
        return name in self._resident

    def index_of(self, name: str) -> int:
        """Bank index of a resident adapter; :class:`UnknownAdapter`
        otherwise (the submit-time refusal)."""
        idx = self._resident.get(name)
        if idx is None:
            raise UnknownAdapter(
                f"adapter {name!r} is not resident — load it with "
                "load_adapter() (it may have been evicted)",
                adapter=name, resident=self.residents,
                capacity=self.capacity)
        return idx

    def refcount(self, name: str) -> int:
        return self._refcount.get(name, 0)

    def resident_bytes(self) -> int:
        """Exact device bytes attributable to *resident* adapters (the
        bank itself is ``capacity * bytes_per_adapter`` at rest —
        residency accounting reports the slice actually in use)."""
        return len(self._resident) * self.bytes_per_adapter

    # -------------------------------------------------------- lifecycle
    def admit(self, name: str) -> Tuple[int, Optional[str]]:
        """Claim a bank index for ``name``: reuse its resident index,
        else a free slot, else evict the LRU refcount-0 resident.
        Returns ``(index, evicted_name)``; raises
        :class:`AdapterBankFull` when every slot is pinned."""
        if not name or not isinstance(name, str):
            raise ValueError(
                f"adapter name must be a non-empty string, got {name!r}")
        idx = self._resident.get(name)
        if idx is not None:
            self._resident.move_to_end(name)
            return idx, None
        evicted: Optional[str] = None
        if self._free:
            idx = self._free.pop(0)
        else:
            victim = next((n for n in self._resident
                           if self._refcount.get(n, 0) == 0), None)
            if victim is None:
                raise AdapterBankFull(
                    f"cannot load adapter {name!r}: all {self.capacity} "
                    "bank slots are pinned by in-flight requests",
                    capacity=self.capacity,
                    pinned=sum(1 for n in self._resident
                               if self._refcount.get(n, 0) > 0))
            idx = self._resident.pop(victim)
            self._refcount.pop(victim, None)
            self.evictions += 1
            evicted = victim
        self._resident[name] = idx
        self._refcount[name] = 0
        self.loads += 1
        return idx, evicted

    def unload(self, name: str) -> int:
        """Release ``name``'s slot back to the free list. Refuses while
        in-flight rows still pin it (eviction safety is the same rule
        stated explicitly)."""
        idx = self.index_of(name)
        refs = self._refcount.get(name, 0)
        if refs > 0:
            raise OccupancyError(
                f"cannot unload adapter {name!r}: {refs} in-flight "
                "request(s) still bound to it",
                adapter=name, refcount=refs)
        del self._resident[name]
        self._refcount.pop(name, None)
        self._free.append(idx)
        self._free.sort()
        return idx

    # --------------------------------------------------------- pinning
    def bind(self, name: str) -> int:
        """Pin ``name`` for one in-flight request (admission): bumps
        the refcount, touches LRU recency, returns the bank index. The
        index is stable for the request's whole residency — eviction
        skips pinned adapters."""
        idx = self.index_of(name)
        self._refcount[name] = self._refcount.get(name, 0) + 1
        self._resident.move_to_end(name)
        return idx

    def unbind(self, name: str) -> None:
        """Drop one request's pin (retire/cancel/rollback)."""
        refs = self._refcount.get(name, 0)
        if refs <= 0:
            raise ValueError(
                f"unbind of adapter {name!r} without a matching bind")
        self._refcount[name] = refs - 1
