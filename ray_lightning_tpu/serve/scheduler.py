"""FIFO admission + prefill/decode interleaving policy.

The scheduler owns the *waiting* side of the engine: a bounded FIFO queue
(admission control — a full queue rejects at submit time, it never grows
unboundedly under overload), per-request deadlines (expired requests are
dropped before they ever touch the accelerator), and the one real policy
decision of continuous batching: **when to spend a step on prefill instead
of decode**.

A prefill pass stalls every in-flight decode for one program dispatch but
fills free slots (raising decode utilization and cutting queue latency);
decoding first drains in-flight requests sooner but leaves slots idle.
``SchedulerConfig.prefill_priority`` moves along exactly that trade:

- ``1.0`` (default): prefill whenever a request waits and a slot is free —
  lowest time-to-first-token, the latency-serving default.
- ``0.0``: batch prefills — wait until enough requests are queued to fill
  a whole prefill batch (or the engine has nothing to decode), amortizing
  the prefill dispatch across more injected rows — highest decode
  throughput under sustained load.
- values in between scale the batching threshold proportionally.
"""
from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Deque, List, Optional, Tuple

from ray_lightning_tpu.serve.request import Request

# scheduler verdicts for the next engine dispatch
ACTION_PREFILL = "prefill"
ACTION_STEP = "step"
ACTION_IDLE = "idle"


class QueueFull(RuntimeError):
    """Admission control: the waiting queue is at max_queue_depth."""


@dataclasses.dataclass
class SchedulerConfig:
    max_queue_depth: int = 64
    # 1.0 = inject eagerly (best TTFT), 0.0 = batch prefills (best decode
    # throughput); see the module docstring
    prefill_priority: float = 1.0
    # applied to requests submitted without an explicit deadline, as an
    # offset from arrival (clock units of the driving client); None = no
    # default deadline
    default_deadline: Optional[float] = None

    def __post_init__(self):
        if not 0.0 <= self.prefill_priority <= 1.0:
            raise ValueError(
                f"prefill_priority must be in [0, 1], got "
                f"{self.prefill_priority}")
        if self.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got "
                f"{self.max_queue_depth}")


class FifoScheduler:
    """Bounded FIFO queue + the prefill/decode interleaving policy."""

    def __init__(self, config: Optional[SchedulerConfig] = None):
        self.config = config or SchedulerConfig()
        self._queue: Deque[Request] = deque()

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def waiting(self) -> List[Request]:
        return list(self._queue)

    def submit(self, request: Request,
               now: Optional[float] = None) -> None:
        """Enqueue, or raise :class:`QueueFull` — overload sheds at the
        door instead of growing an unbounded backlog."""
        if len(self._queue) >= self.config.max_queue_depth:
            raise QueueFull(
                f"queue at max_queue_depth={self.config.max_queue_depth}")
        if (request.deadline is None
                and self.config.default_deadline is not None
                and now is not None):
            request.deadline = now + self.config.default_deadline
        self._queue.append(request)

    def requeue_front(self, requests: List[Request]) -> None:
        """Put popped-but-not-dispatched requests back at the queue head
        in their original order (e.g. a prefill deferred because its seed
        collides with an in-flight request's sample stream)."""
        for req in reversed(requests):
            self._queue.appendleft(req)

    def expire(self, now: float) -> List[Request]:
        """Drop queued requests whose deadline has passed; returns them
        (the client retires each as a timeout completion)."""
        expired = [r for r in self._queue
                   if r.deadline is not None and now >= r.deadline]
        if expired:
            gone = {id(r) for r in expired}
            self._queue = deque(
                r for r in self._queue if id(r) not in gone)
        return expired

    def next_action(self, engine) -> Tuple[str, List[Request]]:
        """Decide the next engine dispatch.

        Returns ``(ACTION_PREFILL, requests)`` with the requests POPPED
        from the queue, ``(ACTION_STEP, [])`` to advance decode, or
        ``(ACTION_IDLE, [])`` when there is nothing to do (the client
        waits for the next arrival).
        """
        free = engine.free_slots
        if self._queue and free > 0:
            k = min(len(self._queue), free, engine.prefill_batch)
            if engine.active_count == 0:
                return ACTION_PREFILL, self._pop(k)
            # batching threshold: how many waiters justify stalling the
            # in-flight decodes for one prefill dispatch
            need = max(1, math.ceil(
                (1.0 - self.config.prefill_priority)
                * min(engine.prefill_batch, free)))
            if len(self._queue) >= need:
                return ACTION_PREFILL, self._pop(k)
        if engine.active_count > 0:
            return ACTION_STEP, []
        return ACTION_IDLE, []

    def _pop(self, k: int) -> List[Request]:
        return [self._queue.popleft() for _ in range(k)]
