"""FIFO admission + prefill/decode/chunk interleaving policy.

The scheduler owns the *waiting* side of the engine: a bounded FIFO queue
(admission control — a full queue rejects at submit time, it never grows
unboundedly under overload), per-request deadlines (expired requests are
dropped before they ever touch the accelerator), and the real policy
decisions of continuous batching: **when to spend a step on prefill
instead of decode**, and — chunked engines — **how to interleave a long
prompt's chunk dispatches with in-flight decode**.

A prefill pass stalls every in-flight decode for one program dispatch but
fills free slots (raising decode utilization and cutting queue latency);
decoding first drains in-flight requests sooner but leaves slots idle.
``SchedulerConfig.prefill_priority`` moves along exactly that trade:

- ``1.0`` (default): prefill whenever a request waits and a slot is free —
  lowest time-to-first-token, the latency-serving default.
- ``0.0``: batch prefills — wait until enough requests are queued to fill
  a whole prefill batch (or the engine has nothing to decode), amortizing
  the prefill dispatch across more injected rows — highest decode
  throughput under sustained load.
- values in between scale the batching threshold proportionally.

Chunk interleaving is deliberately NOT a knob: while decode rows are
active, chunk and decode dispatches strictly alternate, so an in-flight
request's worst decode stall is ONE chunk-sized dispatch (that bound is
the whole point of chunked prefill — ``decode_stall_p99_ms`` in the
bench); with nothing decoding, chunks stream back-to-back.

Admission is **page-aware** on paged engines: the scheduler pops only the
queue-head prefix the engine can actually seat
(``engine.admissible_prefix`` — slots, batched-program width, cumulative
page demand against free + evictable pages), keeping FIFO order — a
short request never jumps a long one that's next in line.
"""
from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Deque, List, Optional, Tuple

from ray_lightning_tpu.serve.request import OccupancyError, Request

# scheduler verdicts for the next engine dispatch
ACTION_PREFILL = "prefill"
ACTION_STEP = "step"
ACTION_CHUNK = "chunk"
ACTION_IDLE = "idle"


class QueueFull(OccupancyError):
    """Admission control: the waiting queue is at max_queue_depth.

    Carries occupancy context for shed-load callers: ``queue_depth``
    (the bound that was hit) and ``oldest_age`` (how long the head of
    the queue has been waiting, in the driving client's clock units —
    None when no clock/arrival data is available). An old head means the
    server is drowning; a young one means a burst just landed.
    """

    def __init__(self, message: str, *, queue_depth: Optional[int] = None,
                 oldest_age: Optional[float] = None, **ctx):
        # **ctx: subclasses and the tenancy layer extend the shed
        # context (per-class queue depths / oldest-age breakdown, the
        # saturated class's name) — the OccupancyError base renders any
        # keys into the message suffix and exposes them as attributes
        super().__init__(message, queue_depth=queue_depth,
                         oldest_age=oldest_age, **ctx)


@dataclasses.dataclass
class SchedulerConfig:
    max_queue_depth: int = 64
    # 1.0 = inject eagerly (best TTFT), 0.0 = batch prefills (best decode
    # throughput); see the module docstring
    prefill_priority: float = 1.0
    # applied to requests submitted without an explicit deadline, as an
    # offset from arrival (clock units of the driving client); None = no
    # default deadline
    default_deadline: Optional[float] = None

    def __post_init__(self):
        if not 0.0 <= self.prefill_priority <= 1.0:
            raise ValueError(
                f"prefill_priority must be in [0, 1], got "
                f"{self.prefill_priority}")
        if self.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got "
                f"{self.max_queue_depth}")


class FifoScheduler:
    """Bounded FIFO queue + the prefill/decode/chunk interleaving
    policy."""

    def __init__(self, config: Optional[SchedulerConfig] = None):
        self.config = config or SchedulerConfig()
        self._queue: Deque[Request] = deque()
        # chunk/decode alternation latch — see the module docstring
        self._last_was_chunk = False

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def waiting(self) -> List[Request]:
        return list(self._queue)

    def oldest_age(self, now: Optional[float]) -> Optional[float]:
        """How long the queue head has been waiting (clock units), or
        ``None`` on an empty queue / missing clock data. The fleet
        router reads this as a live backpressure signal; :class:`QueueFull`
        carries it as shed context."""
        if not self._queue or now is None:
            return None
        head = self._queue[0]
        if head.arrival_time is None:
            return None
        return now - head.arrival_time

    def submit(self, request: Request,
               now: Optional[float] = None) -> None:
        """Enqueue, or raise :class:`QueueFull` — overload sheds at the
        door instead of growing an unbounded backlog."""
        if len(self._queue) >= self.config.max_queue_depth:
            raise QueueFull(
                f"queue at max_queue_depth={self.config.max_queue_depth}",
                queue_depth=len(self._queue),
                oldest_age=self.oldest_age(now))
        self._stamp_admission(request, now, self.config.default_deadline)
        self._queue.append(request)

    @staticmethod
    def _stamp_admission(request: Request, now: Optional[float],
                         deadline_offset: Optional[float]) -> None:
        """The one copy of admission stamping, shared with the tenancy
        scheduler (which passes its per-class deadline offset) so the
        two submit paths cannot drift: apply the default deadline as an
        offset from ``now``, and stamp arrival at admission so
        ``oldest_age`` works for direct scheduler callers too (the
        driving client's own post-submit stamp uses the same ``now``,
        so this is a no-op there)."""
        if now is None:
            return
        if request.deadline is None and deadline_offset is not None:
            request.deadline = now + deadline_offset
        if request.arrival_time is None:
            request.arrival_time = now

    def requeue_front(self, requests: List[Request]) -> None:
        """Put popped-but-not-dispatched requests back at the queue head
        in their original order (e.g. a prefill deferred because its seed
        collides with an in-flight request's seed)."""
        for req in reversed(requests):
            self._queue.appendleft(req)

    def expire(self, now: float) -> List[Request]:
        """Drop queued requests whose deadline has passed; returns them
        (the client retires each as a timeout completion)."""
        expired = [r for r in self._queue
                   if r.deadline is not None and now >= r.deadline]
        if expired:
            gone = {id(r) for r in expired}
            self._queue = deque(
                r for r in self._queue if id(r) not in gone)
        return expired

    def _admit_width(self, engine) -> int:
        """How many queue-head requests :meth:`next_action` would pop
        for a prefill RIGHT NOW — 0 when the next dispatch is not a
        prefill. Pure query (no pops, no latch flips): the decision
        half of ``next_action``, shared with :meth:`peek_action` so the
        lookahead can never drift from the real policy."""
        free = engine.free_slots
        chunks = getattr(engine, "chunk_pending", 0)
        if not self._queue or free <= 0:
            return 0
        k = min(len(self._queue), free)
        probe = getattr(engine, "admissible_prefix", None)
        if probe is not None:
            # page-aware admission: only the head prefix that fits
            # slots, pages AND the batched-program width (the probe
            # owns the width rule — chunk-routed requests consume
            # none of it, so pre-capping at prefill_batch here would
            # needlessly throttle them). The probe's verdict over a
            # FIFO prefix is prefix-stable, so feed it the head
            # slice, not a copy of the whole queue.
            k = min(k, probe([self._queue[i] for i in range(k)]))
        else:
            k = min(k, engine.prefill_batch)
        if k <= 0:
            return 0
        if engine.active_count == 0 and not chunks:
            return k
        # batching threshold: how many waiters justify stalling
        # the in-flight decodes for one prefill dispatch
        need = max(1, math.ceil(
            (1.0 - self.config.prefill_priority)
            * min(engine.prefill_batch, free)))
        return k if len(self._queue) >= need else 0

    def next_action(self, engine) -> Tuple[str, List[Request]]:
        """Decide the next engine dispatch.

        Returns ``(ACTION_PREFILL, requests)`` with the requests POPPED
        from the queue, ``(ACTION_CHUNK, [])`` to advance the head
        mid-chunking prompt, ``(ACTION_STEP, [])`` to advance decode, or
        ``(ACTION_IDLE, [])`` when there is nothing to do (the client
        waits for the next arrival).
        """
        k = self._admit_width(engine)
        if k > 0:
            return ACTION_PREFILL, self._pop(k)
        return self.drain_action(engine), []

    def peek_action(self, engine) -> str:
        """What :meth:`next_action` would return, WITHOUT popping
        requests or flipping the chunk/decode alternation latch.

        The fleet's runnable-replica probe reads this (the async client
        itself pipelines off ``next_action`` returning ``ACTION_STEP``
        — this lookahead shares ``_admit_width`` with it, so the two
        can't drift). The verdict is computed against the engine's
        SYNCED host state, so with a dispatch in flight it answers for
        the synced frontier — exactly the state the next *enqueue*
        would be built from."""
        if self._admit_width(engine) > 0:
            return ACTION_PREFILL
        return self._drain_verdict(engine, self._last_was_chunk)[0]

    @staticmethod
    def _drain_verdict(engine, latch: bool) -> Tuple[str, bool]:
        """The chunk/decode half of the policy as a PURE function of
        the alternation latch: ``(action, new_latch)``.
        :meth:`drain_action` commits the latch, :meth:`peek_action`
        discards it — one copy of the policy, so the lookahead cannot
        drift from what the tick actually dispatches."""
        if getattr(engine, "chunk_pending", 0):
            if engine.active_count > 0 and latch:
                return ACTION_STEP, False
            return ACTION_CHUNK, True
        if engine.active_count > 0:
            return ACTION_STEP, False
        return ACTION_IDLE, False

    def drain_action(self, engine) -> str:
        """The chunk/decode half of the policy: strict alternation while
        decode rows are active (the one-chunk stall bound), chunks
        back-to-back otherwise. The client also calls this directly when
        an admission tick dispatched nothing (every popped request
        seed-deferred) — the substitute dispatch must honor the same
        bound, or a persistent deferral would let chunks starve decode."""
        action, self._last_was_chunk = self._drain_verdict(
            engine, self._last_was_chunk)
        return action

    def _pop(self, k: int) -> List[Request]:
        return [self._queue.popleft() for _ in range(k)]
