"""ZeRO-1 sharded data parallelism (RayShardedStrategy parity).

The reference's ``RayShardedStrategy`` (``ray_lightning/ray_ddp_sharded.py:
12-13``) mixes FairScale's OSS optimizer-state sharding into the DDP
strategy. TPU-native equivalent: identical mesh and batch layout to DDP, but
every **optimizer-state** array is sharded along its largest divisible dim
over ``dp``. XLA then materializes the ZeRO-1 dance — reduce-scatter grads,
shard-local optimizer update, all-gather fresh params — directly from the
sharding annotations; memory drops by ~|opt_state|·(dp-1)/dp exactly as the
reference's README claims for FairScale (``README.md:117-119``).
"""
from __future__ import annotations

from typing import Any

from ray_lightning_tpu.parallel import sharding as shardlib
from ray_lightning_tpu.parallel.mesh import DP_AXIS
from ray_lightning_tpu.strategies.ddp import RayStrategy


class RayShardedStrategy(RayStrategy):
    """DDP with optimizer state sharded over the ``dp`` axis (ZeRO-1)."""
    strategy_name = "ddp_sharded_ray"

    def opt_state_sharding(self, abstract_opt_state: Any) -> Any:
        return shardlib.shard_pytree_along_axis(
            abstract_opt_state, self.mesh, DP_AXIS)


ZeroOneStrategy = RayShardedStrategy
