"""Composite multi-axis mesh strategy (net-new beyond the reference).

The reference's three strategies are all 1-D data parallelism
(SURVEY.md §2.3). On TPU pods the idiomatic layout is a *multi-axis* mesh —
e.g. ``dp×fsdp`` for large-batch ZeRO-3, or ``dp×tp`` with tensor-parallel
weight sharding riding the tightest ICI loops. ``MeshStrategy`` exposes that
directly: pass the axis sizes, optionally a parameter partition rule, and
the trainer compiles one program over the whole layout.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

from ray_lightning_tpu.parallel import sharding as shardlib
from ray_lightning_tpu.parallel.mesh import FSDP_AXIS, MeshSpec
from ray_lightning_tpu.strategies.base import Strategy


class MeshStrategy(Strategy):
    """Explicit multi-axis parallelism.

    Args:
        axes: mesh axis → size, e.g. ``{"dp": 2, "fsdp": 4}``. One axis may
            be ``-1`` (absorb remaining devices). ``num_workers`` is derived
            as the product (data-parallel world size = dp×fsdp for sampler
            parity).
        param_rule: optional ``(path, leaf) -> PartitionSpec`` for
            parameters (tensor-parallel layouts); default shards along
            ``fsdp`` when present, else replicates.
        dcn_axes: multi-slice pods — axis → DCN factor (how many ways the
            axis crosses slice boundaries; must divide the axis size). The
            DCN partition is laid out OUTER so cross-slice traffic carries
            only that axis's collectives (put ``dp`` here; keep tp/sp on
            ICI). E.g. two v4-32 slices running dp=8 × tp=4:
            ``MeshStrategy(axes={"dp": 8, "tp": 4}, dcn_axes={"dp": 2})``.
    """
    strategy_name = "mesh_tpu"

    def __init__(self,
                 axes: Dict[str, int],
                 param_rule: Optional[Callable] = None,
                 dcn_axes: Optional[Dict[str, int]] = None,
                 **kwargs):
        self._axes = dict(axes)
        self._dcn_axes = dict(dcn_axes or {})
        # fail fast on spec errors (axis typos, non-dividing or
        # non-outermost dcn factors) at the construction site — the spec
        # needs no device count, so this is safe driver-side
        MeshSpec(self._axes, dcn_axes=self._dcn_axes)
        self._param_rule = param_rule
        if "num_workers" not in kwargs:
            # product of the fixed axes; with a -1 wildcard the true world
            # size is only known once the mesh is built (world_size and
            # distributed_sampler_kwargs report the resolved value)
            kwargs["num_workers"] = math.prod(
                s for s in axes.values() if s != -1)
        super().__init__(**kwargs)

    def mesh_spec(self) -> MeshSpec:
        return MeshSpec(self._axes, dcn_axes=self._dcn_axes)

    def set_world_size(self, num_workers: int) -> None:
        """Refused: ``num_workers`` here is DERIVED from the axis spec —
        a bare resize would silently desync mesh and rank model. Elastic
        multi-axis restarts must rebuild the strategy with resized axes
        (which axis absorbs the loss is a layout decision, not a
        count)."""
        raise RuntimeError(
            f"MeshStrategy derives num_workers from its axis spec "
            f"{self._axes}; construct a new MeshStrategy with resized "
            "axes instead of set_world_size (elastic GangSupervisor "
            "resize supports the 1-D dp/fsdp strategy families)")

    @property
    def world_size(self) -> int:
        sizes = list(self._axes.values())
        if -1 not in sizes:
            # fixed axes: no device query — a client-mode driver (off the
            # cluster, no TPUs) must be able to build strategy + trainer
            # without ever touching jax.devices() (round-1 review: building
            # the mesh here broke exactly that)
            return math.prod(sizes)
        # wildcard axis: resolved only where devices exist (worker side)
        return math.prod(self.mesh.shape[a] for a in self.mesh.axis_names)

    @property
    def distributed_sampler_kwargs(self) -> Dict[str, int]:
        return dict(num_replicas=self.world_size, rank=self.global_rank)

    def params_sharding(self, abstract_params: Any) -> Any:
        mesh = self.mesh
        if self._param_rule is not None:
            return shardlib.apply_rule(abstract_params, mesh,
                                       self._param_rule)
        if FSDP_AXIS in mesh.axis_names and mesh.shape[FSDP_AXIS] > 1:
            return shardlib.shard_pytree_along_axis(abstract_params, mesh,
                                                    FSDP_AXIS)
        return shardlib.replicated_pytree(abstract_params, mesh)

    def opt_state_sharding(self, abstract_opt_state: Any) -> Any:
        mesh = self.mesh
        if self._param_rule is not None:
            # Optimizer moments mirror the params pytree, so param paths
            # appear as suffixes of opt-state paths and the same rule
            # lands the same layout (scalars/counters match nothing → P()).
            # fallback_replicate: factored states (adafactor v_row/v_col
            # and their (1,) placeholders) match param paths by NAME but
            # not by shape — those leaves replicate instead of tripping
            # pjit's divisibility check.
            return shardlib.apply_rule(abstract_opt_state, mesh,
                                       self._param_rule,
                                       fallback_replicate=True)
        if FSDP_AXIS in mesh.axis_names and mesh.shape[FSDP_AXIS] > 1:
            return shardlib.shard_pytree_along_axis(abstract_opt_state, mesh,
                                                    FSDP_AXIS)
        return shardlib.replicated_pytree(abstract_opt_state, mesh)
