"""Sequence/context parallelism: the long-context strategy.

Net-new beyond the reference (SURVEY.md §5: long-context "entirely absent"
upstream), first-class here per the TPU design brief. The layout is a
``dp×sp`` mesh: the batch dim splits over ``dp`` and the *sequence* dim
splits over ``sp``, so per-chip activation memory scales O(T / sp) — the
lever that makes million-token contexts fit.

Two attention paths compose with it:

- ``attention_impl='ring'`` (recommended): the model nests a ``shard_map``
  over ``sp`` around each attention call and K/V shards rotate via
  ``lax.ppermute`` ICI neighbor hops (``parallel/ring_attention.py``) —
  communication overlaps the blockwise compute, nothing materializes the
  full sequence;
- ``attention_impl='dot'``: plain GSPMD — XLA all-gathers K/V over ``sp``
  inside the jitted step. Correct, simpler, and fine at moderate lengths.

Everything else (embeddings, layernorms, MLP, loss) is token-local, so the
standard jit-with-shardings path handles it: the strategy only owes the
batch layout and a rank model in which *data* replicas = dp (sequence
shards see the same samples).
"""
from __future__ import annotations

from typing import Dict

from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_tpu.strategies.mesh_strategy import MeshStrategy


class SequenceParallelStrategy(MeshStrategy):
    """``dp×sp`` mesh with the sequence dim of every batch leaf sharded.

    Args:
        dp: data-parallel size (batch split). ``-1`` absorbs remaining
            devices.
        sp: sequence-parallel size (sequence split).
        seq_dim: which batch-leaf dim is the sequence (default 1 — the
            (batch, seq, ...) convention every bundled model uses). Batch
            leaves must have at least ``seq_dim + 1`` dims.
    """
    strategy_name = "sequence_parallel_tpu"

    def __init__(self, dp: int = 1, sp: int = 2, seq_dim: int = 1,
                 **kwargs):
        if sp < 2:
            raise ValueError(
                "SequenceParallelStrategy needs sp >= 2; use RayStrategy "
                "or MeshStrategy for pure data parallelism")
        super().__init__(axes={"dp": dp, "sp": sp}, **kwargs)
        self.seq_dim = int(seq_dim)

    def batch_sharding(self) -> NamedSharding:
        spec = [None] * (self.seq_dim + 1)
        spec[0] = "dp"
        spec[self.seq_dim] = "sp"
        return NamedSharding(self.mesh, P(*spec))

    @property
    def distributed_sampler_kwargs(self) -> Dict[str, int]:
        """Data replicas = dp only: every sp shard holds (a slice of) the
        same samples, so host-side feeding must not skip over them.

        The sampler rank is the *dp coordinate*, not the flat global rank:
        the mesh is dp-major with contiguous per-process device blocks
        (asserted at mesh build), so process r sits in dp slice
        ``r // sp`` — its sp peers get the same rank and load the same
        samples. (The default input path, ``put_global_batch``, feeds every
        process the full global batch and transfers only owned shards, so
        these kwargs matter only for rank-sliced custom loaders.)
        """
        dp = self._axes["dp"]
        sp = self._axes["sp"]
        if dp == -1:
            # wildcard resolves against devices — worker-side only (a
            # client-mode driver passes a fixed dp and never gets here)
            dp = self.mesh.shape["dp"]
        rank = self.global_rank // sp
        if self._is_remote:
            # a multi-host process owns a block of devices, so its dp
            # coordinate is that of its FIRST device in mesh-flat order
            # (one-device-per-process reduces to global_rank // sp);
            # device queries are worker-side only, keeping client mode
            # device-free on the driver
            import jax
            rank = (self.global_rank * jax.local_device_count()) // sp
        return dict(num_replicas=dp, rank=rank)
