"""Data-parallel strategy — the flagship (RayStrategy parity).

The reference's ``RayStrategy`` (``ray_lightning/ray_ddp.py:30-343``) wraps
the model in ``DistributedDataParallel`` so NCCL all-reduces gradients each
backward. TPU-native equivalent: parameters and optimizer state are
**replicated** over a 1-D ``dp`` mesh, the batch is **sharded** over it, and
XLA compiles the gradient ``psum`` into the step program, overlapping it with
backprop compute over ICI — same semantics, no wrapper object, no per-step
Python.
"""
from __future__ import annotations

from ray_lightning_tpu.parallel.mesh import DP_AXIS, MeshSpec
from ray_lightning_tpu.strategies.base import Strategy


class RayStrategy(Strategy):
    """Drop-in data-parallel strategy. ``num_workers`` = DP shards (chips).

    Constructor parity: ``ray_ddp.py:76-126`` (``num_workers``,
    ``num_cpus_per_worker``, ``use_gpu``/``use_tpu``, ``init_hook``,
    ``resources_per_worker``, ``worker_runtime_env``). DDP kwargs such as
    ``find_unused_parameters`` are accepted and ignored — dead-parameter
    detection is static under XLA (unused params simply get zero gradients
    from ``jax.grad``), so the failure mode the flag works around cannot
    occur.
    """
    strategy_name = "ddp_ray"

    def mesh_spec(self) -> MeshSpec:
        return MeshSpec({DP_AXIS: self.num_workers})


# TPU-native alias: same object, name that says what it does.
DataParallelStrategy = RayStrategy
