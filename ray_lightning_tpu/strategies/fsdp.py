"""Fully-sharded data parallelism (params + grads + optimizer state).

Net-new beyond the reference's ZeRO-1 (`SURVEY.md` §2.3 marks FSDP as the
TPU equivalent of FairScale's sharded training, `SURVEY.md` §2.2 row
FairScale): every parameter and optimizer-state array is sharded along its
largest divisible dim over the ``fsdp`` axis; XLA's SPMD partitioner
all-gathers weights just-in-time per layer and reduce-scatters gradients,
which is exactly the FSDP schedule, derived from annotations instead of
hand-written hooks.
"""
from __future__ import annotations

from typing import Any

from ray_lightning_tpu.parallel import sharding as shardlib
from ray_lightning_tpu.parallel.mesh import FSDP_AXIS, MeshSpec
from ray_lightning_tpu.strategies.base import Strategy


class FSDPStrategy(Strategy):
    strategy_name = "fsdp_tpu"

    def mesh_spec(self) -> MeshSpec:
        return MeshSpec({FSDP_AXIS: self.num_workers})

    def params_sharding(self, abstract_params: Any) -> Any:
        return shardlib.shard_pytree_along_axis(
            abstract_params, self.mesh, FSDP_AXIS)

    def opt_state_sharding(self, abstract_opt_state: Any) -> Any:
        return shardlib.shard_pytree_along_axis(
            abstract_opt_state, self.mesh, FSDP_AXIS)
