from ray_lightning_tpu.strategies.base import Strategy
from ray_lightning_tpu.strategies.ddp import RayStrategy, DataParallelStrategy
from ray_lightning_tpu.strategies.sharded import (RayShardedStrategy,
                                                  ZeroOneStrategy)
from ray_lightning_tpu.strategies.allreduce import (HorovodRayStrategy,
                                                    AllReduceStrategy)
from ray_lightning_tpu.strategies.fsdp import FSDPStrategy
from ray_lightning_tpu.strategies.mesh_strategy import MeshStrategy
from ray_lightning_tpu.strategies.sequence_parallel import (
    SequenceParallelStrategy)

__all__ = [
    "Strategy", "RayStrategy", "DataParallelStrategy", "RayShardedStrategy",
    "ZeroOneStrategy", "HorovodRayStrategy", "AllReduceStrategy",
    "FSDPStrategy", "MeshStrategy", "SequenceParallelStrategy"
]
