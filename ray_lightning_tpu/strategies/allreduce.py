"""Explicit-allreduce data parallelism (HorovodRayStrategy parity).

The reference's ``HorovodRayStrategy`` (``ray_lightning/ray_horovod.py:32-
183``) is DP where gradient sync is *explicit* — Horovod's
``DistributedOptimizer`` all-reduces on ``step()`` rather than DDP hooking
backward. The TPU-native equivalent keeps that per-rank programming model:
the step runs under ``shard_map`` (via ``ray_lightning_tpu._compat``, which
absorbs the experimental→top-level jax migration) so each mesh slot computes
grads on
its local batch shard, then explicitly ``lax.pmean``-s them over ``dp``
before the optimizer update — the direct analog of ``hvd.allreduce``
lowered to an XLA collective on ICI.

Numerically identical to :class:`RayStrategy`; exists for (a) API parity,
(b) per-rank control (rank-dependent RNG, custom fused collectives), and
(c) as the substrate strategies with hand-written pallas collectives hook
into.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_tpu._compat import shard_map
from ray_lightning_tpu.parallel.mesh import DP_AXIS, MeshSpec
from ray_lightning_tpu.strategies.base import Strategy


class HorovodRayStrategy(Strategy):
    """DP with explicit per-rank gradient allreduce via shard_map."""
    strategy_name = "horovod_ray"

    def mesh_spec(self) -> MeshSpec:
        return MeshSpec({DP_AXIS: self.num_workers})

    def make_train_step(self, loss_fn: Callable, tx: optax.GradientTransformation,
                        state_shardings: Any, batch_sharding: NamedSharding,
                        donate: bool = True,
                        log_grad_norm: bool = False,
                        guard_nonfinite: bool = False) -> Callable:
        from ray_lightning_tpu.reliability.guard import tree_all_finite
        mesh = self.mesh

        def per_rank_step(state, batch):
            # Per-rank RNG: fold in the dp rank so e.g. dropout masks differ
            # across ranks — matching the per-process seeds of the
            # reference's Horovod workers.
            rank = jax.lax.axis_index(DP_AXIS)
            rng = jax.random.fold_in(
                jax.random.fold_in(state.rng, state.step), rank)
            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
            (loss, (logs, new_ms)), grads = grad_fn(
                state.params, state.model_state, batch, rng)
            # The explicit allreduce — hvd.allreduce ≙ lax.pmean over ICI.
            grads = jax.lax.pmean(grads, DP_AXIS)
            loss = jax.lax.pmean(loss, DP_AXIS)
            if log_grad_norm:  # post-allreduce: the effective update norm
                logs = {**logs, "grad_norm": optax.global_norm(grads)}
            logs = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, DP_AXIS)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
                logs)
            new_ms = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, DP_AXIS)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
                new_ms)
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            if guard_nonfinite:
                # checked on the post-allreduce grads, so every rank
                # reaches the same keep/skip verdict with no extra
                # collective (the pmean already synchronized them)
                ok = jnp.isfinite(loss) & tree_all_finite(grads)
                keep = lambda new, old: jax.tree_util.tree_map(  # noqa: E731
                    lambda n, o: jnp.where(ok, n, o), new, old)
                new_params = keep(new_params, state.params)
                new_opt = keep(new_opt, state.opt_state)
                new_ms = keep(new_ms, state.model_state)
                logs = {**logs, "nonfinite": (~ok).astype(jnp.float32)}
            new_state = state.replace(
                step=state.step + 1, params=new_params, opt_state=new_opt,
                model_state=new_ms)
            return new_state, {"loss": loss, **logs}

        batch_spec = batch_sharding.spec
        mapped = shard_map(
            per_rank_step,
            mesh=mesh,
            in_specs=(P(), batch_spec),
            out_specs=(P(), P()),
            check_vma=False)
        # CPU gating as in Strategy.make_train_step: donation + zero-copy
        # host buffers alias on the CPU backend (use-after-free garbage)
        donate = donate and jax.default_backend() != "cpu"
        return jax.jit(mapped, donate_argnums=(0,) if donate else ())

    def join(self) -> None:
        """Barrier parity with ``hvd.join()`` (``ray_horovod.py:143-151``).

        Under SPMD every rank runs the same program, so stragglers cannot
        diverge in step count; blocking on outstanding work is the honest
        equivalent.
        """
        jax.effects_barrier()


AllReduceStrategy = HorovodRayStrategy
