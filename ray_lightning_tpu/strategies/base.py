"""Strategy base class: resource spec + mesh/sharding policy + rank model.

Parity seat of ``ray_lightning/ray_ddp.py:30-136`` (worker-resource config,
launcher installation, rank bookkeeping) re-founded on the mesh model: a
strategy owns

1. a **resource spec** (``num_workers`` etc. — constructor parity with
   ``ray_ddp.py:76-126``, including the ``resources_per_worker`` CPU/TPU
   override semantics),
2. a **mesh policy** (`mesh_spec()`): which named axes exist and their sizes,
3. **sharding rules**: where params / optimizer state / batch live on the
   mesh — this is the part that replaces DDP-wrap vs FairScale-wrap vs
   Horovod-optimizer as the differences between strategies, and
4. the **rank model** (world_size / global_rank / local_rank / node_rank
   properties, ``ray_ddp.py:215-267`` parity) for code that thinks in ranks.
"""
from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_lightning_tpu.launchers.local import LocalLauncher
from ray_lightning_tpu.parallel import sharding as shardlib
from ray_lightning_tpu.parallel.mesh import MeshSpec, build_mesh


class Strategy:
    strategy_name = "base_tpu"

    def __init__(self,
                 num_workers: int = 1,
                 num_cpus_per_worker: int = 1,
                 use_gpu: bool = False,
                 use_tpu: Optional[bool] = None,
                 init_hook: Optional[Callable] = None,
                 resources_per_worker: Optional[Dict] = None,
                 worker_runtime_env: Optional[Dict] = None,
                 use_ray: Optional[bool] = None,
                 allow_colocated_workers: bool = False,
                 gang: Optional[Any] = None,
                 standby: Optional[Any] = None,
                 **kwargs: Any):
        """Resource-spec semantics mirror ``ray_ddp.py:85-112``:
        ``resources_per_worker`` entries override the dedicated args —
        ``CPU`` beats ``num_cpus_per_worker``; ``TPU`` (or legacy ``GPU``)
        beats ``use_tpu``/``use_gpu``. ``num_workers`` is the number of
        accelerator shards (chips), not OS processes — one XLA process
        drives every chip it can address.
        """
        resources_per_worker = dict(resources_per_worker or {})
        self.worker_runtime_env = dict(worker_runtime_env or {})
        self.num_workers = int(num_workers)
        self.num_cpus_per_worker = resources_per_worker.pop(
            "CPU", num_cpus_per_worker)

        accel = resources_per_worker.pop("TPU",
                                         resources_per_worker.pop("GPU", None))
        # An explicit TPU/GPU entry pins the Ray resource request; the bare
        # use_tpu flag leaves it to the launcher, which requests the host's
        # full chip count so Ray spreads one single-owner actor per host.
        self._explicit_chip_request = accel is not None
        if accel is not None:
            self.num_chips_per_worker = accel
        elif use_tpu is not None:
            self.num_chips_per_worker = int(use_tpu)
        else:
            self.num_chips_per_worker = int(use_gpu)
        self.use_tpu = self.num_chips_per_worker > 0
        # `use_gpu` retained as an alias so reference-style constructor
        # calls (`ray_ddp.py:79`) keep working unmodified.
        self.use_gpu = self.use_tpu

        if self.use_tpu and 0 < self.num_chips_per_worker < 1 \
                and num_workers > 1:
            warnings.warn(
                "Less than 1 TPU chip per worker: chips cannot be shared "
                "across SPMD ranks; collectives over ICI require whole "
                "chips. Use 1 chip per worker or a CPU mesh for testing.")

        self.additional_resources_per_worker = resources_per_worker
        self.init_hook = init_hook
        self.use_ray = use_ray
        self.allow_colocated_workers = allow_colocated_workers
        # GangConfig (reliability.gang): arms worker heartbeats + the
        # driver-side hang/death watchdog on Ray-backed launchers this
        # strategy configures. None = the fail-fast-only fault model.
        self.gang = gang
        # StandbyPool (reliability.elastic): warm pre-spawned workers
        # the configured launcher promotes into rank slots on restart.
        self.standby = standby
        self.extra_kwargs = kwargs

        self._mesh: Optional[Mesh] = None
        self._local_rank = 0
        self._global_rank = 0
        self._node_rank = 0
        self._is_remote = False
        self.global_to_local: Optional[list] = None

    # ------------------------------------------------------------------ #
    # launcher
    # ------------------------------------------------------------------ #
    def configure_launcher(self):
        """Install the launcher. Parity: ``ray_ddp.py:128-136``.

        Local (single-process SPMD) by default — one XLA process already
        drives every chip on this host, so no actors are needed. When a Ray
        cluster is attached (``ray.is_initialized()``), the Ray-backed
        multi-host launcher takes over and schedules one executor actor per
        TPU host, exactly where the reference always installs its
        ``RayLauncher``. ``use_ray`` overrides the auto-detection both
        ways: ``False`` keeps training local even inside a notebook that
        happened to ``ray.init()`` for unrelated reasons (round-1 review:
        silent escalation surprised exactly that case); ``True`` demands a
        Ray cluster and fails loudly when none is attached.
        """
        from ray_lightning_tpu.launchers import ray_launcher as _rl
        if self.use_ray is False:
            return LocalLauncher(self)
        ray = _rl._import_ray()
        if ray is not None and ray.is_initialized():
            return _rl.RayLauncher(self, ray_module=ray, gang=self.gang,
                                   standby=self.standby)
        if self.use_ray is True:
            raise RuntimeError(
                "use_ray=True but no Ray runtime is attached: install ray "
                "and call ray.init() (or connect via ray.init('ray://...')) "
                "before fit, or drop use_ray to train locally.")
        return LocalLauncher(self)

    def worker_setup(self, process_idx: int,
                     num_processes: Optional[int] = None,
                     coordinator_address: Optional[str] = None) -> None:
        """Initialize this worker's distributed runtime, then ranks.

        Parity seat of ``_worker_setup`` → ``init_process_group(env://)``
        (``ray_ddp.py:171-213``): NCCL TCP-store rendezvous becomes
        ``jax.distributed.initialize`` against the coordinator brokered by
        the launcher; afterwards every process sees the global device mesh
        and XLA collectives ride ICI/DCN. Single-process (local launcher or
        fake actors) skips initialization — the local mesh is already whole.

        When called without explicit arguments (an out-of-band worker, e.g.
        a user-spawned process joining the job), the coordinator address and
        world size fall back to the ``TL_COORDINATOR_ADDRESS`` /
        ``TL_NUM_PROCESSES`` env vars the launcher broadcasts to every
        actor — the same env-var rendezvous contract as the reference's
        ``MASTER_ADDR``/``MASTER_PORT`` (``ray_launcher.py:160-176``).
        """
        import os as _os
        # Env fallback only when the caller left BOTH at their defaults —
        # an explicit num_processes=1 means "definitely single-process" and
        # must never be overridden by stale TL_* vars.
        if coordinator_address is None and num_processes is None:
            coordinator_address = _os.environ.get("TL_COORDINATOR_ADDRESS")
            try:
                num_processes = int(
                    _os.environ.get("TL_NUM_PROCESSES", "1"))
            except ValueError:
                num_processes = 1
        if num_processes is None:
            num_processes = 1
        if coordinator_address is not None and num_processes > 1:
            from ray_lightning_tpu._compat import distributed_is_initialized
            already = distributed_is_initialized()
            if not already:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_idx)
            if jax.process_index() != process_idx:
                raise AssertionError(
                    f"Launcher assigned global rank {process_idx} but the "
                    f"coordinator handed out process_index "
                    f"{jax.process_index()}: rank map and device mesh "
                    "disagree; per-host batch shards would be misrouted.")
        self.set_world_ranks(process_idx)

    # ------------------------------------------------------------------ #
    # mesh + sharding policy (the strategy-defining part)
    # ------------------------------------------------------------------ #
    def mesh_spec(self) -> MeshSpec:
        raise NotImplementedError

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            self._mesh = build_mesh(self.mesh_spec(), self._mesh_devices())
            if jax.process_count() > 1:
                # Rank-map ↔ mesh alignment: per-host batch feeding relies
                # on global rank r owning the r-th contiguous device block.
                from ray_lightning_tpu.parallel.topology import (
                    assert_mesh_process_alignment)
                assert_mesh_process_alignment(
                    self._mesh, global_rank=self._global_rank,
                    process_index=jax.process_index())
        return self._mesh

    def _mesh_devices(self):
        return jax.devices()

    def params_sharding(self, abstract_params: Any) -> Any:
        """Default: replicate parameters (pure DP)."""
        return shardlib.replicated_pytree(abstract_params, self.mesh)

    def opt_state_sharding(self, abstract_opt_state: Any) -> Any:
        """Default: replicate optimizer state (pure DP)."""
        return shardlib.replicated_pytree(abstract_opt_state, self.mesh)

    def model_state_sharding(self, abstract_model_state: Any) -> Any:
        return shardlib.replicated_pytree(abstract_model_state, self.mesh)

    def batch_sharding(self) -> NamedSharding:
        return shardlib.batch_sharding(self.mesh)

    def scalar_sharding(self) -> NamedSharding:
        return shardlib.replicated(self.mesh)

    def make_train_step(self, loss_fn: Callable, tx: Any,
                        state_shardings: Any, batch_sharding: NamedSharding,
                        donate: bool = True,
                        log_grad_norm: bool = False,
                        guard_nonfinite: bool = False) -> Callable:
        """Build the compiled training step: ``state', logs = step(state, batch)``.

        The jit path: gradient synchronization is *derived* by XLA from the
        sharding annotations (replicated params + dp-sharded batch ⇒ psum of
        grads over ICI, fused into backprop) — this replaces the reference's
        DDP wrapper as the seat of gradient sync (``ray_ddp.py:202-206``).
        Strategies needing explicit per-rank collectives (Horovod parity)
        override this with a ``shard_map`` version.

        ``log_grad_norm`` adds the pre-clip global gradient norm to the
        step logs — computed inside the same XLA program (fused with the
        update), so it costs no extra host sync.

        ``guard_nonfinite`` (the trainer's ``nonfinite_action`` seat)
        checks the loss AND every gradient element for NaN/Inf inside
        the compiled program; a poisoned step keeps the old
        params/opt/model state (a device-side select — donation-safe,
        both versions exist inside the program) and reports
        ``logs["nonfinite"]=1.0`` for the host to act on. The step/rng
        counters still advance: the batch was *attempted*, and the next
        batch draws fresh randomness.
        """
        import optax

        from ray_lightning_tpu.reliability.guard import tree_all_finite

        def step(state, batch):
            rng = jax.random.fold_in(state.rng, state.step)
            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
            (loss, (logs, new_ms)), grads = grad_fn(
                state.params, state.model_state, batch, rng)
            if log_grad_norm:
                logs = {**logs, "grad_norm": optax.global_norm(grads)}
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            if guard_nonfinite:
                ok = jnp.isfinite(loss) & tree_all_finite(grads)
                keep = lambda new, old: jax.tree_util.tree_map(  # noqa: E731
                    lambda n, o: jnp.where(ok, n, o), new, old)
                new_params = keep(new_params, state.params)
                new_opt = keep(new_opt, state.opt_state)
                new_ms = keep(new_ms, state.model_state)
                logs = {**logs,
                        "nonfinite": (~ok).astype(jnp.float32)}
            new_state = state.replace(
                step=state.step + 1, params=new_params, opt_state=new_opt,
                model_state=new_ms)
            return new_state, {"loss": loss, **logs}

        # Donation is gated off on the CPU backend, same as the serve
        # engine's _pick(): CPU jax honors donation by aliasing buffers
        # in place, and CPU device_put/device_get are ZERO-COPY — so a
        # donated step can overwrite memory that host numpy still views
        # (checkpoint-restored states, test snapshots), which surfaces
        # as use-after-free garbage/NaN. Real accelerators copy across
        # the host/HBM boundary, so donation there is both safe and the
        # memory win it exists for.
        donate = donate and jax.default_backend() != "cpu"
        return jax.jit(
            step,
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=(state_shardings, self.scalar_sharding()),
            donate_argnums=(0,) if donate else ())

    def make_eval_step(self, eval_fn: Callable, state_shardings: Any,
                       batch_sharding: NamedSharding) -> Callable:
        """Compiled eval step: ``logs = eval_step(state, batch, rng)``."""

        def step(state, batch, rng):
            return eval_fn(state.params, state.model_state, batch, rng)

        return jax.jit(
            step,
            in_shardings=(state_shardings, batch_sharding,
                          self.scalar_sharding()),
            out_shardings=self.scalar_sharding())

    # ------------------------------------------------------------------ #
    # rank model (parity: ray_ddp.py:138-267)
    # ------------------------------------------------------------------ #
    def set_remote(self, remote: bool) -> None:
        self._is_remote = remote

    def set_global_to_local(self, global_to_local: list) -> None:
        """Driver-computed global→(local, node) map. Parity ``:146-153``."""
        self.global_to_local = global_to_local

    def set_world_ranks(self, process_idx: int = 0) -> None:
        """Parity ``ray_ddp.py:155-169``. Under single-process SPMD the
        process index is the JAX process index (one per TPU host)."""
        self._global_rank = process_idx
        if self.global_to_local is not None and \
                process_idx < len(self.global_to_local):
            self._local_rank, self._node_rank = \
                self.global_to_local[process_idx]
        else:
            self._local_rank, self._node_rank = 0, process_idx

    def set_world_size(self, num_workers: int) -> None:
        """Adopt a world size chosen at RESTART time — the elastic
        recovery seat (``GangSupervisor(elastic=True)``).

        The reference fixes the world at construction; elastic resume
        needs the surviving-capacity count decided *after* a failure.
        Resizing drops the mesh and the driver-computed rank map (both
        describe a world that no longer exists — they rebuild lazily on
        the next launch/fit at the new size); the next restore then
        re-shards the newest checkpoint onto the resized mesh via the
        full-host-array restore path. Only strategies whose mesh is
        derived from ``num_workers`` (the 1-D dp/fsdp families) support
        this — :class:`MeshStrategy` overrides it to refuse.
        """
        n = int(num_workers)
        if n < 1:
            raise ValueError(f"world size must be >= 1, got {n}")
        if n == self.num_workers:
            return
        self.num_workers = n
        self._mesh = None
        self.global_to_local = None
        self.set_world_ranks(min(self._global_rank, n - 1))

    @property
    def world_size(self) -> int:
        """Number of data-parallel ranks. Parity ``ray_ddp.py:215-222``."""
        return self.num_workers

    @property
    def global_rank(self) -> int:
        return self._global_rank

    @property
    def local_rank(self) -> int:
        return self._local_rank

    @property
    def node_rank(self) -> int:
        return self._node_rank

    @property
    def is_remote(self) -> bool:
        return self._is_remote

    @property
    def root_device(self) -> jax.Device:
        """First addressable device of this process's mesh slice.

        Parity with ``ray_ddp.py:269-323`` (CUDA device resolution from
        ``ray.get_gpu_ids``): on TPU, device assignment is the runtime's
        job — the first addressable mesh device is canonical.
        """
        for d in self.mesh.devices.flat:
            if d.process_index == jax.process_index():
                return d
        return jax.local_devices()[0]

    @property
    def accelerator_name(self) -> str:
        """Parity: ``accelerator="_gpu" if use_gpu else "cpu"``
        (``ray_ddp.py:122-123``) — the delayed variant so TPU-less drivers
        can construct the trainer (client mode / CPU head node)."""
        return "_tpu" if self.use_tpu else "cpu"

    @property
    def accelerator(self):
        from ray_lightning_tpu.accelerators import resolve_accelerator
        return resolve_accelerator(self.accelerator_name)

    @property
    def distributed_sampler_kwargs(self) -> Dict[str, int]:
        """Parity ``ray_ddp.py:325-334``: how a rank-sharded dataloader
        should slice. Under SPMD, used only by per-process host data
        feeding (each process loads its shard of the global batch)."""
        return dict(num_replicas=self.num_workers, rank=self.global_rank)

    def teardown(self) -> None:
        self._mesh = None
        # drop the trainer-registered ring/pipeline meshes so later
        # model.apply calls outside a trainer run locally, not in a
        # shard_map over a dead run's devices
        from ray_lightning_tpu.parallel import pipeline as _pipe
        from ray_lightning_tpu.parallel import ring_attention as _ring
        _ring.set_sp_mesh(None)
        _pipe.set_pp_mesh(None)

    def __repr__(self) -> str:
        return (f"{type(self).__name__}(num_workers={self.num_workers}, "
                f"use_tpu={self.use_tpu})")
