"""Unified telemetry: structured events, metrics, and host spans.

Production training and serving treat per-step/per-request telemetry as
a first-class subsystem (MegaScale-style step telemetry, vLLM-style
request lifecycle metrics). This package is that layer for the repo —
one :class:`Telemetry` handle bundling the three primitives:

- :class:`~ray_lightning_tpu.obs.events.EventBus` — ordered structured
  events (what happened), bounded ring + crash-safe JSONL sink.
- :class:`~ray_lightning_tpu.obs.metrics.MetricsRegistry` — counters,
  gauges, log-bucketed histograms (aggregates), with ``snapshot()`` and
  Prometheus-text export.
- :class:`~ray_lightning_tpu.obs.spans.SpanRecorder` — nested host
  spans, exported as Chrome trace-event JSON for Perfetto (viewable
  alongside the device trace ``JaxProfilerCallback`` captures).

**Off by default, zero when off.** Every instrumented component takes
``telemetry=None`` and guards each emission with one attribute read and
a ``None`` check — the disarmed hot loop allocates nothing, mirroring
``FaultPlan``'s zero-cost-when-disarmed design. Thread a handle through
the constructors to arm::

    tel = Telemetry(clock=time.perf_counter, jsonl_path="serve.jsonl")
    client = ServeClient(model, params, telemetry=tel, ...)
    trainer = Trainer(telemetry=tel, callbacks=[StepStatsCallback(tel)])

Process-global channels (fault injection, retry attempts, suppressed
exceptions) have no constructor to thread through; activate the handle
around the workload to capture them too::

    with tel.activated():
        with plan.armed():
            client.serve_trace(trace)
    tel.flush()

Clock contract (shared by bus and spans, mirroring ``ServeClient``):
``clock=None`` is the deterministic tick clock — events carry no wall
time, so the same workload writes a byte-identical JSONL log every run;
``clock=time.perf_counter`` gives real timestamps. See
``docs/observability.md`` for the event schema and metric names table.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ray_lightning_tpu.obs.events import Event, EventBus, JsonlSink
from ray_lightning_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                           MetricsRegistry,
                                           DEFAULT_LATENCY_BUCKETS,
                                           log_buckets)
from ray_lightning_tpu.obs.spans import NULL_SPAN, Span, SpanRecorder


class Telemetry:
    """One handle bundling event bus + metrics registry + span recorder.

    ``clock`` (None = deterministic tick mode) is shared by the bus and
    the span recorder. ``jsonl_path`` arms the crash-safe event log;
    without it events live only in the in-memory ring.
    """

    def __init__(self, *, clock: Optional[Callable[[], float]] = None,
                 capacity: int = 4096,
                 jsonl_path: Optional[str] = None,
                 rotate_bytes: int = 4 << 20,
                 flush_every: int = 256):
        self.clock = clock
        self.bus = EventBus(capacity=capacity, clock=clock,
                            jsonl_path=jsonl_path,
                            rotate_bytes=rotate_bytes,
                            flush_every=flush_every)
        self.metrics = MetricsRegistry()
        self.spans = SpanRecorder(clock=clock)
        # ring-overflow drops surface as a counter so truncated traces
        # are visible in snapshot()/Prometheus, not just on the bus
        self.bus._drop_hook = self.metrics.counter(
            "obs_events_dropped_total",
            help="events evicted from the in-memory ring before being "
                 "read (the JSONL sink, when armed, still has them)").inc

    # ------------------------------------------------------ conveniences
    def event(self, site: str, /, **payload: Any) -> Event:
        return self.bus.emit(site, **payload)

    def span(self, name: str, **args: Any):
        return self.spans.span(name, **args)

    def events(self, site: Optional[str] = None) -> List[Event]:
        return self.bus.events(site)

    def flush(self) -> None:
        self.bus.flush()

    # -------------------------------------------------------- tracing
    def request_traces(self) -> "Dict[int, Any]":
        """Assemble per-request traces from the event ring — one
        :class:`~ray_lightning_tpu.obs.tracing.RequestTrace` per request
        id, with the queue/prefill/decode/sync/failover latency
        decomposition. See ``docs/observability.md`` ("Request
        tracing")."""
        from ray_lightning_tpu.obs.tracing import assemble_request_traces
        return assemble_request_traces(self.bus.events())

    # --------------------------------------------------------- global
    def activated(self) -> "_Activated":
        """Install as the process-global handle for the channels that
        have no constructor seat: ``faults.fire`` injections,
        ``call_with_retry`` attempts, and ``log_suppressed`` records all
        land on the *activated* telemetry. Nests stack-wise (the previous
        handle is restored on exit)."""
        return _Activated(self)


class _Activated:
    def __init__(self, tel: Telemetry):
        self._tel = tel
        self._prev: Optional[Telemetry] = None

    def __enter__(self) -> Telemetry:
        global _GLOBAL
        self._prev = _GLOBAL
        _GLOBAL = self._tel
        return self._tel

    def __exit__(self, *exc_info) -> None:
        global _GLOBAL
        _GLOBAL = self._prev


_GLOBAL: Optional[Telemetry] = None


def get_global() -> Optional[Telemetry]:
    """The activated process-global handle, or None (the default)."""
    return _GLOBAL


def set_global(tel: Optional[Telemetry]) -> None:
    """Install (or clear, with None) the process-global handle directly —
    prefer the scoped :meth:`Telemetry.activated` where possible."""
    global _GLOBAL
    _GLOBAL = tel


def emit_global(site: str, /, **payload: Any) -> None:
    """Hot-path hook for the global channels: one module-global read and
    a None check when no handle is activated — the same zero-cost
    contract as ``faults.fire``."""
    tel = _GLOBAL
    if tel is None:
        return
    tel.bus.emit(site, **payload)


# imported late: stepstats pulls in core.callbacks (jax) — keep the cheap
# primitives importable first
from ray_lightning_tpu.obs.stepstats import StepStatsCallback  # noqa: E402

__all__ = [
    "Telemetry", "Event", "EventBus", "JsonlSink",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS", "log_buckets",
    "Span", "SpanRecorder", "NULL_SPAN", "StepStatsCallback",
    "get_global", "set_global", "emit_global",
]
