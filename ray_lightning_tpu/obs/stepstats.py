"""Per-step training telemetry: timing, data-wait share, throughput, and
an EWMA z-score step-time anomaly detector.

MegaScale-style large-run telemetry (Jiang et al., NSDI'24) boils down
to: measure every step, keep a running distribution, and flag the steps
that fall out of it — a straggling host, a slow storage read, a thermal
throttle all show up as step-time outliers long before they show up in
loss curves. :class:`StepStatsCallback` is that loop for this trainer:

- **step time**: wall clock around the compiled step dispatch. XLA
  dispatch is async — the host only blocks when the device queue is
  full, which is exactly when the device is the bottleneck (same caveat
  as ``SimpleProfiler``); pass ``block=True`` for true device step times
  at the cost of breaking dispatch pipelining.
- **data-wait share**: fraction of the batch-to-batch interval spent
  before the step (loader + host work) — the input-bound indicator.
- **tokens/sec**: inferred from the batch's leading array shape
  (``batch x seq`` for 2-D+ leaves), or supply ``tokens_fn(batch)``.
- **anomaly detection**: an exponentially-weighted moving mean/variance
  of step time; a step whose z-score exceeds ``z_threshold`` (after
  ``warmup_steps``) increments the anomaly counter and emits a
  ``train.straggler`` event with the z-score and the EWMA baseline.

Everything lands in ``trainer.callback_metrics`` (``step_time_ms``,
``data_wait_frac``, ``tokens_per_sec``, ``step_time_z``,
``step_anomalies``), so it rides the existing rank-0 metric transport to
the driver and into ``CSVLogger`` rows unchanged. With a
:class:`~ray_lightning_tpu.obs.Telemetry` handle it additionally feeds
the ``train_step_ms`` histogram, throughput gauges, and the event bus.
"""
from __future__ import annotations

import math
import time
from typing import Any, Callable, Optional

from ray_lightning_tpu.core.callbacks import Callback


def _infer_tokens(batch: Any) -> int:
    """batch x seq for the first 2-D+ leaf; batch size for 1-D; 0 when
    the batch has no array leaves (override with ``tokens_fn``)."""
    import jax
    for leaf in jax.tree_util.tree_leaves(batch):
        shape = getattr(leaf, "shape", None)
        if shape and len(shape) >= 2:
            return int(shape[0]) * int(shape[1])
        if shape and len(shape) == 1:
            return int(shape[0])
    return 0


class StepStatsCallback(Callback):
    """Per-step timing/throughput stats + EWMA z-score straggler detector.

    ``StepStatsCallback(telemetry=tel)`` to feed the metrics registry and
    event bus; without a handle it still populates
    ``trainer.callback_metrics`` (host scalars only — nothing touches the
    compiled step). ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, telemetry=None, *,
                 ewma_alpha: float = 0.1,
                 z_threshold: float = 3.0,
                 warmup_steps: int = 5,
                 min_sigma_frac: float = 0.05,
                 tokens_fn: Optional[Callable[[Any], int]] = None,
                 block: bool = False,
                 clock: Callable[[], float] = time.perf_counter):
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        if z_threshold <= 0:
            raise ValueError(
                f"z_threshold must be > 0, got {z_threshold}")
        if min_sigma_frac < 0:
            raise ValueError(
                f"min_sigma_frac must be >= 0, got {min_sigma_frac}")
        self.telemetry = telemetry
        self.ewma_alpha = ewma_alpha
        self.z_threshold = z_threshold
        self.warmup_steps = warmup_steps
        self.min_sigma_frac = min_sigma_frac
        self.tokens_fn = tokens_fn or _infer_tokens
        self.block = block
        self._clock = clock
        # EWMA state (reset per fit)
        self._mean = 0.0
        self._var = 0.0
        self._n = 0
        self.anomalies = 0
        self._t_start: Optional[float] = None
        self._t_prev_end: Optional[float] = None
        # hot-loop caches: instrument handles resolved once, and the
        # per-batch token count memoized (batch shapes are static in
        # this stack — recomputed only if tokens_fn is user-supplied)
        self._tokens_cached: Optional[int] = None
        self._instruments = None

    # ------------------------------------------------------------ hooks
    def on_train_start(self, trainer, pl_module) -> None:
        self._mean = self._var = 0.0
        self._n = 0
        self.anomalies = 0
        self._t_start = self._t_prev_end = None
        self._tokens_cached = None  # a new fit may feed new shapes

    def on_train_epoch_start(self, trainer, pl_module) -> None:
        # epoch boundaries (validation, checkpointing, callbacks) are not
        # data wait; restart the interval measurement
        self._t_prev_end = None

    def on_train_batch_start(self, trainer, pl_module, batch,
                             batch_idx: int) -> None:
        self._t_start = self._clock()

    def on_train_batch_end(self, trainer, pl_module, outputs, batch,
                           batch_idx: int) -> None:
        if self._t_start is None:
            return
        if self.block:
            trainer.block_until_ready()
        now = self._clock()
        step_s = now - self._t_start
        data_wait_s = (self._t_start - self._t_prev_end
                       if self._t_prev_end is not None else 0.0)
        self._t_prev_end = now
        interval = step_s + data_wait_s
        data_wait_frac = data_wait_s / interval if interval > 0 else 0.0
        if self.tokens_fn is not _infer_tokens:
            tokens = self.tokens_fn(batch)
        else:  # static shapes: infer once, reuse every step
            if self._tokens_cached is None:
                self._tokens_cached = _infer_tokens(batch)
            tokens = self._tokens_cached
        tok_rate = tokens / step_s if step_s > 0 else 0.0

        z = self._update_ewma(step_s)
        anomaly = (z is not None and abs(z) > self.z_threshold)
        if anomaly:
            self.anomalies += 1

        step_ms = step_s * 1e3
        trainer.callback_metrics.update({
            "step_time_ms": step_ms,
            "data_wait_frac": data_wait_frac,
            "tokens_per_sec": tok_rate,
            "step_time_z": 0.0 if z is None else z,
            "step_anomalies": float(self.anomalies),
        })

        tel = self.telemetry
        if tel is not None:
            if self._instruments is None:
                m = tel.metrics
                self._instruments = (
                    m.histogram("train_step_ms",
                                help="train step host wall time (ms)"),
                    m.gauge("train_tokens_per_sec",
                            help="tokens through the train step per "
                            "second"),
                    m.gauge("train_data_wait_frac",
                            help="share of the batch interval spent "
                            "waiting on data"),
                    m.counter("train_step_anomalies_total",
                              help="steps whose time broke the EWMA "
                              "z-score threshold"),
                )
            hist, g_tok, g_wait, c_anom = self._instruments
            hist.observe(step_ms)
            g_tok.set(tok_rate)
            g_wait.set(data_wait_frac)
            if anomaly:
                c_anom.inc()
                tel.event("train.straggler", step=trainer.global_step,
                          z=round(z, 2), step_ms=round(step_ms, 3),
                          ewma_ms=round(self._mean * 1e3, 3))

    # ------------------------------------------------------------- ewma
    def _update_ewma(self, x: float) -> Optional[float]:
        """Fold ``x`` into the EWMA mean/var; return the z-score of ``x``
        against the PRE-update baseline (None during warmup — the
        baseline isn't trustworthy yet, and the anomaly must not poison
        its own reference)."""
        z = None
        if self._n >= self.warmup_steps:
            # sigma floor (min_sigma_frac x mean): an ultra-stable
            # baseline (EWMA var -> 0) must neither divide by zero nor
            # turn ordinary µs jitter into "anomalies"
            sigma = max(math.sqrt(self._var) if self._var > 0 else 0.0,
                        self.min_sigma_frac * abs(self._mean) + 1e-12)
            z = (x - self._mean) / sigma
        if self._n == 0:
            self._mean = x
        else:
            diff = x - self._mean
            incr = self.ewma_alpha * diff
            self._mean += incr
            self._var = (1.0 - self.ewma_alpha) * (self._var + diff * incr)
        self._n += 1
        return z

    # ------------------------------------------------------------ state
    def state_dict(self):
        return {"anomalies": self.anomalies}

    def load_state_dict(self, state) -> None:
        self.anomalies = int(state.get("anomalies", 0))
