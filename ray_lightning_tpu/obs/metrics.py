"""Metrics registry: counters, gauges, and log-bucketed histograms.

The aggregate side of the telemetry layer (events record *occurrences*,
metrics record *totals and distributions*). Three instrument types, the
Prometheus trinity:

- :class:`Counter` — monotonically increasing total (requests served,
  faults injected, tokens generated).
- :class:`Gauge` — a value that goes both ways (queue depth, slot
  occupancy, tokens/sec).
- :class:`Histogram` — fixed log-spaced buckets for latency-shaped
  distributions, PLUS a bounded reservoir of raw samples so quantiles are
  *exact* (numpy-``percentile``-identical linear interpolation) until the
  reservoir cap, and bucket-interpolated after it. This is the single
  quantile implementation in the repo: ``bench.py``'s serve p50/p99/TTFT
  and the production serving metrics report through the same class.

:class:`MetricsRegistry` is the name → instrument map with ``snapshot()``
(plain dict for tests/driver transport) and ``prometheus_text()`` (the
``text/plain; version=0.0.4`` exposition format, scrape-ready).
Instruments are get-or-create by name; re-registering a name as a
different type raises — name collisions are config bugs, not data.
"""
from __future__ import annotations

import bisect
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple


def log_buckets(lo: float, hi: float, count: int) -> Tuple[float, ...]:
    """``count`` log-spaced bucket upper bounds from ``lo`` to ``hi``."""
    if lo <= 0 or hi <= lo or count < 2:
        raise ValueError(
            f"need 0 < lo < hi and count >= 2, got {lo}, {hi}, {count}")
    ratio = (hi / lo) ** (1.0 / (count - 1))
    return tuple(lo * ratio ** i for i in range(count))


# 0.1 ms .. 60 s in ~5 buckets/decade — covers a tick-clock trace (small
# integers) and wall-clock serving latencies in ms with one fixed layout
DEFAULT_LATENCY_BUCKETS = log_buckets(0.1, 60_000.0, 30)


class Counter:
    """Monotonic total. ``inc()`` only — decrements are a type error in
    the model; use a :class:`Gauge` for values that go down."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease by {n}")
        self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """A point-in-time value: set/inc/dec."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self._value += n

    def dec(self, n: float = 1.0) -> None:
        self._value -= n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram + exact-quantile reservoir.

    ``buckets`` are upper bounds (``le``), a ``+Inf`` bucket is implicit.
    ``observe()`` is O(log buckets). Quantiles: while ``count <=
    max_samples`` every observation is retained and ``quantile(q)``
    matches ``np.percentile(samples, 100*q)`` (linear interpolation)
    exactly; past the cap the reservoir stops growing and quantiles fall
    back to linear interpolation *within* the bucket the quantile rank
    lands in — bounded error, bounded memory.
    """

    __slots__ = ("name", "help", "buckets", "counts", "sum", "count",
                 "_samples", "_max_samples")

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None,
                 max_samples: int = 4096):
        self.name = name
        self.help = help
        bs = tuple(sorted(buckets if buckets is not None
                          else DEFAULT_LATENCY_BUCKETS))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs
        self.counts = [0] * (len(bs) + 1)   # last = +Inf
        self.sum = 0.0
        self.count = 0
        self._samples: List[float] = []
        self._max_samples = max_samples

    def observe(self, v: float) -> None:
        v = float(v)
        if math.isnan(v):
            raise ValueError(f"histogram {self.name}: NaN observation")
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1
        if len(self._samples) < self._max_samples:
            self._samples.append(v)

    def quantile(self, q: float) -> float:
        """q in [0, 1]. Exact (numpy-linear) while the reservoir holds
        every observation; bucket-interpolated afterwards."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.count == 0:
            raise ValueError(f"histogram {self.name} is empty")
        if self.count == len(self._samples):
            s = sorted(self._samples)
            h = (len(s) - 1) * q
            lo = math.floor(h)
            hi = min(lo + 1, len(s) - 1)
            return s[lo] + (h - lo) * (s[hi] - s[lo])
        # bucket interpolation: find the bucket holding rank q*count,
        # assume uniform density inside it
        rank = q * self.count
        cum = 0
        lower = 0.0
        for i, c in enumerate(self.counts):
            upper = (self.buckets[i] if i < len(self.buckets)
                     else self.buckets[-1])
            if cum + c >= rank and c > 0:
                frac = (rank - cum) / c
                return lower + frac * (upper - lower)
            cum += c
            lower = upper
        return self.buckets[-1]

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0


class MetricsRegistry:
    """Name → instrument, get-or-create, with snapshot + Prometheus export."""

    def __init__(self):
        self._metrics: Dict[str, Any] = {}

    def _get(self, name: str, cls, **kwargs):
        m = self._metrics.get(name)
        if m is None:
            m = cls(name, **kwargs)
            self._metrics[name] = m
            return m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, requested {cls.__name__}")
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help=help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  max_samples: int = 4096) -> Histogram:
        return self._get(name, Histogram, help=help, buckets=buckets,
                         max_samples=max_samples)

    def get(self, name: str) -> Optional[Any]:
        return self._metrics.get(name)

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict view: counters/gauges → float, histograms →
        ``{count, sum, mean, p50, p99}`` — the driver-transportable form
        (everything is host scalars)."""
        out: Dict[str, Any] = {}
        for name, m in sorted(self._metrics.items()):
            if isinstance(m, Histogram):
                doc = {"count": m.count, "sum": m.sum, "mean": m.mean}
                if m.count:
                    doc["p50"] = m.quantile(0.5)
                    doc["p99"] = m.quantile(0.99)
                out[name] = doc
            else:
                out[name] = m.value
        return out

    def prometheus_text(self) -> str:
        """``text/plain; version=0.0.4`` exposition. Metric names are
        sanitized (dots → underscores); histogram buckets are cumulative
        with the standard ``le`` label and ``+Inf`` terminal."""
        lines: List[str] = []
        for name, m in sorted(self._metrics.items()):
            pname = _prom_name(name)
            if m.help:
                lines.append(f"# HELP {pname} {m.help}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname} {_fmt(m.value)}")
            else:
                lines.append(f"# TYPE {pname} histogram")
                cum = 0
                for le, c in zip(m.buckets, m.counts):
                    cum += c
                    lines.append(
                        f'{pname}_bucket{{le="{_fmt(le)}"}} {cum}')
                cum += m.counts[-1]
                lines.append(f'{pname}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{pname}_sum {_fmt(m.sum)}")
                lines.append(f"{pname}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _fmt(v: float) -> str:
    return repr(round(v, 9)) if v != int(v) else str(int(v))
