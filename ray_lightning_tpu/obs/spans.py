"""Nestable host-side spans, exported as Chrome trace-event JSON.

:class:`ray_lightning_tpu.core.loggers.JaxProfilerCallback` already
captures the *device* timeline (XLA trace, Perfetto-viewable). What it
cannot see is the host: scheduler decisions, prefill-vs-step dispatch,
recovery replays, epoch/validation phases. :class:`SpanRecorder` records
those as nested begin/end spans and exports the Chrome trace-event format
(``{"traceEvents": [...]}`` with complete ``"ph": "X"`` events), so
Perfetto can load the host spans *alongside* the device trace and line
the two timelines up.

Clock modes, same contract as the event bus:

- **tick** (``clock=None``): timestamps are a monotone enter/exit
  counter — deterministic nesting, no wall time. A child span's
  ``[ts, ts+dur]`` is always strictly inside its parent's.
- **wall** (``clock=time.perf_counter``): microsecond timestamps from
  the injected clock, zeroed at the recorder's first span.

Export uses the same tmp + ``os.replace`` publish as checkpoints and the
JSONL sink: the file on disk is always complete, valid JSON.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import os
from typing import Any, Callable, Dict, List, Optional

#: Reusable no-op context for disarmed call sites: ``with (tel.span(...)
#: if tel is not None else NULL_SPAN):`` keeps the hot loop allocation-free.
NULL_SPAN = contextlib.nullcontext()


@dataclasses.dataclass(frozen=True)
class Span:
    """One closed span: name, [ts, ts+dur] (µs or ticks), depth, args."""
    name: str
    ts: float
    dur: float
    depth: int
    args: Dict[str, Any]


class SpanRecorder:
    """Record nested host spans; export Chrome trace-event JSON.

    Use as a context manager factory::

        rec = SpanRecorder()
        with rec.span("epoch", epoch=0):
            with rec.span("train_batch", idx=0):
                ...
        rec.export_chrome_trace("host_trace.json")

    Spans close LIFO per recorder (host-side, single-threaded by design —
    the trainer loop and the serve loop are both synchronous drivers).
    The recorder keeps at most ``capacity`` *closed* spans, dropping the
    oldest; the open stack is unbounded (its depth is the nesting depth).
    """

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 capacity: int = 65536):
        self._clock = clock
        self._t0: Optional[float] = None
        self._seq = 0          # tick mode: advances at every enter/exit
        self._stack: List[tuple] = []
        self._closed: List[Span] = []
        self._capacity = capacity
        self.dropped = 0

    # ------------------------------------------------------------ clock
    def _now(self) -> float:
        if self._clock is None:
            t = float(self._seq)
            self._seq += 1
            return t
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
        return (now - self._t0) * 1e6  # µs, Chrome's unit

    # ------------------------------------------------------------ spans
    @contextlib.contextmanager
    def span(self, name: str, **args: Any):
        self.begin(name, **args)
        try:
            yield self
        finally:
            self.end()

    def begin(self, name: str, **args: Any) -> None:
        """Explicit begin (for code where a ``with`` block is awkward,
        e.g. spanning a loop iteration). Pair with :meth:`end` — spans
        close LIFO."""
        self._stack.append((name, self._now(), args))

    def end(self) -> None:
        if not self._stack:
            raise RuntimeError("SpanRecorder.end() with no open span")
        name, ts, args = self._stack.pop()
        self._closed.append(Span(name=name, ts=ts, dur=self._now() - ts,
                                 depth=len(self._stack), args=args))
        if len(self._closed) > self._capacity:
            del self._closed[0]
            self.dropped += 1

    def record_closed(self, name: str, ts: float, dur: float,
                      depth: int = 0,
                      args: Optional[Dict[str, Any]] = None) -> None:
        """Import one already-closed span measured elsewhere — the
        process-backend ``MSG_SPAN`` leg lands here: a worker stamped
        ``[ts, ts+dur]`` on the shared fleet timeline (µs since the
        fleet epoch) and shipped the closed span over the manager queue.
        Imported spans keep their own timestamps (they are NOT re-zeroed
        against this recorder's ``_t0``) and respect the same capacity /
        ``dropped`` accounting as locally recorded spans."""
        self._closed.append(Span(name=name, ts=float(ts), dur=float(dur),
                                 depth=int(depth), args=dict(args or {})))
        if len(self._closed) > self._capacity:
            del self._closed[0]
            self.dropped += 1

    @property
    def open_depth(self) -> int:
        return len(self._stack)

    def spans(self, name: Optional[str] = None) -> List[Span]:
        """Closed spans in completion order (children before parents)."""
        if name is None:
            return list(self._closed)
        return [s for s in self._closed if s.name == name]

    # ------------------------------------------------------------ export
    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event document: complete (``ph="X"``) events,
        sorted by start time so viewers rebuild the nesting directly.
        ``pid``/``tid`` are fixed at 0 — one host process, one logical
        track — so the document is deterministic under the tick clock."""
        events = [
            {"name": s.name, "ph": "X", "ts": s.ts, "dur": s.dur,
             "pid": 0, "tid": 0, "args": s.args}
            for s in sorted(self._closed, key=lambda s: (s.ts, -s.dur))
        ]
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        """Atomically publish the trace JSON (tmp + ``os.replace``);
        returns ``path``. Load it in Perfetto/``chrome://tracing`` next
        to the device trace ``JaxProfilerCallback`` wrote."""
        doc = self.chrome_trace()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, sort_keys=True)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return path
