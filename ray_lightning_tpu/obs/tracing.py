"""Per-request trace assembly + fleet-stitched Chrome export.

The obs primitives are three parallel streams — events (order), metrics
(aggregates), spans (durations) — with no per-request spine. This module
builds that spine: :func:`assemble_request_traces` folds the event
stream into one :class:`RequestTrace` per request id (the trace id),
with the request's lifetime cut into contiguous, non-overlapping
**segments**::

    queue    submit/arrival -> first admission (class-queue wait)
    prefill  admission -> first token (batched or chunked prefill)
    decode   first token -> retirement (minus the sync split below)
    sync     the enqueue->sync reconciliation window of the async
             dispatch that retired the request (serve.retire `sync`)
    failover any re-admission gap: previous stamp -> the re-admit on a
             surviving replica (replay + re-queue time after a death)

Segments telescope: every segment starts where the previous one ended,
so their durations **sum exactly** to end-to-end latency (``retired -
arrival``) — under the tick clock these are exact integers, which the
tests pin. Failover re-admissions and probation re-seats attach to the
EXISTING trace as annotated edges (``RequestTrace.annotations``,
``resubmits``); they never open a new trace — the fleet keeps request
ids stable across deaths, so the id IS the trace id.

:func:`fleet_chrome_trace` stitches the assembled traces together with
the span recorder (including worker-side spans the process backend
ships over ``MSG_SPAN``) into one multi-track Chrome trace-event
document: ``pid`` = replica seat, ``tid`` = KV slot. Deterministic under
the tick clock — byte-identical across identical runs, same contract as
the JSONL event log.

Everything here is offline/read-only: assembly walks a list of
:class:`~ray_lightning_tpu.obs.events.Event` objects *or* plain dicts
from a flushed JSONL log (``tools/trace_report.py`` runs the same code
over a file on disk).
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: canonical decomposition columns, in report order
SEGMENT_LABELS = ("queue", "prefill", "decode", "sync", "failover")


@dataclasses.dataclass(frozen=True)
class TraceSegment:
    """One contiguous slice of a request's lifetime (client clock
    units). ``replica``/``slot`` locate it on the fleet (the Chrome
    track), when the event stream identified them."""
    label: str
    start: float
    end: float
    replica: Optional[int] = None
    slot: Optional[int] = None

    @property
    def dur(self) -> float:
        return self.end - self.start


@dataclasses.dataclass
class RequestTrace:
    """One request's assembled span tree: identity, outcome, and the
    telescoping latency segments. ``resubmits`` counts failover
    re-admissions (annotated edges on THIS trace, never new traces)."""
    id: int
    tenant: Optional[str] = None
    arrival: Optional[float] = None
    retired: Optional[float] = None
    ttft: Optional[float] = None
    first_token_t: Optional[float] = None
    finish_reason: Optional[str] = None
    tokens: int = 0
    prompt_len: Optional[int] = None
    segments: List[TraceSegment] = dataclasses.field(default_factory=list)
    replicas: List[int] = dataclasses.field(default_factory=list)
    slots: List[int] = dataclasses.field(default_factory=list)
    resubmits: int = 0
    rejected: bool = False
    annotations: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    # assembly state (public but rarely interesting): admissions seen,
    # first token seen
    admits: int = 0
    seen_first_token: bool = False

    @property
    def total(self) -> Optional[float]:
        """End-to-end latency (arrival -> retirement), the exact sum of
        all segment durations."""
        if self.arrival is None or self.retired is None:
            return None
        return self.retired - self.arrival

    def breakdown(self) -> Dict[str, float]:
        """Per-label duration sums over :data:`SEGMENT_LABELS`."""
        out: Dict[str, float] = {k: 0.0 for k in SEGMENT_LABELS}
        for seg in self.segments:
            out[seg.label] = out.get(seg.label, 0.0) + seg.dur
        return out


def _site_payload(e: Any) -> Tuple[Optional[str], Dict[str, Any]]:
    # accept Event objects (the in-memory ring) and plain dicts (a
    # flushed JSONL log read back by tools/trace_report.py)
    if isinstance(e, dict):
        return e.get("site"), e.get("payload") or {}
    return e.site, e.payload


def assemble_request_traces(events: Iterable[Any]) \
        -> Dict[int, "RequestTrace"]:
    """Fold an ordered event stream (ring contents or JSONL dicts) into
    one :class:`RequestTrace` per request id. Tolerant of ring
    truncation: a request whose ``serve.submit`` was evicted is skipped
    rather than half-assembled (``obs.events_dropped`` marks the log)."""
    traces: Dict[int, RequestTrace] = {}
    last: Dict[int, float] = {}             # last stamp per request
    pending_routes: Dict[int, List[int]] = {}  # route before submit

    def push(tr: RequestTrace, label: str, start: float,
             end: float) -> None:
        if end <= start:
            return  # zero-width: adds nothing, keeps telescoping exact
        tr.segments.append(TraceSegment(
            label=label, start=start, end=end,
            replica=tr.replicas[-1] if tr.replicas else None,
            slot=tr.slots[-1] if tr.slots else None))

    for e in events:
        site, p = _site_payload(e)
        if site == "fleet.route":
            rid = p.get("id")
            tr = traces.get(rid)
            if tr is None:
                pending_routes.setdefault(rid, []).append(p.get("replica"))
            else:
                tr.replicas.append(p.get("replica"))
            continue
        if site == "engine.prefill":
            # batch event: ids/slots lists — records each request's KV
            # slot for this admission life (the Chrome tid track)
            for rid, slot in zip(p.get("ids") or [], p.get("slots") or []):
                tr = traces.get(rid)
                if tr is not None:
                    tr.slots.append(slot)
            continue
        rid = p.get("id")
        if rid is None:
            continue
        if site == "serve.submit":
            tr = traces.get(rid)
            if tr is None:
                tr = RequestTrace(id=rid, arrival=p.get("t"),
                                  prompt_len=p.get("prompt_len"))
                tr.replicas.extend(
                    r for r in pending_routes.pop(rid, [])
                    if r is not None)
                traces[rid] = tr
                if tr.arrival is not None:
                    last[rid] = tr.arrival
            else:
                # failover re-admission re-runs submit_request on the
                # survivor: an annotated edge on the SAME trace
                tr.resubmits += 1
                tr.annotations.append({"edge": "resubmit",
                                       "t": p.get("t")})
            continue
        tr = traces.get(rid)
        if tr is None:
            continue  # submit evicted from the ring: skip, don't guess
        if site == "engine.tenant_admitted":
            if tr.tenant is None:
                tr.tenant = p.get("tenant")
        elif site == "serve.admit":
            t = p.get("t")
            if t is None:
                tr.admits += 1
                continue
            if tr.admits == 0 and tr.resubmits == 0:
                qw = p.get("queue_wait")
                if qw is not None:
                    # exact arrival: the client measured queue_wait from
                    # its own arrival stamp — the submit event's t can
                    # lag it by RPC transit under the process backend
                    tr.arrival = t - qw
                push(tr, "queue",
                     tr.arrival if tr.arrival is not None else t, t)
            elif tr.admits == 0:
                # the original admission died unflushed with its
                # replica (kill -9 between dispatch turns): the whole
                # lost window is the failover edge, arrival stays the
                # original submit stamp
                push(tr, "failover", last.get(rid, t), t)
            else:
                push(tr, "failover", last.get(rid, t), t)
            tr.admits += 1
            last[rid] = t
        elif site == "serve.first_token":
            tr.ttft = p.get("ttft")
            t = p.get("t")
            if t is not None:
                push(tr, "prefill", last.get(rid, t), t)
                tr.first_token_t = t
                last[rid] = t
            tr.seen_first_token = True
        elif site == "recovery.replay":
            tr.annotations.append(
                {"edge": "replay",
                 "replayed_tokens": p.get("replayed_tokens")})
        elif site == "fleet.probation":
            tr.annotations.append({"edge": "probation",
                                   "phase": p.get("phase"),
                                   "replica": p.get("replica")})
        elif site == "fleet.probation_cleared":
            tr.annotations.append({"edge": "probation_cleared",
                                   "replica": p.get("replica")})
        elif site == "fleet.readmit_parked":
            tr.annotations.append({"edge": "parked"})
        elif site in ("serve.reject", "fleet.shed"):
            tr.rejected = True
            if tr.finish_reason is None:
                tr.finish_reason = "rejected"
        elif site == "serve.retire":
            tr.finish_reason = p.get("finish_reason")
            tr.tokens = p.get("tokens", 0)
            if p.get("tenant") is not None:
                tr.tenant = p["tenant"]
            t = p.get("t")
            if t is None:
                continue
            tr.retired = t
            prev = last.get(
                rid, tr.arrival if tr.arrival is not None else t)
            tail = ("decode" if tr.seen_first_token
                    else ("prefill" if tr.admits else "queue"))
            sync = p.get("sync") or 0.0
            if 0 < sync < (t - prev):
                push(tr, tail, prev, t - sync)
                push(tr, "sync", t - sync, t)
            elif sync > 0 and (t - prev) > 0:
                push(tr, "sync", prev, t)  # whole tail was the sync
            else:
                push(tr, tail, prev, t)
            last[rid] = t
    return traces


# --------------------------------------------------------------- export
def fleet_chrome_trace(telemetry: Any,
                       traces: Optional[Dict[int, RequestTrace]] = None) \
        -> Dict[str, Any]:
    """Multi-track Chrome trace-event document for a whole fleet run:
    engine/worker spans land on ``pid`` = replica seat (the ``seat``
    span arg — stamped by the fleet in-process, or by the driver when a
    worker ships the span over ``MSG_SPAN``) and ``tid`` = KV slot;
    each request's latency segments are added as ``ph="X"`` events on
    the replica/slot that served them. Deterministic under the tick
    clock (stable sort, no wall time)."""
    if traces is None:
        traces = assemble_request_traces(telemetry.bus.events())
    # segments are client clock units (ticks or SECONDS); Chrome wants
    # µs in wall mode. Spans already come in µs (wall) or ticks (tick).
    scale = 1.0 if telemetry.clock is None else 1e6
    events: List[Dict[str, Any]] = []
    for s in telemetry.spans.spans():
        events.append({"name": s.name, "ph": "X", "ts": s.ts,
                       "dur": s.dur,
                       "pid": int(s.args.get("seat", 0) or 0),
                       "tid": int(s.args.get("slot", 0) or 0),
                       "args": s.args})
    for tr in traces.values():
        for seg in tr.segments:
            events.append({
                "name": f"req{tr.id}/{seg.label}", "ph": "X",
                "ts": seg.start * scale, "dur": seg.dur * scale,
                "pid": int(seg.replica or 0),
                "tid": int(seg.slot or 0),
                "args": {"id": tr.id, "label": seg.label,
                         "tenant": tr.tenant,
                         "failovers": tr.resubmits}})
    events.sort(key=lambda ev: (ev["ts"], -ev["dur"], ev["pid"],
                                ev["tid"], ev["name"]))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_fleet_chrome_trace(path: str, telemetry: Any,
                              traces: Optional[Dict[int, RequestTrace]]
                              = None) -> str:
    """Atomically publish :func:`fleet_chrome_trace` (tmp +
    ``os.replace``, key-sorted JSON — stable bytes under the tick
    clock); returns ``path``."""
    doc = fleet_chrome_trace(telemetry, traces)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return path


# -------------------------------------------------------------- reports
def _percentile(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    vs = sorted(vals)
    k = max(0, min(len(vs) - 1, int(math.ceil(q * len(vs))) - 1))
    return vs[k]


def decomposition_rows(traces: Dict[int, RequestTrace]) \
        -> List[Dict[str, Any]]:
    """Per-request latency decomposition, one plain dict per request
    (id order): identity, outcome, total, ttft, and one column per
    :data:`SEGMENT_LABELS` entry."""
    rows = []
    for tr in sorted(traces.values(), key=lambda t: t.id):
        row: Dict[str, Any] = {
            "id": tr.id, "tenant": tr.tenant,
            "finish": tr.finish_reason, "tokens": tr.tokens,
            "total": tr.total, "ttft": tr.ttft,
            "failovers": tr.resubmits}
        row.update(tr.breakdown())
        rows.append(row)
    return rows


def tenant_rollup(traces: Dict[int, RequestTrace]) \
        -> Dict[str, Dict[str, Any]]:
    """Per-tenant-class rollup: request count, TTFT/latency p50/p99,
    and the summed per-segment breakdown."""
    by_tenant: Dict[str, List[RequestTrace]] = {}
    for tr in traces.values():
        by_tenant.setdefault(tr.tenant or "-", []).append(tr)
    out: Dict[str, Dict[str, Any]] = {}
    for tenant, trs in sorted(by_tenant.items()):
        ttfts = [t.ttft for t in trs if t.ttft is not None]
        totals = [t.total for t in trs if t.total is not None]
        agg = {k: 0.0 for k in SEGMENT_LABELS}
        for t in trs:
            for k, v in t.breakdown().items():
                agg[k] += v
        out[tenant] = {
            "count": len(trs),
            "failovers": sum(t.resubmits for t in trs),
            "ttft_p50": _percentile(ttfts, 0.50),
            "ttft_p99": _percentile(ttfts, 0.99),
            "total_p50": _percentile(totals, 0.50),
            "total_p99": _percentile(totals, 0.99),
            "breakdown": agg}
    return out


def slo_miss_attribution(traces: Dict[int, RequestTrace],
                         slo: Dict[str, float]) \
        -> Dict[str, Dict[str, Any]]:
    """Where did the time go for the requests that MISSED their TTFT
    SLO? For each tenant class in ``slo``, take the requests whose TTFT
    exceeded the target and attribute their pre-first-token time (the
    segments ending at or before the first-token stamp) to
    queue/prefill/failover fractions — the "interactive p99 TTFT miss =
    78% class-queue wait" report."""
    out: Dict[str, Dict[str, Any]] = {}
    for tenant, limit in sorted(slo.items()):
        trs = [t for t in traces.values() if (t.tenant or "-") == tenant]
        missed = [t for t in trs
                  if t.ttft is not None and t.ttft > limit]
        sums: Dict[str, float] = {}
        denom = 0.0
        for tr in missed:
            cut = tr.first_token_t
            for seg in tr.segments:
                if cut is not None and seg.end > cut:
                    continue
                sums[seg.label] = sums.get(seg.label, 0.0) + seg.dur
                denom += seg.dur
        out[tenant] = {
            "slo": limit, "count": len(trs), "misses": len(missed),
            "attribution": ({k: v / denom for k, v in sorted(sums.items())}
                            if denom > 0 else {})}
    return out


def format_decomposition(traces: Dict[int, RequestTrace]) -> str:
    """Human-readable per-request table + per-tenant rollup (client
    clock units — ticks under the tick clock)."""
    def num(v: Any) -> str:
        if v is None:
            return "-"
        return f"{v:.6g}" if isinstance(v, float) else str(v)

    cols = ["id", "tenant", "finish", "tokens", "total", "ttft",
            *SEGMENT_LABELS, "failovers"]
    rows = [[num(r.get(c)) for c in cols]
            for r in decomposition_rows(traces)]
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    lines = ["  ".join(c.rjust(w) for c, w in zip(cols, widths))]
    for r in rows:
        lines.append("  ".join(v.rjust(w) for v, w in zip(r, widths)))
    lines.append("")
    lines.append("per-tenant rollup:")
    for tenant, agg in tenant_rollup(traces).items():
        bd = ", ".join(f"{k}={num(v)}"
                       for k, v in agg["breakdown"].items() if v)
        lines.append(
            f"  {tenant}: n={agg['count']} "
            f"ttft p50={num(agg['ttft_p50'])} p99={num(agg['ttft_p99'])} "
            f"total p99={num(agg['total_p99'])} "
            f"failovers={agg['failovers']}  [{bd}]")
    return "\n".join(lines)


def format_slo_report(traces: Dict[int, RequestTrace],
                      slo: Dict[str, float]) -> str:
    """One line per tenant class: miss count and the dominant
    pre-first-token attribution."""
    lines = []
    for tenant, rep in slo_miss_attribution(traces, slo).items():
        if not rep["misses"]:
            lines.append(f"  {tenant}: 0/{rep['count']} TTFT misses "
                         f"(slo={rep['slo']:g})")
            continue
        attr = ", ".join(f"{100 * v:.0f}% {k}"
                         for k, v in sorted(rep["attribution"].items(),
                                            key=lambda kv: -kv[1]))
        lines.append(f"  {tenant}: {rep['misses']}/{rep['count']} TTFT "
                     f"misses (slo={rep['slo']:g}) = {attr}")
    return "\n".join(lines)


def load_jsonl_events(path: str) -> List[Dict[str, Any]]:
    """Read a flushed JSONL event log back as plain dicts (the offline
    input to :func:`assemble_request_traces`)."""
    events: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


__all__ = [
    "SEGMENT_LABELS", "TraceSegment", "RequestTrace",
    "assemble_request_traces", "fleet_chrome_trace",
    "export_fleet_chrome_trace", "decomposition_rows", "tenant_rollup",
    "slo_miss_attribution", "format_decomposition", "format_slo_report",
    "load_jsonl_events",
]
