"""Process-local structured event bus.

One :class:`Event` is one thing that *happened* at a named site —
``serve.retire``, ``fault.injected``, ``engine.rebuild`` — with a small
JSON-able payload. The bus is deliberately tiny: a bounded in-memory ring
(recent history for probes and tests) plus an optional crash-safe JSONL
sink (the durable operator-facing log). It is NOT a metrics system
(:mod:`~ray_lightning_tpu.obs.metrics` owns aggregates) and NOT a tracer
(:mod:`~ray_lightning_tpu.obs.spans` owns durations) — events are the
ordered, discrete record: *what* happened, in *what order*.

Two clock modes, mirroring :class:`~ray_lightning_tpu.serve.client.ServeClient`:

- **tick clock** (``clock=None``, the default): ``Event.wall_ms`` is
  ``None`` and the only time coordinate is ``tick`` — the bus's emit
  counter. Fully deterministic: the same workload emits a byte-identical
  JSONL log every run, which is what the serving chaos tests pin.
- **wall clock** (``clock=time.perf_counter`` or any callable):
  ``wall_ms`` is milliseconds since the bus's first emit — real
  timestamps for production logs.

The JSONL sink uses the same tmp + ``os.replace`` discipline as
checkpointing: every flush atomically publishes the *complete* current
segment, so a reader (or a crash) never sees a torn line. When a segment
outgrows ``rotate_bytes`` it is rotated to ``<path>.1`` (one generation
kept) and a fresh segment starts.
"""
from __future__ import annotations

import collections
import dataclasses
import json
import os
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class Event:
    """One structured occurrence: site + bus tick (+ wall time) + payload."""
    site: str
    tick: int                      # per-bus emit index (0-based)
    wall_ms: Optional[float]       # None under the tick clock
    payload: Dict[str, Any]

    def to_json(self) -> str:
        """Compact, key-sorted JSON — stable bytes for deterministic logs."""
        doc: Dict[str, Any] = {"site": self.site, "tick": self.tick,
                               "payload": self.payload}
        if self.wall_ms is not None:
            doc["wall_ms"] = round(self.wall_ms, 3)
        return json.dumps(doc, sort_keys=True, separators=(",", ":"))


class JsonlSink:
    """Crash-safe JSONL segment writer (tmp + ``os.replace`` publish).

    Lines accumulate in memory and are serialized lazily; ``flush()``
    writes the whole current segment to ``<path>.tmp-<pid>`` and
    atomically replaces ``path`` — the published file is always complete,
    valid JSONL. Rotation: once the segment passes ``rotate_bytes`` the
    published file moves to ``<path>.1`` and the segment restarts.
    """

    def __init__(self, path: str, rotate_bytes: int = 4 << 20):
        self.path = path
        self.rotate_bytes = rotate_bytes
        self._lines: List[str] = []
        self._bytes = 0
        self._dirty = False

    def write(self, line: str) -> None:
        self._lines.append(line)
        self._bytes += len(line) + 1
        self._dirty = True

    def flush(self) -> None:
        if not self._dirty:
            return
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{self.path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write("\n".join(self._lines) + "\n")
            os.replace(tmp, self.path)
        finally:
            if os.path.exists(tmp):  # failed before the rename: no litter
                os.remove(tmp)
        self._dirty = False
        if self._bytes > self.rotate_bytes:
            os.replace(self.path, f"{self.path}.1")
            self._lines = []
            self._bytes = 0
            # publish the fresh (empty) segment so `path` always exists
            open(self.path, "w").close()


class EventBus:
    """Bounded ring of recent :class:`Event`\\ s + optional JSONL sink.

    ``emit(site, **payload)`` is the single producer call. The ring keeps
    the last ``capacity`` events for in-process probes; the sink (when a
    ``jsonl_path`` is given) keeps the full segment history on disk,
    auto-flushed every ``flush_every`` emits and on :meth:`flush`.
    Payload values must be JSON-serializable scalars/lists/dicts — call
    sites keep payloads small (ids, counts, reasons), never arrays.
    """

    def __init__(self, capacity: int = 4096,
                 clock: Optional[Callable[[], float]] = None,
                 jsonl_path: Optional[str] = None,
                 rotate_bytes: int = 4 << 20,
                 flush_every: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._clock = clock
        self._t0: Optional[float] = None
        self._tick = 0
        self._flush_every = max(1, flush_every)
        self._sink = (JsonlSink(jsonl_path, rotate_bytes)
                      if jsonl_path else None)
        # ring-overflow accounting, mirroring SpanRecorder.dropped: the
        # sink (when armed) still keeps every line — drops only truncate
        # the in-memory ring that probes and the trace assembler read.
        self.dropped = 0
        self._warned_drop = False
        self._drop_hook: Optional[Callable[[], Any]] = None

    @property
    def tick(self) -> int:
        """Total events emitted (the next event's ``tick``)."""
        return self._tick

    def __getstate__(self):
        # a pickled bus is a WORKER-SIDE copy (remote launchers ship the
        # trainer, telemetry included): the driver process owns the
        # jsonl segment, and a copy flushing the same path would
        # atomically clobber it with only its own events. Copies keep
        # the ring (local probes still work) but lose the sink.
        state = self.__dict__.copy()
        state["_sink"] = None
        # the hook closes over the DRIVER's metrics registry — a worker
        # copy incrementing it would double-count (and may not pickle)
        state["_drop_hook"] = None
        return state

    def emit(self, site: str, /, **payload: Any) -> Event:
        # `site` is positional-only so a payload may carry its own
        # "site" key (e.g. fault.injected records the *fault's* site)
        wall_ms = None
        if self._clock is not None:
            now = self._clock()
            if self._t0 is None:
                self._t0 = now
            wall_ms = (now - self._t0) * 1e3
        ev = Event(site=site, tick=self._tick, wall_ms=wall_ms,
                   payload=payload)
        self._tick += 1
        evicting = len(self._ring) == self._ring.maxlen
        if evicting:
            self.dropped += 1
            if self._drop_hook is not None:
                self._drop_hook()
        self._ring.append(ev)
        if self._sink is not None:
            self._sink.write(ev.to_json())
            if self._tick % self._flush_every == 0:
                self._sink.flush()
        if evicting and not self._warned_drop:
            # one-shot, so a truncated ring is self-describing; the flag
            # flips BEFORE the nested emit (which itself evicts one more
            # ring entry, counted like any other) to bound the recursion
            self._warned_drop = True
            self.emit("obs.events_dropped", capacity=self._ring.maxlen)
        return ev

    def events(self, site: Optional[str] = None) -> List[Event]:
        """Ring contents (oldest first), optionally filtered by site
        (exact match, or a ``"prefix."`` match when ``site`` ends with
        a dot)."""
        evs = list(self._ring)
        if site is None:
            return evs
        if site.endswith("."):
            return [e for e in evs if e.site.startswith(site)]
        return [e for e in evs if e.site == site]

    def flush(self) -> None:
        """Publish the sink segment (atomic tmp + ``os.replace``)."""
        if self._sink is not None:
            self._sink.flush()
