"""Version compatibility shims for the moving parts of the jax API.

One import site per migrating symbol, so a jax upgrade is absorbed here
instead of across every module that uses it.

``shard_map``: promoted out of ``jax.experimental`` upstream — newer
releases expose it as ``jax.shard_map`` (with the replication check
renamed ``check_rep`` → ``check_vma``) and eventually drop the
experimental path; older releases have only the experimental path. This
module exports the new-API surface either way — call sites write
``check_vma=`` and the shim translates for old runtimes. Import it from
here::

    from ray_lightning_tpu._compat import shard_map
"""
from __future__ import annotations

import functools

import jax

if callable(getattr(jax, "shard_map", None)):
    # post-promotion releases: the top-level export is the one true
    # spelling and already speaks check_vma
    shard_map = jax.shard_map
else:  # pre-promotion releases: experimental path, check_rep spelling
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    @functools.wraps(_exp_shard_map)
    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _exp_shard_map(f, *args, **kwargs)

if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        """``jax.lax.axis_size`` for releases that predate it: the size of
        a mapped mesh axis, computed as a counting ``psum`` (a compile-time
        constant, not a runtime collective)."""
        return jax.lax.psum(1, axis_name)

def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` across its three spellings:
    the public predicate (newest), the public ``global_state`` attribute
    (middle), and the private module state (releases like 0.4.37 that
    expose neither — a compat shim is the one place a ``jax._src`` import
    is acceptable)."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    state = getattr(jax.distributed, "global_state", None)
    if state is None:
        from jax._src.distributed import global_state as state
    return getattr(state, "client", None) is not None


__all__ = ["shard_map", "axis_size", "distributed_is_initialized"]
