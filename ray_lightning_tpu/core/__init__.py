from ray_lightning_tpu.core.module import TpuModule, TpuDataModule
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.core.callbacks import (Callback, EarlyStopping,
                                              EMAWeightAveraging,
                                              LambdaCallback,
                                              LearningRateMonitor,
                                              ModelCheckpoint,
                                              EpochStatsCallback)
from ray_lightning_tpu.core.loggers import CSVLogger, JaxProfilerCallback
from ray_lightning_tpu.core.optim import make_optimizer, opt_state_bytes
from ray_lightning_tpu.core.profiler import (PassThroughProfiler,
                                             SimpleProfiler)
from ray_lightning_tpu.core.seed import seed_everything, reset_seed

__all__ = [
    "TpuModule", "TpuDataModule", "Trainer", "Callback", "EarlyStopping",
    "EMAWeightAveraging", "LambdaCallback",
    "LearningRateMonitor", "ModelCheckpoint", "EpochStatsCallback",
    "CSVLogger", "JaxProfilerCallback", "PassThroughProfiler",
    "SimpleProfiler", "seed_everything", "reset_seed",
    "make_optimizer", "opt_state_bytes"
]
