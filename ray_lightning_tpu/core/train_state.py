"""The replicated/sharded training state container.

Everything the hot loop touches lives here as one pytree so the whole step is
a single donated-argument jitted function: ``state' = step(state, batch)``.
This replaces the reference's mutable torch module + optimizer objects (the
DDP-wrapped model living inside each Ray actor) with the functional
equivalent XLA can fuse and shard.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class TrainState:
    """Pure pytree training state.

    Attributes:
        step: global optimizer step (int32 scalar on device).
        params: model parameters pytree.
        opt_state: optax optimizer state pytree (this is what ZeRO-1 shards).
        model_state: mutable model collections (e.g. flax ``batch_stats``).
        rng: PRNG key folded per-step for dropout etc.
    """
    step: jax.Array
    params: Any
    opt_state: Any
    model_state: Dict[str, Any]
    rng: jax.Array

    @classmethod
    def create(cls, params: Any, opt_state: Any,
               model_state: Optional[Dict[str, Any]] = None,
               rng: Optional[jax.Array] = None) -> "TrainState":
        if rng is None:
            rng = jax.random.PRNGKey(0)
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            model_state=model_state or {},
            rng=rng)

    @property
    def variables(self) -> Dict[str, Any]:
        """Variables dict as flax ``Module.apply`` expects."""
        return {"params": self.params, **self.model_state}
