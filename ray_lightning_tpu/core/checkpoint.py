"""Sharded (orbax) checkpoint format for ZeRO/FSDP states.

SURVEY.md §7 names this as a hard part the reference dodges: its rank-0
byte-stream (``launchers/ray_launcher.py:329-337``) only works because DP
states are replicated; FairScale consolidates sharded optimizer state under
the hood. Here sharded states are first-class, so the framework offers two
formats:

- **stream** (default): the reference-parity in-memory byte stream —
  consolidates to host, works anywhere, right for replicated DP.
- **orbax** (directory): each host writes its own shards through
  `orbax.checkpoint` (OCDBT), no consolidation, scales to states that
  don't fit one host's RAM; restore re-shards onto whatever mesh the
  resuming run uses (worker-count resize included).

Both produce the same logical dict (``state`` / ``epoch`` / ``global_step``
/ ``callbacks`` / ``module``), so ``Trainer.fit(ckpt_path=…)`` accepts
either — a file is a stream, a directory is orbax.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
from flax import serialization

_META_NAME = "tl_meta.msgpack"
_STATE_NAME = "state"


def save_sharded_checkpoint(dirpath: str, ckpt: Dict[str, Any],
                            train_state: Any) -> None:
    """Write ``ckpt`` (minus the state) + the *sharded* train state.

    ``train_state`` leaves stay ``jax.Array``s — orbax writes each shard
    from the process that owns it (multi-host safe), so no host gather and
    no 2× host-RAM spike like the stream format.
    """
    import orbax.checkpoint as ocp

    dirpath = os.path.abspath(dirpath)
    os.makedirs(dirpath, exist_ok=True)
    state_dict = serialization.to_state_dict(train_state)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(dirpath, _STATE_NAME), state_dict, force=True)
    ckptr.wait_until_finished()

    meta = {k: v for k, v in ckpt.items() if k != "state"}
    with open(os.path.join(dirpath, _META_NAME), "wb") as f:
        f.write(serialization.msgpack_serialize(meta))


def load_sharded_checkpoint(dirpath: str,
                            target: Optional[Any] = None) -> Dict[str, Any]:
    """Inverse of :func:`save_sharded_checkpoint` → the logical ckpt dict.

    Without ``target`` the state comes back as host numpy (then re-placed
    by the trainer's sharding rules — resize-friendly). With a ``target``
    pytree of ``jax.ShapeDtypeStruct`` + shardings, orbax restores straight
    into the sharded layout with no host round-trip.
    """
    import numpy as np
    import orbax.checkpoint as ocp

    dirpath = os.path.abspath(dirpath)
    ckptr = ocp.StandardCheckpointer()
    state_path = os.path.join(dirpath, _STATE_NAME)
    if target is not None:
        state = ckptr.restore(state_path, target)
    else:
        # Restore to host numpy EXPLICITLY: a bare restore replays the
        # saving run's device layout, which fails whenever the resuming
        # world differs (e.g. a 2-process save resumed single-process —
        # the worker-count-resize path this format exists for).
        state_meta = ckptr.metadata(state_path)
        meta_tree = getattr(state_meta, "item_metadata", state_meta)
        restore_args = jax.tree_util.tree_map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta_tree)
        state = ocp.PyTreeCheckpointer().restore(state_path,
                                                 restore_args=restore_args)
    meta_path = os.path.join(dirpath, _META_NAME)
    meta: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            meta = serialization.msgpack_restore(f.read())
    out = dict(meta)
    out["state"] = state
    return out


def is_sharded_checkpoint(path: str) -> bool:
    return os.path.isdir(path)
