"""Sharded (orbax) checkpoint format for ZeRO/FSDP states.

SURVEY.md §7 names this as a hard part the reference dodges: its rank-0
byte-stream (``launchers/ray_launcher.py:329-337``) only works because DP
states are replicated; FairScale consolidates sharded optimizer state under
the hood. Here sharded states are first-class, so the framework offers two
formats:

- **stream** (default): the reference-parity in-memory byte stream —
  consolidates to host, works anywhere, right for replicated DP.
- **orbax** (directory): each host writes its own shards through
  `orbax.checkpoint` (OCDBT), no consolidation, scales to states that
  don't fit one host's RAM; restore re-shards onto whatever mesh the
  resuming run uses (worker-count resize included).

Both produce the same logical dict (``state`` / ``epoch`` / ``global_step``
/ ``callbacks`` / ``module``), so ``Trainer.fit(ckpt_path=…)`` accepts
either — a file is a stream, a directory is orbax.

Crash-safety contract (docs/reliability.md):

- Directory checkpoints are *committed*, never half-visible: orbax items
  commit atomically on their own (tmp dir + rename inside orbax), and the
  **numpy fallback** (used when orbax is absent, or forced with
  ``backend="numpy"``) stages everything in a ``<dir>.tmp-<pid>`` sibling
  and ``os.replace()``\\ s it into place — a process killed mid-save
  leaves only a tmp dir that resume scans ignore.
- ``tl_meta.msgpack`` is the commit marker, written *last*: a directory
  missing it (or its state item) is an interrupted save, and
  :func:`load_sharded_checkpoint` raises :class:`CorruptCheckpointError`
  with the reason instead of a bare numpy/orbax error.
  ``Trainer(resume="auto")`` catches that, skips the corpse, and falls
  back to the previous candidate (:func:`find_resume_candidates`).
- The ``ckpt.save`` fault site fires at the pre-commit point of every
  writer, so tests kill saves deterministically mid-flight.
"""
from __future__ import annotations

import atexit
import os
import shutil
from typing import Any, Dict, List, Optional

import jax
from flax import serialization

from ray_lightning_tpu.reliability import faults, log_suppressed

_META_NAME = "tl_meta.msgpack"
_STATE_NAME = "state"
_CB_NAME = "cb_arrays"
_NP_STATE_NAME = "np_state.msgpack"
_TMP_MARK = ".tmp-"

# process-wide async checkpointer: orbax requires one long-lived instance
# (it owns the background commit thread + multihost barrier ids)
_ASYNC_CKPTR = None


class CorruptCheckpointError(RuntimeError):
    """A checkpoint directory/file is incomplete or unreadable — the
    saving process likely died before its commit finished. Auto-resume
    skips such candidates; manual loads should pick an older one."""


_HAVE_ORBAX: Optional[bool] = None


def have_orbax() -> bool:
    # probed once per process: a failed import is NOT cached by Python
    # (sys.path is rescanned every attempt), and the save path may run
    # every N batches — pay the probe and the log line a single time
    global _HAVE_ORBAX
    if _HAVE_ORBAX is None:
        try:
            import orbax.checkpoint  # noqa: F401
            _HAVE_ORBAX = True
        except Exception as exc:  # noqa: BLE001 — fallback records why
            log_suppressed("ckpt.backend", exc,
                           "orbax unavailable; using the numpy fallback")
            _HAVE_ORBAX = False
    return _HAVE_ORBAX


def _async_checkpointer():
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        import orbax.checkpoint as ocp
        _ASYNC_CKPTR = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        # a run that exits right after its last async save must not lose
        # the tail commit: drain at interpreter exit (the trainer also
        # drains at fit teardown — this covers bare-script users)
        atexit.register(wait_for_async_saves)
    return _ASYNC_CKPTR


def wait_for_async_saves() -> None:
    """Block until every in-flight async checkpoint commit finishes.

    No-op when no async save was ever issued. The trainer calls this at
    fit end (and before reading a checkpoint), and it is registered via
    ``atexit`` when the first async save is issued, so a process never
    exits — or restores — with a half-committed directory.
    """
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_sharded_checkpoint(dirpath: str, ckpt: Dict[str, Any],
                            train_state: Any,
                            async_save: bool = False,
                            backend: Optional[str] = None) -> None:
    """Write ``ckpt`` (minus the state) + the *sharded* train state.

    ``train_state`` leaves stay ``jax.Array``s — orbax writes each shard
    from the process that owns it (multi-host safe), so no host gather and
    no 2× host-RAM spike like the stream format.

    ``async_save=True`` returns as soon as the device→host copy is done;
    the disk write commits on a background thread (training overlaps the
    I/O). Call :func:`wait_for_async_saves` before relying on the files.

    ``backend``: ``"orbax"`` | ``"numpy"`` | ``None`` (auto: orbax when
    importable). The numpy fallback host-gathers (single-process states
    only), stages into a tmp sibling and commits with ``os.replace`` —
    crash-safe, synchronous, dependency-free.
    """
    backend = backend or ("orbax" if have_orbax() else "numpy")
    if backend == "numpy":
        _save_numpy_checkpoint(dirpath, ckpt, train_state, async_save)
        return
    if backend != "orbax":
        raise ValueError(
            f"backend must be 'orbax', 'numpy' or None, got {backend!r}")
    import orbax.checkpoint as ocp

    dirpath = os.path.abspath(dirpath)
    os.makedirs(dirpath, exist_ok=True)
    state_dict = serialization.to_state_dict(train_state)
    # callback device trees (e.g. EMA params) are saved as a sibling orbax
    # item — shard-by-shard like the state, never through the msgpack meta
    # (whose host-gather would crash on non-addressable multi-host shards)
    cb_arrays = ckpt.get("callback_arrays") or None
    if async_save:
        ckptr = _async_checkpointer()
        ckptr.save(os.path.join(dirpath, _STATE_NAME),
                   args=ocp.args.StandardSave(state_dict), force=True)
        if cb_arrays:  # serializes behind the state save; still async
            ckptr.save(os.path.join(dirpath, _CB_NAME),
                       args=ocp.args.StandardSave(
                           serialization.to_state_dict(cb_arrays)),
                       force=True)
    else:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(dirpath, _STATE_NAME), state_dict,
                   force=True)
        if cb_arrays:
            ckptr.save(os.path.join(dirpath, _CB_NAME),
                       serialization.to_state_dict(cb_arrays), force=True)
        ckptr.wait_until_finished()

    # the meta file is the COMMIT MARKER (written last; a directory
    # without it reads as an interrupted save) — the ckpt.save fault
    # fires just before it, so chaos tests produce exactly the torn
    # state a mid-save kill leaves behind
    faults.fire("ckpt.save")
    meta = {k: v for k, v in ckpt.items()
            if k not in ("state", "callback_arrays")}
    with open(os.path.join(dirpath, _META_NAME), "wb") as f:
        f.write(serialization.msgpack_serialize(meta))


def _save_numpy_checkpoint(dirpath: str, ckpt: Dict[str, Any],
                           train_state: Any, async_save: bool) -> None:
    """Orbax-free directory checkpoint: host numpy via flax msgpack.

    Everything is staged in ``<dirpath>.tmp-<pid>`` and committed with a
    single ``os.replace`` — readers either see the complete old
    checkpoint or the complete new one, never a torn write. Host-gathers
    the state (``device_get``), so it is for single-process /
    fully-addressable states; multi-host sharded states need orbax.
    """
    if async_save:
        raise ValueError(
            "async_save requires orbax (the numpy fallback is a "
            "synchronous host write)")
    dirpath = os.path.abspath(dirpath)
    parent = os.path.dirname(dirpath)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = f"{dirpath}{_TMP_MARK}{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    try:
        payload = {"state": jax.device_get(
            serialization.to_state_dict(ckpt.get("state", train_state)))}
        cb_arrays = ckpt.get("callback_arrays") or None
        if cb_arrays:
            payload["callback_arrays"] = jax.device_get(
                serialization.to_state_dict(cb_arrays))
        with open(os.path.join(tmp, _NP_STATE_NAME), "wb") as f:
            f.write(serialization.msgpack_serialize(payload))
        meta = {k: v for k, v in ckpt.items()
                if k not in ("state", "callback_arrays")}
        with open(os.path.join(tmp, _META_NAME), "wb") as f:
            f.write(serialization.msgpack_serialize(meta))
        # pre-commit point: a raise here = the process died mid-save;
        # only the tmp staging dir (ignored by resume scans) remains
        faults.fire("ckpt.save")
        # Overwrite without a destroy-before-commit window: os.replace
        # cannot atomically replace a non-empty directory, so the old
        # checkpoint is renamed ASIDE (atomic) rather than rmtree'd
        # before the new one lands. A kill between the two renames
        # leaves the aside dir — still a complete, loadable checkpoint
        # that resume scans DO consider (only ".tmp-" staging is
        # ignored) — so at every instant at least one committed copy of
        # this checkpoint exists on disk.
        aside = None
        if os.path.isdir(dirpath):
            aside = f"{dirpath}.prev-{os.getpid()}"
            shutil.rmtree(aside, ignore_errors=True)
            os.replace(dirpath, aside)
        os.replace(tmp, dirpath)
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def load_sharded_checkpoint(dirpath: str,
                            target: Optional[Any] = None) -> Dict[str, Any]:
    """Inverse of :func:`save_sharded_checkpoint` → the logical ckpt dict.

    Without ``target`` the state comes back as host numpy (then re-placed
    by the trainer's sharding rules — resize-friendly). With a ``target``
    pytree of ``jax.ShapeDtypeStruct`` + shardings, orbax restores straight
    into the sharded layout with no host round-trip (orbax format only).

    Raises :class:`CorruptCheckpointError` for truncated/partial
    directories — missing state item, missing ``tl_meta.msgpack`` commit
    marker, or undecodable contents — instead of a bare numpy/orbax
    error, so resume logic can skip to an older candidate.
    """
    dirpath = os.path.abspath(dirpath)
    np_path = os.path.join(dirpath, _NP_STATE_NAME)
    state_path = os.path.join(dirpath, _STATE_NAME)
    meta_path = os.path.join(dirpath, _META_NAME)
    if not os.path.exists(meta_path):
        # the meta is written last: its absence means the save never
        # committed (e.g. an async commit interrupted by OOM/preemption)
        raise CorruptCheckpointError(
            f"{dirpath} has no '{_META_NAME}' commit marker — the save "
            "was interrupted before it finished. Pick an older "
            "checkpoint.")
    if os.path.exists(np_path):
        out = _load_numpy_checkpoint(dirpath, np_path, meta_path)
        if target is not None:
            out["state"] = serialization.from_state_dict(target,
                                                         out["state"])
        return out
    if not os.path.isdir(state_path):
        raise CorruptCheckpointError(
            f"{dirpath} has no committed '{_STATE_NAME}' item — the "
            "checkpoint is incomplete (the saving process likely died "
            "before its orbax commit finished). Pick an older "
            "checkpoint.")
    return _load_orbax_checkpoint(dirpath, state_path, meta_path, target)


def _read_meta(meta_path: str) -> Dict[str, Any]:
    try:
        with open(meta_path, "rb") as f:
            return serialization.msgpack_restore(f.read())
    except Exception as exc:
        raise CorruptCheckpointError(
            f"unreadable checkpoint meta {meta_path}: "
            f"{type(exc).__name__}: {exc}") from exc


def _load_numpy_checkpoint(dirpath: str, np_path: str,
                           meta_path: str) -> Dict[str, Any]:
    try:
        with open(np_path, "rb") as f:
            payload = serialization.msgpack_restore(f.read())
    except Exception as exc:
        raise CorruptCheckpointError(
            f"unreadable numpy checkpoint {dirpath}: "
            f"{type(exc).__name__}: {exc}") from exc
    out = dict(_read_meta(meta_path))
    out["state"] = payload.get("state")
    if payload.get("callback_arrays") is not None:
        out["callback_arrays"] = payload["callback_arrays"]
    return out


def _load_orbax_checkpoint(dirpath: str, state_path: str, meta_path: str,
                           target: Optional[Any]) -> Dict[str, Any]:
    import numpy as np
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()

    def _restore_numpy(path):
        # Restore to host numpy EXPLICITLY: a bare restore replays the
        # saving run's device layout, which fails whenever the resuming
        # world differs (e.g. a 2-process save resumed single-process —
        # the worker-count-resize path this format exists for).
        item_meta = ckptr.metadata(path)
        meta_tree = getattr(item_meta, "item_metadata", item_meta)
        restore_args = jax.tree_util.tree_map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta_tree)
        return ocp.PyTreeCheckpointer().restore(path,
                                                restore_args=restore_args)

    try:
        if target is not None:
            state = ckptr.restore(state_path, target)
        else:
            state = _restore_numpy(state_path)
    except Exception as exc:
        raise CorruptCheckpointError(
            f"failed to restore orbax state from {dirpath}: "
            f"{type(exc).__name__}: {exc}") from exc
    out = dict(_read_meta(meta_path))
    out["state"] = state
    cb_path = os.path.join(dirpath, _CB_NAME)
    if os.path.isdir(cb_path):
        try:
            out["callback_arrays"] = _restore_numpy(cb_path)
        except Exception as exc:
            raise CorruptCheckpointError(
                f"failed to restore callback arrays from {dirpath}: "
                f"{type(exc).__name__}: {exc}") from exc
    return out


def is_sharded_checkpoint(path: str) -> bool:
    return os.path.isdir(path)


def reshard_state(template: Any, restored: Any, shardings: Any) -> Any:
    """Place a restored host state onto the CURRENT mesh — the
    save-N-way / restore-M-way seat of elastic recovery.

    ``template`` is a live train state with the resuming run's
    structure (its values are discarded), ``restored`` the host-numpy
    state dict a checkpoint loader produced, ``shardings`` the resuming
    strategy's sharding pytree. Because every loader in this module
    returns *full* host arrays (orbax restores are forced to host numpy
    precisely so the saving run's device layout never leaks —
    ``_restore_numpy``), re-sharding is one ``device_put`` under the new
    rules: a checkpoint written 4-way restores 2-way (or 8-way) with
    element-identical params AND optimizer state, which the elastic
    tests pin. Raises :class:`CorruptCheckpointError` when the restored
    tree cannot adopt the template's structure (a genuinely foreign
    checkpoint), so ``resume="auto"`` can fall back to an older
    candidate instead of crashing the restart.
    """
    try:
        host = serialization.from_state_dict(jax.device_get(template),
                                             restored)
    except (KeyError, TypeError, ValueError) as exc:
        raise CorruptCheckpointError(
            f"checkpoint state does not match the resuming run's state "
            f"structure: {type(exc).__name__}: {exc}") from exc
    return jax.device_put(host, shardings)


def step_of(path: str) -> int:
    """Parse the ``step=N`` our ModelCheckpoint naming embeds, else -1."""
    name = os.path.basename(path)
    for part in name.replace(".ckpt", "").replace(".orbax", "").split("-"):
        if part.startswith("step="):
            try:
                return int(part[len("step="):])
            except ValueError:
                return -1
    return -1


# back-compat alias (pre-elastic private spelling)
_step_of = step_of


def is_committed_checkpoint(path: str) -> bool:
    """True when ``path`` is a *committed* checkpoint: a stream file, or
    a directory carrying the ``tl_meta.msgpack`` commit marker. A
    marker-less directory is an in-flight or interrupted save and must
    never be treated as prunable data (an async orbax commit may still
    be writing it)."""
    if os.path.isdir(path):
        return os.path.exists(os.path.join(path, _META_NAME))
    return path.endswith(".ckpt")


def prune_checkpoints(root: str, keep_last_n: int,
                      protect: Any = ()) -> List[str]:
    """Delete committed checkpoints beyond the newest ``keep_last_n``.

    Retention for long chaos runs: repeated crash/restart cycles save a
    checkpoint per epoch (plus periodic mid-epoch saves) and never
    delete — this prunes the tail. Safety rails:

    - only **committed** candidates are touched
      (:func:`is_committed_checkpoint`): staging dirs (``*.tmp-*``) are
      never even candidates, and marker-less directories (possibly an
      in-flight async commit) are left alone;
    - the newest ``keep_last_n`` committed candidates always survive
      (``keep_last_n >= 1``), so the newest committed checkpoint is
      never pruned;
    - any path in ``protect`` (e.g. a ModelCheckpoint's best/top-k
      ledger) survives regardless of age.

    Returns the paths actually deleted.
    """
    if keep_last_n < 1:
        raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
    protected = {os.path.abspath(p) for p in protect if p}
    committed = [p for p in find_resume_candidates(root)
                 if is_committed_checkpoint(p)]
    doomed = [p for p in committed[keep_last_n:]
              if os.path.abspath(p) not in protected]
    deleted = []
    for path in doomed:
        try:
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.remove(path)
        except OSError as exc:
            # still on disk, still a valid resume fallback: it must NOT
            # be reported deleted (find_resume_candidates filters the
            # returned paths out of the candidate list)
            log_suppressed("ckpt.prune", exc,
                           f"could not prune old checkpoint {path}")
        else:
            deleted.append(path)
    return deleted


def find_resume_candidates(root: str,
                           keep_last_n: Optional[int] = None) -> List[str]:
    """Checkpoint candidates under ``root``, best-first.

    Ordered by the ``step=N`` embedded in our checkpoint filenames
    (newest training progress first), falling back to mtime for foreign
    names. Staging dirs (``*.tmp-*``) are never candidates. The caller
    (``resume="auto"``) tries each in turn and skips the ones that raise
    :class:`CorruptCheckpointError`.

    ``keep_last_n`` additionally prunes committed candidates beyond the
    newest ``keep_last_n`` before returning (see
    :func:`prune_checkpoints` for the safety rails — the newest
    committed candidate is never pruned).
    """
    if not os.path.isdir(root):
        return []
    out = []
    for name in sorted(os.listdir(root)):
        if _TMP_MARK in name:
            continue
        path = os.path.join(root, name)
        if os.path.isdir(path) or name.endswith(".ckpt"):
            out.append(path)
    out.sort(key=lambda p: (step_of(p), os.path.getmtime(p), p),
             reverse=True)
    if keep_last_n is not None:
        pruned = set(prune_checkpoints(root, keep_last_n))
        out = [p for p in out if p not in pruned]
    return out
