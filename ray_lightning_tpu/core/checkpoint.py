"""Sharded (orbax) checkpoint format for ZeRO/FSDP states.

SURVEY.md §7 names this as a hard part the reference dodges: its rank-0
byte-stream (``launchers/ray_launcher.py:329-337``) only works because DP
states are replicated; FairScale consolidates sharded optimizer state under
the hood. Here sharded states are first-class, so the framework offers two
formats:

- **stream** (default): the reference-parity in-memory byte stream —
  consolidates to host, works anywhere, right for replicated DP.
- **orbax** (directory): each host writes its own shards through
  `orbax.checkpoint` (OCDBT), no consolidation, scales to states that
  don't fit one host's RAM; restore re-shards onto whatever mesh the
  resuming run uses (worker-count resize included).

Both produce the same logical dict (``state`` / ``epoch`` / ``global_step``
/ ``callbacks`` / ``module``), so ``Trainer.fit(ckpt_path=…)`` accepts
either — a file is a stream, a directory is orbax.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
from flax import serialization

_META_NAME = "tl_meta.msgpack"
_STATE_NAME = "state"
_CB_NAME = "cb_arrays"

# process-wide async checkpointer: orbax requires one long-lived instance
# (it owns the background commit thread + multihost barrier ids)
_ASYNC_CKPTR = None


def _async_checkpointer():
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        import orbax.checkpoint as ocp
        _ASYNC_CKPTR = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _ASYNC_CKPTR


def wait_for_async_saves() -> None:
    """Block until every in-flight async checkpoint commit finishes.

    No-op when no async save was ever issued. The trainer calls this at
    fit end (and before reading a checkpoint) so a process never exits —
    or restores — with a half-committed directory.
    """
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_sharded_checkpoint(dirpath: str, ckpt: Dict[str, Any],
                            train_state: Any,
                            async_save: bool = False) -> None:
    """Write ``ckpt`` (minus the state) + the *sharded* train state.

    ``train_state`` leaves stay ``jax.Array``s — orbax writes each shard
    from the process that owns it (multi-host safe), so no host gather and
    no 2× host-RAM spike like the stream format.

    ``async_save=True`` returns as soon as the device→host copy is done;
    the disk write commits on a background thread (training overlaps the
    I/O). Call :func:`wait_for_async_saves` before relying on the files.
    """
    import orbax.checkpoint as ocp

    dirpath = os.path.abspath(dirpath)
    os.makedirs(dirpath, exist_ok=True)
    state_dict = serialization.to_state_dict(train_state)
    # callback device trees (e.g. EMA params) are saved as a sibling orbax
    # item — shard-by-shard like the state, never through the msgpack meta
    # (whose host-gather would crash on non-addressable multi-host shards)
    cb_arrays = ckpt.get("callback_arrays") or None
    if async_save:
        ckptr = _async_checkpointer()
        ckptr.save(os.path.join(dirpath, _STATE_NAME),
                   args=ocp.args.StandardSave(state_dict), force=True)
        if cb_arrays:  # serializes behind the state save; still async
            ckptr.save(os.path.join(dirpath, _CB_NAME),
                       args=ocp.args.StandardSave(
                           serialization.to_state_dict(cb_arrays)),
                       force=True)
    else:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(dirpath, _STATE_NAME), state_dict,
                   force=True)
        if cb_arrays:
            ckptr.save(os.path.join(dirpath, _CB_NAME),
                       serialization.to_state_dict(cb_arrays), force=True)
        ckptr.wait_until_finished()

    meta = {k: v for k, v in ckpt.items()
            if k not in ("state", "callback_arrays")}
    with open(os.path.join(dirpath, _META_NAME), "wb") as f:
        f.write(serialization.msgpack_serialize(meta))


def load_sharded_checkpoint(dirpath: str,
                            target: Optional[Any] = None) -> Dict[str, Any]:
    """Inverse of :func:`save_sharded_checkpoint` → the logical ckpt dict.

    Without ``target`` the state comes back as host numpy (then re-placed
    by the trainer's sharding rules — resize-friendly). With a ``target``
    pytree of ``jax.ShapeDtypeStruct`` + shardings, orbax restores straight
    into the sharded layout with no host round-trip.
    """
    import numpy as np
    import orbax.checkpoint as ocp

    dirpath = os.path.abspath(dirpath)
    ckptr = ocp.StandardCheckpointer()
    state_path = os.path.join(dirpath, _STATE_NAME)
    if not os.path.isdir(state_path):
        # orbax commits the item atomically (tmp dir + rename), so a
        # missing 'state' item means the save never finished — e.g. an
        # async commit interrupted by OOM/preemption. The meta file alone
        # does not make a checkpoint.
        raise FileNotFoundError(
            f"{dirpath} has no committed '{_STATE_NAME}' item — the "
            "checkpoint is incomplete (the saving process likely died "
            "before its orbax commit finished). Pick an older checkpoint.")

    def _restore_numpy(path):
        # Restore to host numpy EXPLICITLY: a bare restore replays the
        # saving run's device layout, which fails whenever the resuming
        # world differs (e.g. a 2-process save resumed single-process —
        # the worker-count-resize path this format exists for).
        item_meta = ckptr.metadata(path)
        meta_tree = getattr(item_meta, "item_metadata", item_meta)
        restore_args = jax.tree_util.tree_map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta_tree)
        return ocp.PyTreeCheckpointer().restore(path,
                                                restore_args=restore_args)

    if target is not None:
        state = ckptr.restore(state_path, target)
    else:
        state = _restore_numpy(state_path)
    meta_path = os.path.join(dirpath, _META_NAME)
    meta: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            meta = serialization.msgpack_restore(f.read())
    out = dict(meta)
    out["state"] = state
    cb_path = os.path.join(dirpath, _CB_NAME)
    if os.path.isdir(cb_path):
        out["callback_arrays"] = _restore_numpy(cb_path)
    return out


def is_sharded_checkpoint(path: str) -> bool:
    return os.path.isdir(path)
