"""Metric loggers + profiling callbacks.

SURVEY.md §5 parity seats:

- tracing/profiling: the reference has none in-repo — PTL profiler flags
  pass through, and the only artifact is the sharded example's
  ``CUDACallback`` (epoch time / peak memory — our
  :class:`~ray_lightning_tpu.core.callbacks.EpochStatsCallback`).
  :class:`JaxProfilerCallback` is the TPU-native step up: it captures an XLA
  profiler trace (viewable in TensorBoard/Perfetto) for a window of steps.
- metrics/logging/observability: the reference transports
  ``callback_metrics`` rank-0 → driver; persistent logging is PTL's
  logger stack. :class:`CSVLogger` is the framework-owned equivalent —
  epoch-level metric rows on rank 0, resumable across restarts.
"""
from __future__ import annotations

import csv
import os
from typing import Any, Dict, Optional

import numpy as np

from ray_lightning_tpu.core.callbacks import Callback


class CSVLogger(Callback):
    """Append one metrics row per train epoch (+validation) to metrics.csv.

    Rank-0 only; the file lives under
    ``<default_root_dir>/<name>/version_<k>/metrics.csv`` like PTL's
    CSVLogger so downstream tooling works unchanged.
    """

    def __init__(self, save_dir: Optional[str] = None,
                 name: str = "tpu_logs", version: Optional[int] = None):
        self.save_dir = save_dir
        self.name = name
        self.version = version
        self._path: Optional[str] = None
        self._fieldnames: list = []

    @property
    def log_dir(self) -> Optional[str]:
        return os.path.dirname(self._path) if self._path else None

    def setup(self, trainer, pl_module, stage: str) -> None:
        if trainer.global_rank != 0 or self._path is not None:
            return
        root = self.save_dir or trainer.default_root_dir
        base = os.path.join(root, self.name)
        version = self.version
        if version is None:
            os.makedirs(base, exist_ok=True)
            existing = [
                int(d.split("_", 1)[1]) for d in os.listdir(base)
                if d.startswith("version_") and d.split("_", 1)[1].isdigit()
            ]
            version = max(existing) + 1 if existing else 0
        d = os.path.join(base, f"version_{version}")
        os.makedirs(d, exist_ok=True)
        self._path = os.path.join(d, "metrics.csv")

    def on_train_epoch_end(self, trainer, pl_module) -> None:
        if trainer.global_rank != 0 or self._path is None:
            return
        row: Dict[str, Any] = {
            "epoch": trainer.current_epoch,
            "step": trainer.global_step,
        }
        for k, v in trainer.callback_metrics.items():
            if hasattr(v, "__float__") or np.isscalar(v):
                # np.isscalar("abc") is True — a string metric (e.g. a
                # status tag) must be skipped, not crash the epoch
                try:
                    row[k] = float(v)
                except (TypeError, ValueError):
                    continue
        self._write(row)

    def _write(self, row: Dict[str, Any]) -> None:
        new_fields = [k for k in row if k not in self._fieldnames]
        if new_fields:
            self._fieldnames.extend(new_fields)
            # rewrite with the extended header (rows are few; epochs)
            rows = []
            if os.path.exists(self._path):
                with open(self._path) as f:
                    rows = list(csv.DictReader(f))
            with open(self._path, "w", newline="") as f:
                w = csv.DictWriter(f, fieldnames=self._fieldnames)
                w.writeheader()
                for r in rows:
                    w.writerow(r)
                w.writerow(row)
        else:
            with open(self._path, "a", newline="") as f:
                csv.DictWriter(f, fieldnames=self._fieldnames).writerow(row)

    def state_dict(self) -> Dict[str, Any]:
        return {"path": self._path, "fieldnames": self._fieldnames}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._path = state.get("path")
        self._fieldnames = list(state.get("fieldnames", []))


class JaxProfilerCallback(Callback):
    """Capture an XLA profiler trace for a window of training steps.

    TPU-native tracing (SURVEY.md §5 "tracing/profiling: none in-repo"):
    starts ``jax.profiler`` at ``start_step`` and stops after
    ``num_steps``, writing a TensorBoard/Perfetto-compatible trace with
    device (MXU/HBM) timelines into ``<root>/profile``. Rank-0 only.
    """

    def __init__(self, start_step: int = 5, num_steps: int = 3,
                 log_dir: Optional[str] = None):
        self.start_step = start_step
        self.num_steps = num_steps
        self.log_dir = log_dir
        self._active = False
        self._done = False          # one window per callback instance
        self._started_at: Optional[int] = None
        self.trace_dir: Optional[str] = None

    def on_train_batch_start(self, trainer, pl_module, batch,
                             batch_idx: int) -> None:
        if trainer.global_rank != 0 or self._active or self._done:
            return
        # >= (not ==): a run resumed PAST start_step must still profile —
        # with == the window is silently skipped forever. The window then
        # covers num_steps from wherever tracing actually started.
        if trainer.global_step >= self.start_step:
            import jax
            self.trace_dir = self.log_dir or os.path.join(
                trainer.default_root_dir, "profile")
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
            self._started_at = trainer.global_step

    def on_train_batch_end(self, trainer, pl_module, outputs, batch,
                           batch_idx: int) -> None:
        if not self._active:
            return
        if trainer.global_step >= self._started_at + self.num_steps:
            import jax
            trainer.block_until_ready()
            jax.profiler.stop_trace()
            self._active = False
            self._done = True

    def teardown(self, trainer, pl_module, stage: str) -> None:
        if self._active:  # trace window larger than the run: close cleanly
            import jax
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
