"""Trainer profilers.

PTL-parity surface: the reference passes PTL's ``Trainer(profiler=...)``
flag through untouched (SURVEY.md §5 — tracing is delegated); owning the
Trainer means owning that seat. Two profilers ship:

- :class:`SimpleProfiler` (``profiler="simple"``): wall-clock per section
  (data wait, step dispatch, validation, callbacks), printed as a table at
  fit end. Note the XLA async-dispatch caveat: "train_step" measures host
  dispatch time — the host only blocks here when the device queue is full,
  which is exactly when the device is the bottleneck, so a large
  "train_step" share means device-bound and a large "get_train_batch"
  share means input-bound.
- For device-side traces use
  :class:`ray_lightning_tpu.core.loggers.JaxProfilerCallback`, which
  captures an XLA trace viewable in TensorBoard/Perfetto.
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Tuple


class PassThroughProfiler:
    """No-op seat so the hot loop never branches on profiler presence."""

    @contextlib.contextmanager
    def profile(self, name: str):
        yield

    def profile_iterable(self, iterable, name: str):
        return iterable

    def summary(self) -> str:
        return ""

    def describe(self) -> None:
        pass

    def reset(self) -> None:
        pass

    def records(self) -> Dict[str, Tuple[int, float]]:
        """``{section: (calls, total_seconds)}`` — the machine-readable
        view the trainer exports into the telemetry metrics registry at
        fit end (``profile_<section>_s`` gauges)."""
        return {}


class SimpleProfiler(PassThroughProfiler):
    """Accumulate wall-clock per named section (scoped per fit: the
    trainer resets the records at fit start so a reused Trainer reports
    each run separately)."""

    def __init__(self):
        self._records: Dict[str, Tuple[int, float]] = {}

    def reset(self) -> None:
        self._records = {}

    @contextlib.contextmanager
    def profile(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            count, total = self._records.get(name, (0, 0.0))
            self._records[name] = (count + 1, total + dt)

    def records(self) -> Dict[str, Tuple[int, float]]:
        return dict(self._records)

    def profile_iterable(self, iterable, name: str):
        """Time each ``next()`` — the data-wait measurement."""
        it = iter(iterable)
        while True:
            with self.profile(name):
                try:
                    item = next(it)
                except StopIteration:
                    return
            yield item

    def summary(self) -> str:
        if not self._records:
            return ""
        total_all = sum(t for _, t in self._records.values())
        lines = [
            f"{'Action':<24}| {'Mean (s)':>10} | {'Calls':>7} | "
            f"{'Total (s)':>10} | {'%':>6}",
            "-" * 68,
        ]
        for name, (count, total) in sorted(self._records.items(),
                                           key=lambda kv: -kv[1][1]):
            pct = 100.0 * total / total_all if total_all else 0.0
            lines.append(f"{name:<24}| {total / count:>10.5f} | "
                         f"{count:>7} | {total:>10.3f} | {pct:>5.1f}%")
        return "\n".join(lines)

    def describe(self) -> None:
        s = self.summary()
        if s:
            print("SimpleProfiler report\n" + s)


def resolve_profiler(profiler) -> PassThroughProfiler:
    if profiler is None:
        return PassThroughProfiler()
    if isinstance(profiler, str):
        if profiler == "simple":
            return SimpleProfiler()
        raise ValueError(
            f"Unknown profiler {profiler!r}; use 'simple', None, or a "
            "profiler object with profile()/profile_iterable()/describe()")
    missing = [m for m in ("profile", "profile_iterable", "describe")
               if not callable(getattr(profiler, m, None))]
    if missing:
        raise ValueError(
            f"profiler object {profiler!r} lacks required method(s) "
            f"{missing}; pass 'simple', None, or implement the "
            "profile()/profile_iterable()/describe() contract")
    return profiler
