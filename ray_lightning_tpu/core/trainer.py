"""The Trainer: PTL-style fit/validate/test/predict driving compiled SPMD loops.

The reference never implements a training loop — it ships PTL's Trainer into
Ray actors (``ray_lightning/launchers/ray_launcher.py:222-311``) and lets it
re-enter. Building TPU-native means owning that loop: here the hot path is a
single donated, jitted ``step(state, batch)`` whose gradient collectives XLA
derives from strategy sharding annotations, and the Trainer around it
reproduces the orchestration contract the reference adds on top of PTL:

- strategies install launchers; ``fit`` runs through ``launcher.launch``
  (parity: ``ray_ddp.py:128-136`` → ``ray_launcher.py:48-69``),
- rank-0 results come back as a :class:`WorkerOutput` — state as bytes,
  metrics as numpy (parity: ``ray_launcher.py:313-350``),
- the driver recovers weights/metrics into the user-visible objects
  (parity: ``ray_launcher.py:352-380``),
- Tune-style callbacks reach the driver through the session queue, drained
  between batches (parity: ``util.py:49-70``).
"""
from __future__ import annotations

import os
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import serialization

from ray_lightning_tpu import util as _util
from ray_lightning_tpu.core.callbacks import Callback, ModelCheckpoint
from ray_lightning_tpu.reliability import faults as _faults
from ray_lightning_tpu.reliability import log_suppressed
from ray_lightning_tpu.parallel import sharding as shardlib
from ray_lightning_tpu.core.module import TpuDataModule, TpuModule
from ray_lightning_tpu.core.seed import seed_everything
from ray_lightning_tpu.core.train_state import TrainState
from ray_lightning_tpu.launchers.utils import WorkerOutput


def _normalize_step_output(out: Any, prev_model_state: Any):
    """training_step may return loss | (loss, logs) | (loss, logs, state)."""
    if isinstance(out, tuple):
        if len(out) == 2:
            return out[0], dict(out[1]), prev_model_state
        if len(out) == 3:
            return out[0], dict(out[1]), out[2]
        raise ValueError(
            f"training_step returned a {len(out)}-tuple; expected "
            "loss, (loss, logs) or (loss, logs, model_state)")
    return out, {}, prev_model_state


class Trainer:
    def __init__(self,
                 strategy=None,
                 max_epochs: int = 1,
                 max_steps: int = -1,
                 callbacks: Optional[List[Callback]] = None,
                 limit_train_batches: Optional[float] = None,
                 limit_val_batches: Optional[float] = None,
                 limit_test_batches: Optional[float] = None,
                 limit_predict_batches: Optional[float] = None,
                 num_sanity_val_steps: int = 0,
                 check_val_every_n_epoch: int = 1,
                 val_check_interval=None,
                 enable_checkpointing: bool = False,
                 default_root_dir: Optional[str] = None,
                 enable_progress_bar: bool = False,
                 log_every_n_steps: int = 50,
                 precision: str = "32",
                 gradient_clip_val: Optional[float] = None,
                 accumulate_grad_batches: int = 1,
                 track_grad_norm: bool = False,
                 profiler=None,
                 seed: Optional[int] = None,
                 resume: Optional[str] = None,
                 nonfinite_action: Optional[str] = None,
                 telemetry=None):
        from ray_lightning_tpu.strategies.ddp import RayStrategy
        self.strategy = strategy if strategy is not None else RayStrategy(
            num_workers=1)
        self.max_epochs = max_epochs
        self.max_steps = max_steps
        self.callbacks: List[Callback] = list(callbacks or [])
        self.limit_train_batches = limit_train_batches
        self.limit_val_batches = limit_val_batches
        self.limit_test_batches = limit_test_batches
        self.limit_predict_batches = limit_predict_batches
        self.num_sanity_val_steps = num_sanity_val_steps
        self.check_val_every_n_epoch = check_val_every_n_epoch
        if val_check_interval is not None:
            if isinstance(val_check_interval, float):
                if not 0.0 < val_check_interval <= 1.0:
                    raise ValueError(
                        f"float val_check_interval must be in (0, 1], got "
                        f"{val_check_interval}")
            elif int(val_check_interval) < 1:
                raise ValueError(
                    f"int val_check_interval must be >= 1, got "
                    f"{val_check_interval}")
        self.val_check_interval = val_check_interval
        self.enable_checkpointing = enable_checkpointing
        self.default_root_dir = default_root_dir or os.path.join(
            os.getcwd(), "tpu_lightning_logs")
        self.enable_progress_bar = enable_progress_bar
        self.log_every_n_steps = log_every_n_steps
        self.precision = str(precision)
        self.gradient_clip_val = gradient_clip_val
        self.accumulate_grad_batches = int(accumulate_grad_batches)
        self.track_grad_norm = bool(track_grad_norm)
        from ray_lightning_tpu.core.profiler import resolve_profiler
        self.profiler = resolve_profiler(profiler)
        self.seed = seed_everything(seed) if seed is not None else None
        # crash-safe resume: resume="auto" makes fit() (when called
        # without an explicit ckpt_path) scan the checkpoint dir, restore
        # the newest VALID checkpoint (corrupt/partial candidates are
        # skipped with a logged warning) and continue at the saved step —
        # mid-epoch checkpoints fast-forward the dataloader to the saved
        # batch. See docs/reliability.md.
        if resume not in (None, "auto"):
            raise ValueError(
                f"resume must be None or 'auto', got {resume!r}")
        self.resume = resume
        # NaN/Inf guard over loss AND gradients (checked element-exact
        # inside the compiled step): None = off (no per-step host sync),
        # "raise" = fail fast, "skip_batch" = drop the poisoned update
        # (device-side select, weights never touched), or
        # "restore_last_ckpt" = roll weights/optimizer back to the last
        # saved checkpoint and keep training.
        if nonfinite_action not in (None, "raise", "skip_batch",
                                    "restore_last_ckpt"):
            raise ValueError(
                "nonfinite_action must be None, 'raise', 'skip_batch' or "
                f"'restore_last_ckpt', got {nonfinite_action!r}")
        self.nonfinite_action = nonfinite_action
        self.nonfinite_batches = 0   # guarded steps that came back bad
        self.nonfinite_restores = 0  # times restore_last_ckpt fired
        # obs.Telemetry handle (None = disarmed): the trainer emits
        # fit/epoch/worker lifecycle events; per-step stats are the
        # opt-in StepStatsCallback's job so the hot loop stays untouched
        self.telemetry = telemetry

        if self.enable_checkpointing and not any(
                isinstance(cb, ModelCheckpoint) for cb in self.callbacks):
            self.callbacks.append(ModelCheckpoint())

        # progress / results (user-visible, PTL names)
        self.current_epoch = 0
        self.global_step = 0
        self.callback_metrics: Dict[str, Any] = {}
        self.logged_metrics: Dict[str, Any] = {}
        self.sanity_checking = False
        self.should_stop = False  # settable by callbacks (EarlyStopping)
        self.state = "idle"
        self.train_state: Optional[TrainState] = None

        # worker-side handles (populated inside the launched fit)
        self._module: Optional[TpuModule] = None
        self._model = None
        self._launcher = None
        self._last_logs: Dict[str, Any] = {}
        self._last_ckpt_path: str = ""   # newest save_checkpoint target
        # batches completed in the CURRENT epoch (-1 = epoch boundary):
        # checkpointed so resume="auto" can fast-forward a mid-epoch save
        self._batch_in_epoch: int = -1
        self._resume_skip: int = 0

    # ------------------------------------------------------------------ #
    # properties
    # ------------------------------------------------------------------ #
    @property
    def mesh(self):
        return self.strategy.mesh

    @property
    def devices(self) -> List[jax.Device]:
        return list(self.strategy.mesh.devices.flat)

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    @property
    def global_rank(self) -> int:
        return self.strategy.global_rank

    @property
    def world_size(self) -> int:
        return self.strategy.world_size

    @property
    def checkpoint_callback(self) -> Optional[ModelCheckpoint]:
        for cb in self.callbacks:
            if isinstance(cb, ModelCheckpoint):
                return cb
        return None

    def block_until_ready(self) -> None:
        if self.train_state is not None:
            jax.block_until_ready(self.train_state.params)

    # ------------------------------------------------------------------ #
    # entry points (driver side)
    # ------------------------------------------------------------------ #
    def fit(self, module: TpuModule,
            datamodule: Optional[TpuDataModule] = None,
            ckpt_path: Optional[str] = None) -> None:
        if ckpt_path is None and self.resume is not None:
            ckpt_path = self.resume  # "auto": scan-and-restore in worker
        self.state = "fitting"
        if self._launcher is None:
            self._launcher = self.strategy.configure_launcher()
        output = self._launcher.launch(
            self._fit_worker, module, datamodule, ckpt_path, trainer=self)
        self._recover_results(output, module)
        self.state = "finished"

    def validate(self, module: TpuModule,
                 datamodule: Optional[TpuDataModule] = None,
                 ckpt_path: Optional[str] = None) -> List[Dict[str, Any]]:
        return self._run_evaluate(module, datamodule, ckpt_path, "validate")

    def test(self, module: TpuModule,
             datamodule: Optional[TpuDataModule] = None,
             ckpt_path: Optional[str] = None) -> List[Dict[str, Any]]:
        return self._run_evaluate(module, datamodule, ckpt_path, "test")

    def predict(self, module: TpuModule,
                datamodule: Optional[TpuDataModule] = None,
                ckpt_path: Optional[str] = None) -> List[Any]:
        self.state = "predicting"
        if self._launcher is None:
            self._launcher = self.strategy.configure_launcher()
        output = self._launcher.launch(
            self._predict_worker, module, datamodule, ckpt_path, trainer=self)
        self.state = "finished"
        return output.results

    def _run_evaluate(self, module, datamodule, ckpt_path,
                      stage: str) -> List[Dict[str, Any]]:
        self.state = f"{stage[:-1] if stage.endswith('e') else stage}ing"
        if self._launcher is None:
            self._launcher = self.strategy.configure_launcher()
        output = self._launcher.launch(
            self._evaluate_worker, module, datamodule, ckpt_path, stage,
            trainer=self)
        self.callback_metrics.update(
            _util.numpy_metrics_to_device(output.callback_metrics))
        self.state = "finished"
        return output.results

    # ------------------------------------------------------------------ #
    # worker-side setup
    # ------------------------------------------------------------------ #
    def _attach(self, module: TpuModule,
                datamodule: Optional[TpuDataModule]) -> None:
        module.trainer = self
        self._module = module
        self._datamodule = datamodule
        self.strategy.set_world_ranks(jax.process_index())

    def _dataloader(self, name: str):
        if self._datamodule is not None:
            loader = getattr(self._datamodule, name)()
            if loader is not None:
                return loader
        return getattr(self._module, name)()

    @staticmethod
    def _peek_first_batch(loader):
        """First batch + a loader safe to iterate from the start.

        Re-iterable loaders pass through untouched; a bare iterator or
        generator gets its consumed head chained back on so batch 0 is
        still trained (multi-epoch runs need a re-iterable loader)."""
        import itertools
        it = iter(loader)
        first = next(it)
        if it is loader:  # non-re-iterable: iter() returned self
            loader = itertools.chain([first], it)
        return first, loader

    def _optimizer(self) -> optax.GradientTransformation:
        out = self._module.configure_optimizers()
        # PTL's optimizer+scheduler pairing, optax-style: the module may
        # return (tx, schedule_fn) where schedule_fn(step) -> lr; the
        # schedule is already baked into tx (optax composes them), the
        # handle only feeds lr logging / LearningRateMonitor.
        self._lr_schedule = None
        # NB: optax.GradientTransformation IS a (Named)tuple — a bare tx
        # is distinguished by its init/update fields, not by type
        if isinstance(out, tuple) and not hasattr(out, "update") \
                and len(out) == 2:
            tx, self._lr_schedule = out
        else:
            tx = out
        chain = []
        if self.gradient_clip_val:
            chain.append(optax.clip_by_global_norm(self.gradient_clip_val))
        chain.append(tx)
        tx = optax.chain(*chain) if len(chain) > 1 else tx
        if self.accumulate_grad_batches > 1:
            tx = optax.MultiSteps(tx, self.accumulate_grad_batches)
        return tx

    @property
    def current_lr(self):
        """Learning rate at the current global step, when the module
        returned an ``(tx, schedule)`` pair; None otherwise."""
        schedule = getattr(self, "_lr_schedule", None)
        if schedule is None and self._module is not None:
            # after a remote launch only counters/metrics sync back to the
            # driver-side trainer; re-probe the module (pure optax
            # construction, no devices — client-mode safe)
            out = self._module.configure_optimizers()
            if isinstance(out, tuple) and not hasattr(out, "update") \
                    and len(out) == 2:
                schedule = out[1]
        if schedule is None:
            return None
        # optax.MultiSteps advances the inner schedule once per k batches
        step = self.global_step // max(1, self.accumulate_grad_batches)
        return float(schedule(step))

    def _cast_batch(self, batch: Any) -> Any:
        if not self.precision.startswith("bf16"):
            return batch
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if np.issubdtype(np.asarray(x).dtype, np.floating) else x, batch)

    def _setup_state(self, sample_batch: Any,
                     restored: Optional[Dict[str, Any]] = None):
        """Init (or restore) the sharded TrainState + compiled steps.

        Two-phase init: abstract shapes via ``eval_shape``, then strategy
        sharding rules, then a jitted init with ``out_shardings`` — so even
        FSDP-sharded giants materialize directly in their sharded layout.
        """
        strategy = self.strategy
        mesh = strategy.mesh
        # register the mesh for attention_impl='ring' and pipelined_stack:
        # models nest shard_maps over the sp/pp axes inside the jitted
        # step (no-ops when the mesh lacks those axes)
        from ray_lightning_tpu.parallel import pipeline as _pipe
        from ray_lightning_tpu.parallel import ring_attention as _ring
        _ring.set_sp_mesh(mesh)
        _pipe.set_pp_mesh(mesh)
        module = self._module
        model = module.configure_model()
        self._model = model
        tx = self._optimizer()
        self._tx = tx
        seed = self.seed if self.seed is not None else 0
        root_rng = jax.random.PRNGKey(seed)
        init_rng, state_rng = jax.random.split(root_rng)

        sample_batch = self._cast_batch(sample_batch)
        batch_sharding = strategy.batch_sharding()
        device_batch = shardlib.put_global_batch(sample_batch,
                                                 batch_sharding)

        def init_fn(rng, batch):
            variables = module.init_variables(model, rng, batch)
            params = variables.pop("params")
            model_state = dict(variables)
            opt_state = tx.init(params)
            return TrainState.create(params, opt_state, model_state,
                                     state_rng)

        abstract = jax.eval_shape(init_fn, init_rng, device_batch)
        state_shardings = TrainState(
            step=strategy.scalar_sharding(),
            params=strategy.params_sharding(abstract.params),
            opt_state=strategy.opt_state_sharding(abstract.opt_state),
            model_state=strategy.model_state_sharding(abstract.model_state),
            rng=strategy.scalar_sharding())
        state = jax.jit(
            init_fn, out_shardings=state_shardings)(init_rng, device_batch)

        if restored is not None:
            # re-shard on restore: the loaders hand back FULL host
            # arrays, so a checkpoint saved N-way lands on this run's
            # (possibly different-sized) mesh in one device_put — the
            # elastic save-N-way / restore-M-way contract
            from ray_lightning_tpu.core.checkpoint import reshard_state
            state = reshard_state(state, restored, state_shardings)

        def loss_fn(params, model_state, batch, rng):
            variables = {"params": params, **model_state}
            out = module.training_step(model, variables, batch, rng)
            logged, _meta = module._log_buffer.drain()
            loss, logs, new_ms = _normalize_step_output(out, model_state)
            return loss, ({**logs, **logged}, new_ms)

        def eval_fn_builder(step_name):
            def eval_fn(params, model_state, batch, rng):
                variables = {"params": params, **model_state}
                logs = getattr(module, step_name)(model, variables, batch,
                                                  rng)
                logged, _meta = module._log_buffer.drain()
                return {**(logs or {}), **logged}
            return eval_fn

        train_step = strategy.make_train_step(
            loss_fn, tx, state_shardings, batch_sharding,
            log_grad_norm=self.track_grad_norm,
            guard_nonfinite=self.nonfinite_action is not None)
        val_step = strategy.make_eval_step(
            eval_fn_builder("validation_step"), state_shardings,
            batch_sharding)
        test_step = strategy.make_eval_step(
            eval_fn_builder("test_step"), state_shardings, batch_sharding)

        self._state_shardings = state_shardings
        self._batch_sharding = batch_sharding
        self._train_step = train_step
        self._val_step = val_step
        self._test_step = test_step
        self.train_state = state
        return state

    # ------------------------------------------------------------------ #
    # fit loop (worker side)
    # ------------------------------------------------------------------ #
    def _fit_worker(self, module: TpuModule,
                    datamodule: Optional[TpuDataModule],
                    ckpt_path: Optional[str]) -> WorkerOutput:
        self._attach(module, datamodule)
        self.should_stop = False
        getattr(self.profiler, "reset", lambda: None)()  # per-fit scope
        module.prepare_data()
        if datamodule is not None:
            datamodule.prepare_data()
            datamodule.setup("fit")
        module.setup("fit")
        for cb in self.callbacks:
            cb.setup(self, module, "fit")

        train_loader = self._dataloader("train_dataloader")
        val_loader = self._dataloader("val_dataloader")

        sample_batch, train_loader = self._peek_first_batch(train_loader)
        restored_ckpt = None
        if ckpt_path == "auto":
            ckpt_path, restored_ckpt = self._resolve_auto_resume()
        elif ckpt_path is not None:
            restored_ckpt = self._read_checkpoint(ckpt_path)
        state = self._setup_state(
            sample_batch,
            restored_ckpt["state"] if restored_ckpt else None)
        start_epoch = 0
        self._resume_skip = 0
        if restored_ckpt is not None:
            saved_world = int(
                (restored_ckpt.get("world") or {}).get("world_size") or 0)
            if saved_world and saved_world != self.strategy.num_workers \
                    and self.telemetry is not None:
                from ray_lightning_tpu.reliability.elastic import (
                    COUNTER_RESHARDS, EVENT_CKPT_RESHARD)
                self.telemetry.event(
                    EVENT_CKPT_RESHARD, from_world=saved_world,
                    to_world=self.strategy.num_workers,
                    global_step=int(restored_ckpt.get("global_step", 0)))
                self.telemetry.metrics.counter(
                    COUNTER_RESHARDS,
                    help="checkpoints re-sharded onto a different world "
                         "size on restore").inc()
            saved_epoch = int(restored_ckpt.get("epoch", -1))
            # mid-epoch checkpoints (periodic every_n_train_steps saves)
            # record how many batches of `saved_epoch` were done; resume
            # re-enters that epoch and fast-forwards the loader. -1 (or a
            # pre-knob checkpoint) = saved at the epoch boundary.
            bie = int((restored_ckpt.get("loop") or {})
                      .get("batch_in_epoch", -1))
            if bie < 0:
                start_epoch = saved_epoch + 1
            else:
                start_epoch = max(0, saved_epoch)
                self._resume_skip = bie
            self.global_step = int(restored_ckpt.get("global_step", 0))
            for cb in self.callbacks:
                cb_state = restored_ckpt.get("callbacks", {}).get(
                    type(cb).__name__)
                if cb_state:
                    cb.load_state_dict(cb_state)
                cb_tree = restored_ckpt.get("callback_arrays", {}).get(
                    type(cb).__name__)
                if cb_tree is not None:
                    cb.load_sharded_state(cb_tree)
            module.on_load_checkpoint(restored_ckpt.get("module", {}))

        module.on_fit_start()
        for cb in self.callbacks:
            cb.on_fit_start(self, module)

        # sanity validation: PTL fires the full validation hook sequence
        # here too, with trainer.sanity_checking=True so callbacks that
        # must skip it (e.g. Tune reports) can gate on the flag
        if val_loader is not None and self.num_sanity_val_steps > 0:
            self.sanity_checking = True
            for cb in self.callbacks:
                cb.on_sanity_check_start(self, module)
            self._run_validation(val_loader, module,
                                 limit=self.num_sanity_val_steps)
            for cb in self.callbacks:
                cb.on_sanity_check_end(self, module)
            self.sanity_checking = False

        module.on_train_start()
        for cb in self.callbacks:
            cb.on_train_start(self, module)

        tel = self.telemetry
        if tel is not None:
            tel.event("worker.start", rank=self.global_rank,
                      world_size=self.world_size,
                      num_devices=self.num_devices)
            tel.event("fit.start", max_epochs=self.max_epochs,
                      max_steps=self.max_steps,
                      start_epoch=start_epoch,
                      global_step=self.global_step,
                      resumed=restored_ckpt is not None)

        # gang supervision seat: under a remote launcher this resolves to
        # the worker-side shim's heartbeat (per-rank liveness beats back
        # to the driver's watchdog); local launchers have no attribute
        # and the loop skips it — one None check per batch when disarmed
        _beat = getattr(self._launcher, "heartbeat", None)
        _rank = self.strategy.global_rank

        stop = False
        for epoch in range(start_epoch, self.max_epochs):
            self.current_epoch = epoch
            if tel is not None:
                tel.event("epoch.start", epoch=epoch,
                          global_step=self.global_step)
            if hasattr(train_loader, "set_epoch"):
                train_loader.set_epoch(epoch)
            module.on_train_epoch_start()
            for cb in self.callbacks:
                cb.on_train_epoch_start(self, module)

            epoch_logs: List[Dict[str, Any]] = []
            n_batches = self._resolve_limit(train_loader,
                                            self.limit_train_batches)
            # mid-epoch validation cadence (PTL val_check_interval):
            # float f = every int(f * n_batches) batches of this epoch;
            # int N = every N train batches counted across epochs.
            # check_val_every_n_epoch still gates WHICH epochs validate;
            # the interval subdivides those epochs (PTL composition).
            epoch_validates = (epoch + 1) % self.check_val_every_n_epoch \
                == 0
            val_every = 0
            if self.val_check_interval is not None and \
                    val_loader is not None and epoch_validates:
                if isinstance(self.val_check_interval, float):
                    if n_batches >= 2**31:
                        raise ValueError(
                            "a float val_check_interval needs a sized "
                            "train dataloader (or an integer "
                            "limit_train_batches) to resolve the epoch "
                            "length; pass an int interval instead")
                    val_every = max(1, int(self.val_check_interval
                                           * n_batches))
                else:
                    val_every = int(self.val_check_interval)
            # resume fast-forward: a mid-epoch checkpoint recorded how
            # many batches of this epoch it had completed; skip exactly
            # those (the loader is deterministic per epoch via set_epoch,
            # so the replayed tail matches the uninterrupted run)
            skip = self._resume_skip if epoch == start_epoch else 0
            self._batch_in_epoch = skip
            feed = train_loader
            if skip:
                import itertools
                feed = itertools.islice(iter(train_loader), skip, None)
            t0 = time.perf_counter()
            for batch_idx, batch in enumerate(
                    self.profiler.profile_iterable(
                        self._prefetch(feed, max(0, n_batches - skip)),
                        "get_train_batch"), start=skip):
                # worker-class chaos sites fire before the step: "stall"
                # wedges this loop (heartbeats stop, the driver's gang
                # watchdog must notice), "exit" hard-kills the process
                _faults.fire("worker.stall", rank=_rank)
                _faults.fire("worker.exit", rank=_rank)
                mode = _faults.fire("train.step")
                if mode == _faults.MODE_NAN:
                    from ray_lightning_tpu.reliability.guard import \
                        poison_nan
                    batch = shardlib.put_global_batch(
                        poison_nan(jax.device_get(batch)),
                        self._batch_sharding)
                module.on_train_batch_start(batch, batch_idx)
                for cb in self.callbacks:
                    cb.on_train_batch_start(self, module, batch, batch_idx)
                module.on_before_optimizer_step(self._tx)
                for cb in self.callbacks:
                    cb.on_before_optimizer_step(self, module, self._tx)
                with self.profiler.profile("train_step"):
                    state, logs = self._train_step(state, batch)
                if self.nonfinite_action is not None and \
                        bool(np.asarray(jax.device_get(
                            logs["nonfinite"]))):
                    state = self._handle_nonfinite(state)
                self.train_state = state
                self.global_step += 1
                self._batch_in_epoch = batch_idx + 1
                if _beat is not None:  # step completed: tick liveness
                    _beat(self.global_step)
                epoch_logs.append(logs)
                self._last_logs = logs
                module.on_train_batch_end(logs, batch, batch_idx)
                for cb in self.callbacks:
                    cb.on_train_batch_end(self, module, logs, batch,
                                          batch_idx)
                if hasattr(self._launcher, "drain_queue"):
                    self._launcher.drain_queue()
                if val_every:
                    count = (batch_idx + 1 if isinstance(
                        self.val_check_interval, float)
                        else self.global_step)
                    if count % val_every == 0:
                        with self.profiler.profile("validation"):
                            self._run_validation(val_loader, module)
                if 0 <= self.max_steps <= self.global_step:
                    stop = True
                    break
                if self.should_stop:  # PTL parity: honored mid-epoch too
                    break

            # the epoch's batch loop is over: checkpoints taken from here
            # on (epoch-end ModelCheckpoint saves) resume at the NEXT
            # epoch, not inside this one
            self._batch_in_epoch = -1

            # epoch aggregation: one host sync per epoch, not per step
            agg = self._aggregate_epoch_logs(epoch_logs, prefix="train_")
            self.callback_metrics.update(agg)
            if epoch_logs:
                self.logged_metrics = _util.tensor_metrics_to_numpy(
                    jax.device_get(epoch_logs[-1]))
            if self.enable_progress_bar and self.strategy.global_rank == 0:
                dt = time.perf_counter() - t0
                msg = ", ".join(f"{k}={v:.4f}" for k, v in agg.items()
                                if np.isscalar(v))
                print(f"epoch {epoch}: {msg} ({dt:.1f}s)")  # tl-lint: allow-print — enable_progress_bar console UI

            # `self.should_stop` too: a mid-epoch interval validation may
            # have tripped EarlyStopping after the batch loop broke —
            # epoch-end validation must not run after a requested stop
            run_epoch_val = val_loader is not None and not stop and \
                not self.should_stop and epoch_validates
            if val_every:
                # interval mode owns validation; the epoch boundary only
                # adds one for a float interval that doesn't divide the
                # epoch (PTL: f=0.5 validates at 50% and 100%)
                run_epoch_val = (run_epoch_val
                                 and isinstance(self.val_check_interval,
                                                float)
                                 and n_batches % val_every != 0)
            if run_epoch_val:
                with self.profiler.profile("validation"):
                    self._run_validation(val_loader, module)

            module.on_train_epoch_end()
            with self.profiler.profile("epoch_end_callbacks"):
                for cb in self.callbacks:
                    cb.on_train_epoch_end(self, module)
            if tel is not None:
                tel.event("epoch.end", epoch=epoch,
                          global_step=self.global_step)
            if stop or self.should_stop:
                break

        module.on_train_end()
        for cb in self.callbacks:
            cb.on_train_end(self, module)
        module.on_fit_end()
        for cb in self.callbacks:
            cb.on_fit_end(self, module)
        module.teardown("fit")
        for cb in self.callbacks:
            cb.teardown(self, module, "fit")

        from ray_lightning_tpu.core.checkpoint import wait_for_async_saves
        wait_for_async_saves()
        if tel is not None:
            tel.event("fit.end", epoch=self.current_epoch,
                      global_step=self.global_step,
                      stopped_early=self.should_stop)
            # profiler sections (when one is armed) become gauges, so
            # the wall-clock breakdown is scrapeable, not just printable
            for name, (count, total) in getattr(
                    self.profiler, "records", dict)().items():
                tel.metrics.gauge(
                    f"profile_{name}_s",
                    help="SimpleProfiler section total (s)").set(total)
            # in-process launches only: under a remote launcher this
            # trainer is a worker-side COPY, and a flush here would
            # atomically overwrite a shared jsonl_path with only this
            # rank's events, clobbering the driver's log (the driver
            # flushes its own handle after launch.done)
            if not self.strategy.is_remote:
                tel.flush()
        if self.strategy.global_rank == 0:
            self.profiler.describe()
        return self._collect_rank_zero_results()

    def _handle_nonfinite(self, state):
        """Apply ``nonfinite_action`` to a step whose loss/grads went
        NaN/Inf. The compiled step already kept the pre-step weights
        (device-side select), so ``skip_batch`` only has to account for
        it; ``restore_last_ckpt`` additionally rolls the train state back
        to the newest checkpoint this run saved."""
        from ray_lightning_tpu.reliability.guard import NonFiniteError
        self.nonfinite_batches += 1
        where = (f"global step {self.global_step} "
                 f"(epoch {self.current_epoch})")
        if self.nonfinite_action == "raise":
            raise NonFiniteError(
                f"non-finite loss/gradients at {where}; use "
                "nonfinite_action='skip_batch' or 'restore_last_ckpt' "
                "to continue past poisoned batches instead")
        if self.nonfinite_action == "skip_batch":
            log_suppressed("train.step",
                           NonFiniteError(f"non-finite update at {where}"),
                           "update skipped, weights untouched")
            return state
        # restore_last_ckpt
        path = self._last_ckpt_path
        if path and not os.path.exists(path):
            # the recorded path can be pruned out from under us (top-k
            # kept better checkpoints): fall back to the newest valid
            # candidate in the same directory instead of crashing
            from ray_lightning_tpu.core.checkpoint import \
                find_resume_candidates
            candidates = find_resume_candidates(os.path.dirname(path))
            path = candidates[0] if candidates else ""
        if not path:
            raise NonFiniteError(
                f"non-finite loss/gradients at {where} and "
                "nonfinite_action='restore_last_ckpt', but no checkpoint "
                "is available — enable checkpointing (e.g. "
                "ModelCheckpoint(every_n_train_steps=...)) or use "
                "'skip_batch'")
        restored = self._read_checkpoint(path)
        host = serialization.from_state_dict(
            jax.device_get(state), restored["state"])
        self.nonfinite_restores += 1
        log_suppressed("train.step",
                       NonFiniteError(f"non-finite update at {where}"),
                       f"state rolled back to {path}")
        return jax.device_put(host, self._state_shardings)

    def _resolve_auto_resume(self):
        """``resume="auto"``: newest *valid* checkpoint — in-memory tier
        first, then the on-disk scan — or ``(None, None)`` for a fresh
        start.

        Only corruption-class errors (``CorruptCheckpointError``, I/O and
        decode failures) skip to an older candidate — a programming error
        (e.g. a callback's ``on_load_checkpoint`` raising) propagates
        instead of silently restarting training from scratch."""
        from ray_lightning_tpu.core.checkpoint import (
            CorruptCheckpointError, find_resume_candidates)
        ckpt_cb = self.checkpoint_callback
        root = ckpt_cb.dirpath if ckpt_cb is not None and ckpt_cb.dirpath \
            else os.path.join(self.default_root_dir, "checkpoints")
        candidates = find_resume_candidates(root)
        mem = self._memory_resume(candidates)
        if mem is not None:
            return mem
        for path in candidates:
            try:
                return path, self._read_checkpoint(path)
            except (CorruptCheckpointError, OSError, EOFError,
                    ValueError) as exc:
                log_suppressed(
                    "ckpt.load", exc,
                    f"resume='auto' skipping corrupt candidate {path}")
        return None, None

    def _memory_resume(self, disk_candidates):
        """The in-memory checkpoint tier of ``resume="auto"``.

        When a :class:`~ray_lightning_tpu.reliability.elastic
        .MemoryCheckpointStore` (or its worker-side client) is
        installed, its candidates are consulted AHEAD of disk: resume
        cost stops scaling with checkpoint storage. Disk still wins
        when it holds strictly newer progress — the memory tier (or its
        ring buddy) can die with the host while the disk copy survives,
        and resuming from a stale memory snapshot would silently lose
        committed steps. Uninstalled store = one global read + ``None``
        check."""
        from ray_lightning_tpu.reliability import elastic as _elastic
        store = _elastic.get_memory_store()
        if store is None:
            return None
        from ray_lightning_tpu.core.checkpoint import step_of
        disk_best = step_of(disk_candidates[0]) if disk_candidates else -1
        if disk_candidates and disk_best < 0:
            # disk checkpoints exist but their names carry no step= we
            # can order against — we cannot prove the memory tier is not
            # stale (its channel may have dropped commits while disk
            # advanced), and resuming stale RAM would silently roll back
            # committed progress. Disk wins.
            return None
        # copy lazily: only the one candidate actually restored is
        # copied — eager copies of every held multi-GB state would
        # double peak host RAM for nothing
        for step, ckpt in store.resume_candidates(copy_payloads=False):
            if step < disk_best:
                break  # disk holds newer committed progress
            if not isinstance(ckpt, dict) or ckpt.get("state") is None:
                log_suppressed(
                    "ckpt.memory",
                    ValueError(f"malformed in-memory candidate at "
                               f"step {step}"),
                    "skipping to the next memory candidate")
                continue
            import copy as _copy
            ckpt = _copy.deepcopy(ckpt)  # callbacks/restore may mutate
            for cb in self.callbacks:
                cb.on_load_checkpoint(self, self._module, ckpt)
            if self.telemetry is not None:
                from ray_lightning_tpu.reliability.elastic import \
                    EVENT_MEMORY_RESUME
                self.telemetry.event(EVENT_MEMORY_RESUME, step=step)
            return f"<memory:step={step}>", ckpt
        return None

    def _run_validation(self, val_loader, module, limit=None):
        module.on_validation_epoch_start()
        for cb in self.callbacks:
            cb.on_validation_start(self, module)
            cb.on_validation_epoch_start(self, module)
        n = self._resolve_limit(
            val_loader, self.limit_val_batches if limit is None else limit)
        agg = self._eval_loop(val_loader, self._val_step, n,
                              module=module, mode="validation")
        if not self.sanity_checking:
            # PTL discards sanity metrics: 2 untrained-weight batches must
            # never drive checkpoint monitors or reported values
            self.callback_metrics.update(agg)
        module.on_validation_epoch_end()
        for cb in self.callbacks:
            cb.on_validation_epoch_end(self, module)
            cb.on_validation_end(self, module)
        if hasattr(self._launcher, "drain_queue"):
            self._launcher.drain_queue()
        return agg

    def _eval_loop(self, loader, step_fn, n_batches: int,
                   module=None, mode: Optional[str] = None
                   ) -> Dict[str, Any]:
        """``mode`` ("validation" | "test") enables per-batch hooks (the
        sanity pass uses "validation" too, PTL-style, with
        ``trainer.sanity_checking`` set for callbacks that must skip it)."""
        logs_list: List[Dict[str, Any]] = []
        # gang liveness for evaluation too: eval batches advance no
        # global_step, but a rank chewing through them is NOT hung — beat
        # once per batch (step clamped >= 1 so the monitor switches from
        # startup_grace to the steady-state timeout once eval progresses)
        _beat = getattr(self._launcher, "heartbeat", None)
        # fold the training progress in so successive validation epochs see
        # fresh randomness (round-1 review: a fixed key reused identical
        # eval randomness every epoch), while staying run-deterministic
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.seed if self.seed is not None else 0),
            self.global_step)
        for batch_idx, batch in enumerate(self._prefetch(loader, n_batches)):
            if mode is not None:
                getattr(module, f"on_{mode}_batch_start",
                        lambda *a: None)(batch, batch_idx)
                for cb in self.callbacks:
                    getattr(cb, f"on_{mode}_batch_start")(
                        self, module, batch, batch_idx)
            logs = step_fn(self.train_state, batch,
                           jax.random.fold_in(rng, batch_idx))
            logs_list.append(logs)
            # sanity checking stays on liveness beats only (step=-1): a
            # step>=1 beat here would switch the monitor off its startup
            # grace BEFORE the first train-step compile — exactly the
            # quiet window the grace exists to cover
            if _beat is not None:
                _beat(-1 if self.sanity_checking
                      else max(1, self.global_step))
            if mode is not None:
                getattr(module, f"on_{mode}_batch_end",
                        lambda *a: None)(logs, batch, batch_idx)
                for cb in self.callbacks:
                    getattr(cb, f"on_{mode}_batch_end")(
                        self, module, logs, batch, batch_idx)
        return self._aggregate_epoch_logs(logs_list)

    def _aggregate_epoch_logs(self, logs_list: List[Dict[str, Any]],
                              prefix: str = "") -> Dict[str, Any]:
        if not logs_list:
            return {}
        host = jax.device_get(logs_list)
        keys = host[0].keys()
        out: Dict[str, Any] = {}
        for k in keys:
            vals = [np.asarray(h[k]) for h in host if k in h]
            name = k if (k != "loss" or not prefix) else prefix + k
            out[name] = float(np.mean([v.mean() for v in vals]))
        return out

    def _prefetch(self, loader, n_batches: int, depth: int = 2):
        """Cast + ``device_put`` up to ``depth`` batches ahead of the step.

        Double-buffering the input pipeline hides host→HBM transfer behind
        device compute (the overlap the reference inherits from torch
        DataLoader pinned-memory prefetch); backed by the same mechanism as
        :class:`ray_lightning_tpu.data.multiproc.DevicePrefetcher`.
        """
        import collections
        buf = collections.deque()
        count = 0
        for batch in loader:
            if count >= n_batches:
                break
            mode = _faults.fire("loader.next")
            if mode == _faults.MODE_NAN:
                from ray_lightning_tpu.reliability.guard import poison_nan
                batch = poison_nan(batch)
            buf.append(shardlib.put_global_batch(
                self._cast_batch(batch), self._batch_sharding))
            count += 1
            if len(buf) >= depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()

    def _resolve_limit(self, loader, limit) -> int:
        try:
            total = len(loader)
        except TypeError:
            total = float("inf")
        if limit is None:
            return total if total != float("inf") else 2**31
        if isinstance(limit, float) and 0 <= limit <= 1:
            if total == float("inf"):
                raise ValueError(
                    "A fractional batch limit requires a dataloader with "
                    "__len__; pass an integer limit instead.")
            return int(total * limit)
        return int(limit)

    # ------------------------------------------------------------------ #
    # evaluate / predict workers
    # ------------------------------------------------------------------ #
    def _prepare_eval(self, module, datamodule, ckpt_path, stage: str,
                      loader_name: str):
        self._attach(module, datamodule)
        module.prepare_data()
        if datamodule is not None:
            datamodule.prepare_data()
            datamodule.setup(stage)
        module.setup(stage)
        loader = self._dataloader(loader_name)
        if loader is None:
            raise ValueError(f"No {loader_name} defined for {stage}")
        if ckpt_path == "auto":
            _path, restored = self._resolve_auto_resume()
        elif ckpt_path:
            restored = self._read_checkpoint(ckpt_path)
        else:
            restored = None
        restored_state = restored["state"] if restored else None
        if restored_state is None and self.train_state is None:
            # weights recovered from a remote fit without a local template
            restored_state = getattr(self, "train_state_dict", None)
        if self.train_state is None or restored_state is not None:
            sample, loader = self._peek_first_batch(loader)
            self._setup_state(sample, restored_state)
        elif not hasattr(self, "_val_step"):
            sample, loader = self._peek_first_batch(loader)
            self._setup_state(sample)
        return loader

    def _evaluate_worker(self, module, datamodule, ckpt_path,
                         stage: str) -> WorkerOutput:
        loader_name = ("val_dataloader" if stage == "validate" else
                       "test_dataloader")
        loader = self._prepare_eval(module, datamodule, ckpt_path, stage,
                                    loader_name)
        if stage == "validate":
            agg = self._run_validation(loader, module)
        else:
            n = self._resolve_limit(loader, self.limit_test_batches)
            for cb in self.callbacks:
                cb.on_test_start(self, module)
                cb.on_test_epoch_start(self, module)
            agg = self._eval_loop(loader, self._test_step, n,
                                  module=module, mode="test")
            self.callback_metrics.update(agg)
            for cb in self.callbacks:
                cb.on_test_epoch_end(self, module)
                cb.on_test_end(self, module)
        return WorkerOutput(
            best_model_path=None,
            state_stream=None,
            trainer_state=dict(epoch=self.current_epoch,
                               global_step=self.global_step),
            callback_metrics=_util.tensor_metrics_to_numpy(
                self.callback_metrics),
            logged_metrics={},
            results=[agg])

    def _predict_worker(self, module, datamodule,
                        ckpt_path) -> WorkerOutput:
        loader = self._prepare_eval(module, datamodule, ckpt_path, "predict",
                                    "predict_dataloader")
        model = self._model
        state_shardings = self._state_shardings

        # out_shardings replicates the predictions (an all-gather over the
        # batch axis): under multi-controller SPMD the raw output is
        # sharded across processes and rank 0 could not device_get its
        # non-addressable shards. Single-process this is a no-op.
        @partial(jax.jit, out_shardings=self.strategy.scalar_sharding())
        def predict_step(state, batch):
            return module.predict_step(model, state.variables, batch,
                                       state.rng)

        n = self._resolve_limit(loader, self.limit_predict_batches)
        outs = []
        _beat = getattr(self._launcher, "heartbeat", None)
        for cb in self.callbacks:
            cb.on_predict_start(self, module)
            cb.on_predict_epoch_start(self, module)
        for batch_idx, batch in enumerate(loader):
            if batch_idx >= n:
                break
            for cb in self.callbacks:
                cb.on_predict_batch_start(self, module, batch, batch_idx)
            batch = shardlib.put_global_batch(
                self._cast_batch(batch), self._batch_sharding)
            out = jax.device_get(predict_step(self.train_state, batch))
            outs.append(out)
            if _beat is not None:  # gang liveness during prediction
                _beat(max(1, self.global_step))
            for cb in self.callbacks:
                cb.on_predict_batch_end(self, module, out, batch,
                                        batch_idx)
        for cb in self.callbacks:
            cb.on_predict_epoch_end(self, module)
            cb.on_predict_end(self, module)
        return WorkerOutput(
            best_model_path=None, state_stream=None,
            trainer_state=dict(epoch=self.current_epoch,
                               global_step=self.global_step),
            callback_metrics={}, logged_metrics={}, results=outs)

    # ------------------------------------------------------------------ #
    # results / checkpointing (worker↔driver contract)
    # ------------------------------------------------------------------ #
    def _consolidated_state(self, collective: bool = False):
        """Train state with every leaf host-fetchable on this process.

        Multi-controller SPMD with sharded leaves (ZeRO/FSDP) cannot
        ``device_get`` non-addressable shards. When every process reaches
        this call at the same program point (``collective=True``, e.g. the
        end-of-fit result collection), an all-gather replicates them first.
        From rank-0-gated paths (stream ``ModelCheckpoint``, Tune
        checkpoint thunks) a collective would deadlock the other ranks, so
        sharded multi-process states fail loudly there instead — use
        ``save_format="orbax"``, whose per-host shard writing exists for
        exactly this. Single-process or fully-addressable states pass
        through untouched.
        """
        state = self.train_state
        if state is None or jax.process_count() == 1:
            return state
        # Fully-replicated leaves (default DP) are host-fetchable even when
        # not fully addressable: the local shard holds the whole value.
        if all(getattr(leaf, "is_fully_addressable", True)
               or getattr(leaf, "is_fully_replicated", False)
               for leaf in jax.tree_util.tree_leaves(state)):
            return state
        if not collective:
            raise RuntimeError(
                "Cannot consolidate a cross-process sharded train state "
                "from a rank-0-only code path (the required all-gather is "
                "a collective every process must join). Save sharded "
                "multi-host states with save_format='orbax' instead of "
                "the stream format.")
        reps = jax.tree_util.tree_map(
            lambda _: self.strategy.scalar_sharding(), state)
        return jax.jit(lambda s: s, out_shardings=reps)(state)

    def _collect_rank_zero_results(self) -> WorkerOutput:
        """Parity: ``ray_launcher.py:313-350`` — best ckpt path, state as an
        in-memory byte stream, progress counters, numpy metrics."""
        ckpt_cb = self.checkpoint_callback
        best_path = ckpt_cb.best_model_path if ckpt_cb else None
        stream = None
        if self.strategy.is_remote:
            stream = _util.to_state_stream(
                serialization.to_state_dict(
                    jax.device_get(
                        self._consolidated_state(collective=True))))
        return WorkerOutput(
            best_model_path=best_path,
            state_stream=stream,
            trainer_state=dict(epoch=self.current_epoch,
                               global_step=self.global_step,
                               should_stop=self.should_stop),
            callback_metrics=_util.tensor_metrics_to_numpy(
                self.callback_metrics),
            logged_metrics=_util.tensor_metrics_to_numpy(
                self.logged_metrics),
            callback_states={
                type(cb).__name__: cb.state_dict()
                for cb in self.callbacks
            })

    def _recover_results(self, output: WorkerOutput,
                         module: TpuModule) -> None:
        """Parity: ``ray_launcher.py:352-380`` — restore weights, trainer
        progress, metrics into driver-side objects."""
        if output is None:
            return
        self.current_epoch = output.trainer_state.get(
            "epoch", self.current_epoch)
        self.global_step = output.trainer_state.get(
            "global_step", self.global_step)
        self.should_stop = output.trainer_state.get(
            "should_stop", self.should_stop)
        self.callback_metrics.update(
            _util.numpy_metrics_to_device(output.callback_metrics))
        self.logged_metrics.update(
            _util.numpy_metrics_to_device(output.logged_metrics))
        if output.state_stream is not None:
            restored = _util.load_state_stream(output.state_stream)
            if self.train_state is not None and \
                    hasattr(self, "_state_shardings"):
                host = serialization.from_state_dict(
                    jax.device_get(self.train_state), restored)
                self.train_state = jax.device_put(host,
                                                  self._state_shardings)
            else:
                # Remote launch with no driver-side template: keep the raw
                # state dict; `restore_train_state` re-materializes it once
                # a mesh/template exists (e.g. a later validate/predict).
                self.train_state_dict = restored
        if output.callback_states:
            for cb in self.callbacks:
                st = output.callback_states.get(type(cb).__name__)
                if st:
                    cb.load_state_dict(st)

    def save_checkpoint(self, filepath: str,
                        save_format: str = "stream",
                        async_save: bool = False) -> None:
        """Dump a full resumable checkpoint.

        ``save_format="stream"``: reference-parity byte-stream file
        (consolidates to host — rank-0 only). ``save_format="orbax"``:
        sharded directory checkpoint, every host writes its own shards —
        see :mod:`ray_lightning_tpu.core.checkpoint`. ``async_save``
        (orbax only) overlaps the disk commit with training; the trainer
        waits for in-flight commits at fit end.
        """
        if async_save and save_format != "orbax":
            raise ValueError(
                "async_save requires save_format='orbax' (the stream "
                "format is a rank-0 host consolidation; there is no "
                "device-side copy to overlap)")
        if save_format == "orbax":
            from ray_lightning_tpu.core.checkpoint import \
                save_sharded_checkpoint
            ckpt = self.dump_checkpoint(consolidate=False)
            save_sharded_checkpoint(filepath, ckpt, self.train_state,
                                    async_save=async_save)
            self._last_ckpt_path = filepath
            self._memory_checkpoint(ckpt)
            return
        ckpt = self.dump_checkpoint()
        os.makedirs(os.path.dirname(os.path.abspath(filepath)), exist_ok=True)
        tmp = f"{filepath}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(_util.to_state_stream(ckpt))
            # pre-commit fault seat + atomic publish: a crash mid-write
            # leaves only the tmp file, which resume scans ignore
            _faults.fire("ckpt.save")
            os.replace(tmp, filepath)
        finally:
            if os.path.exists(tmp):  # failed before the rename: no litter
                os.remove(tmp)
        self._last_ckpt_path = filepath
        self._memory_checkpoint(ckpt)

    def _memory_checkpoint(self, ckpt: Dict[str, Any]) -> None:
        """Mirror a just-committed checkpoint into the in-memory tier.

        Runs AFTER the disk commit (the memory entry must never be the
        only copy of progress disk doesn't have) and only when a
        :class:`~ray_lightning_tpu.reliability.elastic
        .MemoryCheckpointStore`/client is installed — otherwise this is
        one global read + ``None`` check. Best-effort by design: a
        state that cannot be host-gathered (multi-host non-addressable
        shards) skips the memory tier with a logged suppression and the
        disk copy stands alone."""
        from ray_lightning_tpu.reliability import elastic as _elastic
        store = _elastic.get_memory_store()
        if store is None:
            return
        try:
            payload = jax.device_get(ckpt)
            store.put(int(self.global_step), payload,
                      rank=self.strategy.global_rank,
                      world_size=self.strategy.num_workers)
        except Exception as exc:  # noqa: BLE001 — memory tier is best-effort
            log_suppressed("ckpt.memory", exc,
                           "in-memory checkpoint skipped; the disk copy "
                           "is intact")

    def dump_checkpoint(self, consolidate: bool = True) -> Dict[str, Any]:
        module_state: Dict[str, Any] = {}
        if self._module is not None:
            self._module.on_save_checkpoint(module_state)
        ckpt = {
            "epoch": self.current_epoch,
            "global_step": self.global_step,
            # loop position inside the current epoch (-1 = boundary):
            # lets resume="auto" fast-forward the dataloader instead of
            # skipping the rest of a half-trained epoch
            "loop": {"batch_in_epoch": int(self._batch_in_epoch)},
            # the saving world's size: restore compares it against the
            # resuming world and emits ckpt.reshard on a mismatch (the
            # state itself re-shards via full host arrays either way)
            "world": {"world_size": int(self.strategy.num_workers)},
            "state": serialization.to_state_dict(
                jax.device_get(self._consolidated_state()) if consolidate
                else self.train_state),
            "callbacks": {
                type(cb).__name__: cb.state_dict()
                for cb in self.callbacks
            },
            "module": module_state,
        }
        # device trees contributed by callbacks (e.g. EMA params) ride the
        # train-state path: consolidated to host for the stream format,
        # left as live shards for orbax (each process writes its own)
        cb_arrays = {}
        for cb in self.callbacks:
            tree = cb.sharded_state()
            if tree is not None:
                cb_arrays[type(cb).__name__] = (
                    jax.device_get(tree) if consolidate else tree)
        if cb_arrays:
            ckpt["callback_arrays"] = cb_arrays
        for cb in self.callbacks:
            cb.on_save_checkpoint(self, self._module, ckpt)
        return ckpt

    def _read_checkpoint(self, path: str) -> Dict[str, Any]:
        from ray_lightning_tpu.core.checkpoint import (
            is_sharded_checkpoint, load_sharded_checkpoint,
            wait_for_async_saves)
        wait_for_async_saves()  # never restore a half-committed directory
        if is_sharded_checkpoint(path):
            ckpt = load_sharded_checkpoint(path)
        else:
            with open(path, "rb") as f:
                ckpt = _util.load_state_stream(f.read())
        for cb in self.callbacks:
            cb.on_load_checkpoint(self, self._module, ckpt)
        return ckpt
