"""User-facing module contract — the LightningModule analog, functional-style.

The reference rides PyTorch Lightning's ``LightningModule`` (models in
``ray_lightning/tests/utils.py:28-148`` implement ``training_step``,
``configure_optimizers``, dataloaders). The TPU-native contract keeps the
same mental model but splits *stateful configuration* (done once, host-side)
from *pure traced steps* (compiled by XLA):

- ``configure_model()`` returns a flax ``nn.Module`` (the architecture).
- ``configure_optimizers()`` returns an optax ``GradientTransformation``.
- ``training_step(model, variables, batch, rng)`` is PURE: it is traced once
  under ``jit`` and must contain no data-dependent Python control flow. It
  returns a scalar loss (metrics via ``self.log`` or a ``(loss, logs)``
  tuple).
- ``self.log(name, value)`` works *inside* traced steps: logged tracers are
  captured at trace time and threaded through the compiled function's
  outputs, so per-step metrics incur zero extra host↔device syncs.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

import optax


class _LogBuffer:
    """Trace-time metric capture (see module docstring)."""

    def __init__(self):
        self._buf: Dict[str, Any] = {}
        self._meta: Dict[str, Dict[str, Any]] = {}

    def log(self, name, value, on_step=False, on_epoch=True, prog_bar=False):
        self._buf[name] = value
        self._meta[name] = dict(
            on_step=on_step, on_epoch=on_epoch, prog_bar=prog_bar)

    def drain(self) -> Tuple[Dict[str, Any], Dict[str, Dict[str, Any]]]:
        buf, meta = self._buf, self._meta
        self._buf, self._meta = {}, {}
        return buf, meta


class TpuModule:
    """Base class for user models. See module docstring for the contract."""

    def __init__(self):
        self.trainer = None  # set by Trainer.fit
        self._log_buffer = _LogBuffer()

    # ------------------------------------------------------------------ #
    # configuration (host-side, called once per fit inside the worker)
    # ------------------------------------------------------------------ #
    def configure_model(self):
        """Return the flax ``nn.Module`` architecture."""
        raise NotImplementedError

    def configure_optimizers(self) -> optax.GradientTransformation:
        """Return an optax transform (default: Adam 1e-3)."""
        return optax.adam(1e-3)

    def init_variables(self, model, rng, batch):
        """Initialize model variables from an example batch.

        Default heuristic: feed the first element of a tuple batch (the
        inputs) or the batch itself. Override for models whose ``__call__``
        takes extra arguments (masks, deterministic flags, ...). Runs under
        ``jit`` with sharded outputs, so giant models initialize directly
        into their sharded layout without a host-memory copy.
        """
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        return model.init(rng, x)

    def setup(self, stage: str) -> None:
        """Called in every worker before model construction."""

    def teardown(self, stage: str) -> None:
        """Called in every worker after the stage completes."""

    def prepare_data(self) -> None:
        """Host-side data download/preparation.

        Parity with PTL ``prepare_data`` as invoked by the reference worker
        (``ray_lightning/launchers/ray_launcher.py:291``): runs once per
        worker process before the fit loop.
        """

    # ------------------------------------------------------------------ #
    # pure steps (traced under jit; NO python side effects besides log())
    # ------------------------------------------------------------------ #
    def training_step(self, model, variables, batch, rng):
        """Return scalar loss, or ``(loss, logs)``, or
        ``(loss, logs, mutated_model_state)`` for models with mutable
        collections (e.g. batchnorm ``batch_stats``)."""
        raise NotImplementedError

    def validation_step(self, model, variables, batch, rng) -> Dict[str, Any]:
        """Return a dict of metric scalars (or use ``self.log``)."""
        return {}

    def test_step(self, model, variables, batch, rng) -> Dict[str, Any]:
        return self.validation_step(model, variables, batch, rng)

    def predict_step(self, model, variables, batch, rng):
        return model.apply(variables, batch)

    # ------------------------------------------------------------------ #
    # logging
    # ------------------------------------------------------------------ #
    def log(self, name: str, value: Any, on_step: bool = False,
            on_epoch: bool = True, prog_bar: bool = False,
            sync_dist: bool = True) -> None:
        """Log a metric from inside (or outside) a traced step.

        ``sync_dist`` is accepted for API parity; under SPMD every metric is
        already computed on the global batch, so cross-worker reduction is
        implicit — the collective the reference needs here (PTL's
        ``sync_dist`` all-reduce) does not exist as a separate step.
        """
        del sync_dist
        self._log_buffer.log(name, value, on_step, on_epoch, prog_bar)

    # ------------------------------------------------------------------ #
    # data
    # ------------------------------------------------------------------ #
    def train_dataloader(self) -> Iterable:
        raise NotImplementedError

    def val_dataloader(self) -> Optional[Iterable]:
        return None

    def test_dataloader(self) -> Optional[Iterable]:
        return None

    def predict_dataloader(self) -> Optional[Iterable]:
        return None

    # ------------------------------------------------------------------ #
    # hooks (subset of PTL's, the ones the reference's tests exercise)
    # ------------------------------------------------------------------ #
    def on_fit_start(self) -> None: ...
    def on_fit_end(self) -> None: ...
    def on_train_start(self) -> None: ...
    def on_train_end(self) -> None: ...
    def on_train_epoch_start(self) -> None: ...
    def on_train_epoch_end(self) -> None: ...
    def on_validation_epoch_start(self) -> None: ...
    def on_validation_epoch_end(self) -> None: ...
    def on_train_batch_start(self, batch, batch_idx: int) -> None: ...
    def on_train_batch_end(self, outputs, batch, batch_idx: int) -> None: ...
    def on_validation_batch_start(self, batch, batch_idx: int) -> None: ...
    def on_validation_batch_end(self, outputs, batch,
                                batch_idx: int) -> None: ...
    def on_before_optimizer_step(self, optimizer) -> None:
        """Per training batch, before the fused compiled step (see
        ``Callback.on_before_optimizer_step`` for the TPU semantics)."""
        ...

    # checkpointable custom state (parity: BoringModel's
    # on_save_checkpoint/on_load_checkpoint, tests/utils.py:28-96)
    def on_save_checkpoint(self, checkpoint: Dict[str, Any]) -> None: ...
    def on_load_checkpoint(self, checkpoint: Dict[str, Any]) -> None: ...


class TpuDataModule:
    """Datamodule analog (parity: ``XORDataModule``, tests/utils.py:151-210)."""

    def prepare_data(self) -> None: ...

    def setup(self, stage: str) -> None: ...

    def train_dataloader(self) -> Iterable:
        raise NotImplementedError

    def val_dataloader(self) -> Optional[Iterable]:
        return None

    def test_dataloader(self) -> Optional[Iterable]:
        return None

    def predict_dataloader(self) -> Optional[Iterable]:
        return None
