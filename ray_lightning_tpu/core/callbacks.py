"""Trainer callbacks.

Callback hooks mirror the subset of PTL's callback API the reference's tests
actually exercise (the "callback-as-probe" pattern, SURVEY.md §4): epoch
start/end, batch end, validation end, sanity-check gates, plus checkpoint
save/load state. ``EpochStatsCallback`` is the TPU analog of the reference's
``CUDACallback`` (``examples/ray_ddp_sharded_example.py:16-45``) measuring
epoch wall-time and device memory.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

import jax
import numpy as np


class Callback:
    def setup(self, trainer, pl_module, stage: str) -> None: ...
    def teardown(self, trainer, pl_module, stage: str) -> None: ...
    def on_fit_start(self, trainer, pl_module) -> None: ...
    def on_fit_end(self, trainer, pl_module) -> None: ...
    def on_sanity_check_start(self, trainer, pl_module) -> None: ...
    def on_sanity_check_end(self, trainer, pl_module) -> None: ...
    def on_train_start(self, trainer, pl_module) -> None: ...
    def on_train_end(self, trainer, pl_module) -> None: ...
    def on_train_epoch_start(self, trainer, pl_module) -> None: ...
    def on_train_epoch_end(self, trainer, pl_module) -> None: ...
    def on_train_batch_start(self, trainer, pl_module, batch,
                             batch_idx: int) -> None: ...
    def on_train_batch_end(self, trainer, pl_module, outputs, batch,
                           batch_idx: int) -> None: ...
    def on_validation_start(self, trainer, pl_module) -> None: ...
    def on_validation_end(self, trainer, pl_module) -> None: ...
    def on_validation_epoch_start(self, trainer, pl_module) -> None: ...
    def on_validation_epoch_end(self, trainer, pl_module) -> None: ...
    def on_validation_batch_start(self, trainer, pl_module, batch,
                                  batch_idx: int,
                                  dataloader_idx: int = 0) -> None: ...
    def on_validation_batch_end(self, trainer, pl_module, outputs, batch,
                                batch_idx: int,
                                dataloader_idx: int = 0) -> None: ...
    def on_test_start(self, trainer, pl_module) -> None: ...
    def on_test_end(self, trainer, pl_module) -> None: ...
    def on_test_epoch_start(self, trainer, pl_module) -> None: ...
    def on_test_epoch_end(self, trainer, pl_module) -> None: ...
    def on_test_batch_start(self, trainer, pl_module, batch,
                            batch_idx: int,
                            dataloader_idx: int = 0) -> None: ...
    def on_test_batch_end(self, trainer, pl_module, outputs, batch,
                          batch_idx: int,
                          dataloader_idx: int = 0) -> None: ...
    def on_predict_start(self, trainer, pl_module) -> None: ...
    def on_predict_end(self, trainer, pl_module) -> None: ...
    def on_predict_epoch_start(self, trainer, pl_module) -> None: ...
    def on_predict_epoch_end(self, trainer, pl_module) -> None: ...
    def on_predict_batch_start(self, trainer, pl_module, batch,
                               batch_idx: int,
                               dataloader_idx: int = 0) -> None: ...
    def on_predict_batch_end(self, trainer, pl_module, outputs, batch,
                             batch_idx: int,
                             dataloader_idx: int = 0) -> None: ...
    def on_before_optimizer_step(self, trainer, pl_module,
                                 optimizer) -> None:
        """Fired once per training batch, before the compiled step.

        TPU-native semantic shift vs PTL: grads, update, and apply are
        fused into ONE XLA program (the whole point — psum fuses into
        backprop), so there is no host point "after backward, before
        step". This hook is the per-batch seat for LR scheduling /
        optimizer introspection; per-gradient inspection belongs inside
        ``training_step`` (jnp ops) instead.
        """
        ...
    def on_save_checkpoint(self, trainer, pl_module,
                           checkpoint: Dict[str, Any]) -> None: ...
    def on_load_checkpoint(self, trainer, pl_module,
                           checkpoint: Dict[str, Any]) -> None: ...
    def state_dict(self) -> Dict[str, Any]:
        return {}
    def load_state_dict(self, state: Dict[str, Any]) -> None: ...
    def sharded_state(self) -> Optional[Any]:
        """Optional pytree of ``jax.Array`` leaves to persist with the
        checkpoint. Unlike ``state_dict`` (host scalars, msgpack-encoded),
        this travels the same path as the train state: consolidated for
        the stream format, written shard-by-shard for orbax — so device
        trees (e.g. an EMA of sharded params) checkpoint without a host
        gather."""
        return None
    def load_sharded_state(self, tree: Any) -> None: ...


class ModelCheckpoint(Callback):
    """Epoch-end checkpointing with best-model tracking.

    Parity target: PTL's ``ModelCheckpoint`` as used by the reference —
    runs inside the rank-0 worker, and only ``best_model_path`` crosses back
    to the driver (``ray_lightning/launchers/ray_launcher.py:320-322``).
    """

    def __init__(self,
                 dirpath: Optional[str] = None,
                 filename: str = "epoch={epoch}-step={step}",
                 monitor: Optional[str] = None,
                 mode: str = "min",
                 save_top_k: int = 1,
                 save_last: bool = False,
                 save_format: str = "stream",
                 async_save: bool = False,
                 every_n_train_steps: int = 0,
                 keep_last_n: Optional[int] = None):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        if save_format not in ("stream", "orbax"):
            raise ValueError(
                f"save_format must be 'stream' or 'orbax', got "
                f"{save_format!r}")
        if async_save and save_format != "orbax":
            raise ValueError("async_save requires save_format='orbax'")
        if every_n_train_steps < 0:
            raise ValueError(
                f"every_n_train_steps must be >= 0, got "
                f"{every_n_train_steps}")
        if keep_last_n is not None and keep_last_n < 1:
            raise ValueError(
                f"keep_last_n must be >= 1 (the newest committed "
                f"checkpoint is never pruned), got {keep_last_n}")
        self.dirpath = dirpath
        self.filename = filename
        self.monitor = monitor
        self.mode = mode
        self.save_top_k = save_top_k
        self.save_last = save_last
        self.save_format = save_format
        self.async_save = async_save
        # periodic cadence for crash-safe resume: every N train batches,
        # save an unmonitored mid-epoch checkpoint (the ckpt records its
        # batch-in-epoch position, so resume="auto" fast-forwards the
        # loader instead of replaying or skipping the half-epoch). 0 =
        # epoch-end saves only.
        self.every_n_train_steps = every_n_train_steps
        # retention for long chaos runs: after each save, prune committed
        # checkpoints beyond the newest keep_last_n (tmp-safe and
        # marker-aware — see core.checkpoint.prune_checkpoints; the
        # best/top-k ledger and 'last' are always protected). None = keep
        # everything the top-k ledger doesn't already prune.
        self.keep_last_n = keep_last_n
        self.best_model_path: str = ""
        self.best_model_score: Optional[float] = None
        self.last_model_path: str = ""
        self._saved: list = []  # (score, path), worst-first
        self._last_saved_path: str = ""
        # rolling crash-safety checkpoint (monitored configs only; see
        # _save — unmonitored configs keep periodic saves in the ledger)
        self._last_periodic_path: str = ""

    def setup(self, trainer, pl_module, stage: str) -> None:
        if self.dirpath is None:
            self.dirpath = os.path.join(trainer.default_root_dir,
                                        "checkpoints")

    def _is_better(self, score: float) -> bool:
        if self.best_model_score is None:
            return True
        return (score < self.best_model_score if self.mode == "min" else
                score > self.best_model_score)

    def on_train_batch_end(self, trainer, pl_module, outputs, batch,
                           batch_idx: int) -> None:
        # periodic mid-epoch cadence: unmonitored (metrics may not exist
        # yet), purely for crash-safe resume
        if self.every_n_train_steps < 1 or \
                trainer.global_step % self.every_n_train_steps:
            return
        self._save(trainer, monitor_val=None, periodic=True)

    def on_train_epoch_end(self, trainer, pl_module) -> None:
        self._save(trainer, monitor_val=self._monitor_value(trainer))

    _SKIP = object()  # monitored metric absent: skip this save entirely

    def _monitor_value(self, trainer):
        if self.monitor is None:
            return None
        raw = trainer.callback_metrics.get(self.monitor)
        if raw is None:
            # PTL semantics: monitored metric absent this epoch (e.g.
            # validation didn't run) ⇒ skip, never rank an unscored
            # checkpoint against real scores.
            if trainer.global_rank == 0:
                import warnings
                warnings.warn(
                    f"ModelCheckpoint: monitored metric "
                    f"{self.monitor!r} not found in callback_metrics; "
                    "skipping checkpoint this epoch.")
            return self._SKIP
        return float(np.asarray(raw))

    def _save(self, trainer, monitor_val, periodic: bool = False) -> None:
        if self.save_top_k == 0 or monitor_val is self._SKIP:
            return
        # The orbax save is a *collective*: every jax.distributed process
        # must join (each writes its own non-addressable shards and all
        # meet at orbax's multihost sync barrier). Only the stream format —
        # a rank-0 host consolidation — may be rank-gated. Decisions below
        # (skip / filename) are computed identically on every rank from
        # replicated metrics, so all ranks stay convergent.
        collective = self.save_format == "orbax" and jax.process_count() > 1
        if trainer.global_rank != 0 and not collective:
            return
        name = self.filename.format(
            epoch=trainer.current_epoch, step=trainer.global_step)
        if monitor_val is not None:
            name = f"{name}-{self.monitor}={monitor_val:.4f}"
        if trainer.global_rank == 0:
            os.makedirs(self.dirpath, exist_ok=True)
        suffix = ".ckpt" if self.save_format == "stream" else ".orbax"
        path = os.path.join(self.dirpath, name + suffix)
        trainer.save_checkpoint(path, save_format=self.save_format,
                                async_save=self.async_save)
        self._last_saved_path = path
        # 'last' tracks epoch-end saves only: rewriting it every periodic
        # tick would double the cadence's checkpoint I/O for a copy the
        # step-ordered resume scan never prefers over the periodic file
        if self.save_last and not periodic:
            last_path = os.path.join(self.dirpath, "last" + suffix)
            trainer.save_checkpoint(last_path,
                                    save_format=self.save_format,
                                    async_save=self.async_save)
        if trainer.global_rank != 0:
            return
        # bookkeeping + pruning stay rank-0-only
        if periodic and self.monitor is not None:
            # a monitored checkpoint ledger scores in metric units; an
            # unmonitored crash-safety save must NOT compete there (a
            # recency score of -global_step would beat every real
            # mode='min' metric and hijack best_model_path / top-k).
            # Periodic saves instead roll: keep only the newest one.
            prev = self._last_periodic_path
            if prev and prev != path and os.path.exists(prev) and \
                    prev != self.best_model_path and \
                    all(prev != p for _s, p in self._saved):
                if os.path.isdir(prev):
                    import shutil
                    shutil.rmtree(prev, ignore_errors=True)
                else:
                    os.remove(prev)
            self._last_periodic_path = path
            self._retention_prune()
            return
        score = monitor_val if monitor_val is not None else \
            -float(trainer.global_step)  # no monitor: newest is best
        if self._is_better(score):
            self.best_model_score = score
            self.best_model_path = path
        # a periodic save and an epoch-end save can land on the same
        # step= path: keep one ledger entry per file on disk
        self._saved = [(s, p) for s, p in self._saved if p != path]
        self._saved.append((score, path))
        self._prune()
        if self.save_last:
            self.last_model_path = os.path.join(self.dirpath,
                                                "last" + suffix)
        self._retention_prune()

    def _retention_prune(self) -> None:
        """``keep_last_n`` retention: bound what long chaos runs leave in
        the checkpoint dir. Everything the callback still tracks (top-k
        ledger, best, 'last', the rolling periodic save) is protected —
        recency pruning must never delete a path the metric ledger
        would hand out."""
        if not self.keep_last_n or not self.dirpath:
            return
        if self.async_save and jax.process_count() > 1:
            # multi-host: other hosts' commit progress is unobservable
            # from here, so rank 0 must drain before deleting anything
            # (never rmtree across an unobserved async commit barrier).
            # Single-host needs no barrier: AsyncCheckpointer serializes
            # saves, so the only possibly-in-flight dir is
            # _last_saved_path — protected below — and a full wait per
            # save would serialize the loop async_save exists to overlap.
            from ray_lightning_tpu.core.checkpoint import \
                wait_for_async_saves
            wait_for_async_saves()
        from ray_lightning_tpu.core.checkpoint import prune_checkpoints
        protect = {p for _s, p in self._saved}
        protect.update({self.best_model_path, self.last_model_path,
                        self._last_periodic_path, self._last_saved_path})
        prune_checkpoints(self.dirpath, self.keep_last_n, protect=protect)

    def _prune(self) -> None:
        if self.save_top_k < 0:
            return
        reverse = self.mode == "max"
        self._saved.sort(key=lambda t: t[0], reverse=reverse)
        while len(self._saved) > self.save_top_k:
            _score, path = self._saved.pop()
            if path != self.best_model_path and os.path.exists(path):
                if os.path.isdir(path):  # orbax checkpoints are directories
                    # directories from *previous* epochs are already
                    # committed (AsyncCheckpointer serializes saves), but
                    # the save issued THIS call can itself be the worst
                    # and get pruned immediately — wait for that one case
                    # instead of serializing every epoch. Multi-process
                    # orbax saves are collective: this process's local
                    # serialization order says nothing about the other
                    # hosts' commit progress, so there rank 0 must drain
                    # its async queue before deleting any directory
                    # (ADVICE round 2: never rmtree across an unobserved
                    # commit barrier).
                    import shutil
                    if self.async_save and (
                            path == self._last_saved_path
                            or jax.process_count() > 1):
                        from ray_lightning_tpu.core.checkpoint import \
                            wait_for_async_saves
                        wait_for_async_saves()
                    shutil.rmtree(path, ignore_errors=True)
                else:
                    os.remove(path)

    def state_dict(self) -> Dict[str, Any]:
        return {
            "best_model_path": self.best_model_path,
            "best_model_score": self.best_model_score,
            "last_model_path": self.last_model_path,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.best_model_path = state.get("best_model_path", "")
        self.best_model_score = state.get("best_model_score")
        self.last_model_path = state.get("last_model_path", "")


class EarlyStopping(Callback):
    """Stop training when a monitored metric stops improving.

    Parity target: PTL's ``EarlyStopping`` as exercised through the
    reference's launcher (``tests/test_ddp.py:289-308`` — patience-driven
    stop on ``val_loss`` inside a Ray worker). Runs identically on every
    rank: the monitored metric comes from replicated ``callback_metrics``,
    so all SPMD processes reach the same stop decision with no collective.
    """

    def __init__(self,
                 monitor: str = "val_loss",
                 min_delta: float = 0.0,
                 patience: int = 3,
                 mode: str = "min",
                 check_on_train_epoch_end: bool = False,
                 verbose: bool = False,
                 strict: bool = True):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.monitor = monitor
        self.min_delta = abs(min_delta)
        self.patience = patience
        self.mode = mode
        self.check_on_train_epoch_end = check_on_train_epoch_end
        self.verbose = verbose
        self.strict = strict
        self.wait_count = 0
        self.stopped_epoch = 0
        self.best_score: Optional[float] = None

    def _improved(self, score: float) -> bool:
        if self.best_score is None:
            return True
        if self.mode == "min":
            return score < self.best_score - self.min_delta
        return score > self.best_score + self.min_delta

    def _run_check(self, trainer) -> None:
        if trainer.sanity_checking:
            return
        raw = trainer.callback_metrics.get(self.monitor)
        if raw is None:
            if self.strict:
                raise RuntimeError(
                    f"EarlyStopping: monitored metric {self.monitor!r} not "
                    f"found in callback_metrics "
                    f"({sorted(trainer.callback_metrics)}); pass strict="
                    "False to skip epochs where it is absent.")
            return
        score = float(np.asarray(raw))
        if self._improved(score):
            self.best_score = score
            self.wait_count = 0
            return
        self.wait_count += 1
        if self.wait_count >= self.patience:
            trainer.should_stop = True
            self.stopped_epoch = trainer.current_epoch
            if self.verbose and trainer.global_rank == 0:
                print(f"EarlyStopping: {self.monitor} did not improve for "  # tl-lint: allow-print — verbose=True console UI
                      f"{self.wait_count} checks (best "
                      f"{self.best_score:.6f}); stopping at epoch "
                      f"{self.stopped_epoch}.")

    def on_validation_end(self, trainer, pl_module) -> None:
        if not self.check_on_train_epoch_end:
            self._run_check(trainer)

    def on_train_epoch_end(self, trainer, pl_module) -> None:
        if self.check_on_train_epoch_end:
            self._run_check(trainer)

    def state_dict(self) -> Dict[str, Any]:
        return {
            "wait_count": self.wait_count,
            "stopped_epoch": self.stopped_epoch,
            "best_score": self.best_score,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.wait_count = state.get("wait_count", 0)
        self.stopped_epoch = state.get("stopped_epoch", 0)
        self.best_score = state.get("best_score")


class EpochStatsCallback(Callback):
    """Epoch wall-time + device HBM stats, averaged across the mesh.

    TPU analog of the reference's ``CUDACallback``
    (``examples/ray_ddp_sharded_example.py:16-45``), which records epoch
    time and peak CUDA memory and all-reduces the averages. Under SPMD a
    single process sees every local device, so the "all-reduce" is a host
    mean over per-device memory stats.
    """

    def __init__(self, print_stats: bool = True):
        self.print_stats = print_stats
        self.epoch_times: list = []
        self.peak_memory_mib: list = []
        self._t0 = 0.0
        self._stats_unavailable_logged = False

    def on_train_epoch_start(self, trainer, pl_module) -> None:
        self._t0 = time.perf_counter()

    def on_train_epoch_end(self, trainer, pl_module) -> None:
        trainer.block_until_ready()
        dt = time.perf_counter() - self._t0
        self.epoch_times.append(dt)
        peaks = []
        for d in trainer.devices:
            try:
                stats = d.memory_stats()
                if stats and "peak_bytes_in_use" in stats:
                    peaks.append(stats["peak_bytes_in_use"] / 2**20)
            except Exception as exc:  # noqa: BLE001 - cpu has no stats
                # expected on the CPU backend: note it ONCE per run, not
                # per device per epoch — the suppressed-exception channel
                # must stay readable for real failures
                if not self._stats_unavailable_logged:
                    from ray_lightning_tpu.reliability import \
                        log_suppressed
                    log_suppressed("callbacks.memory_stats", exc,
                                   f"device {d} exposes no memory stats"
                                   " (expected on CPU); reported once")
                    self._stats_unavailable_logged = True
        peak = float(np.mean(peaks)) if peaks else 0.0
        self.peak_memory_mib.append(peak)
        if self.print_stats and trainer.global_rank == 0:
            print(f"Epoch {trainer.current_epoch}: {dt:.2f}s, "  # tl-lint: allow-print — print_stats=True console UI
                  f"avg peak HBM {peak:.0f} MiB")


class EMAWeightAveraging(Callback):
    """Maintain an exponential moving average of the parameters on-device.

    TPU-native take on PTL's ``StochasticWeightAveraging``: the average is
    updated by a jitted elementwise merge that inherits the params'
    shardings (EMA shards live beside the param shards — no host copy, no
    gather), so it composes with DP/ZeRO/FSDP meshes unchanged.

    ``swap_validation=True`` runs every validation/test epoch with the
    averaged weights (swapped in before the eval loop, restored after) —
    monitored metrics and early stopping then see the EMA model. The raw
    weights are restored before ``ModelCheckpoint`` saves; checkpoints
    always carry BOTH trees (raw params in the train state, the EMA
    average in this callback's sharded state), so either model can be
    exported after resume.
    """

    def __init__(self, decay: float = 0.999, update_every: int = 1,
                 swap_validation: bool = False):
        if not 0.0 < decay < 1.0:
            raise ValueError(f"decay must be in (0, 1), got {decay}")
        self.decay = decay
        self.update_every = max(1, int(update_every))
        self.swap_validation = swap_validation
        self.ema_params = None
        self._stashed = None
        self._update = None

    def on_train_start(self, trainer, pl_module) -> None:
        if self.ema_params is None:
            # start from a true COPY of the current params (restored EMA
            # arrives via load_state_dict before this hook): the train
            # step donates its input state, so aliasing the live buffers
            # would leave the EMA pointing at deleted memory
            import jax.numpy as jnp
            self.ema_params = jax.tree_util.tree_map(
                jnp.copy, trainer.train_state.params)
        else:  # resumed: host numpy → device, following the live sharding
            self.ema_params = jax.tree_util.tree_map(
                lambda host, live: jax.device_put(host, live.sharding),
                self.ema_params, trainer.train_state.params)
        decay = self.decay

        @jax.jit
        def update(ema, params):
            return jax.tree_util.tree_map(
                lambda e, p: decay * e + (1.0 - decay) * p, ema, params)

        self._update = update

    def on_train_batch_end(self, trainer, pl_module, outputs, batch,
                           batch_idx: int) -> None:
        if trainer.global_step % self.update_every == 0:
            self.ema_params = self._update(self.ema_params,
                                           trainer.train_state.params)

    # -- swap the averaged weights in for evaluation ------------------- #
    def _swap_in(self, trainer) -> None:
        if self.swap_validation and self.ema_params is not None \
                and self._stashed is None:
            self._stashed = trainer.train_state.params
            trainer.train_state = trainer.train_state.replace(
                params=self.ema_params)

    def _swap_out(self, trainer) -> None:
        if self._stashed is not None:
            trainer.train_state = trainer.train_state.replace(
                params=self._stashed)
            self._stashed = None

    def on_validation_start(self, trainer, pl_module) -> None:
        self._swap_in(trainer)

    def on_validation_end(self, trainer, pl_module) -> None:
        self._swap_out(trainer)

    def on_test_start(self, trainer, pl_module) -> None:
        self._swap_in(trainer)

    def on_test_end(self, trainer, pl_module) -> None:
        self._swap_out(trainer)

    def sharded_state(self) -> Optional[Any]:
        # the EMA tree rides the train-state path (shard-by-shard under
        # orbax) — NEVER through the msgpack meta, which would host-gather
        # shards that multi-host processes can't even address
        return self.ema_params

    def load_sharded_state(self, tree: Any) -> None:
        # host numpy (stream/orbax restore) — re-placed onto the live
        # sharding by on_train_start
        self.ema_params = tree


class LambdaCallback(Callback):
    """Attach ad-hoc hook functions — the tests' callback-as-probe helper."""

    def __init__(self, **hooks):
        for name, fn in hooks.items():
            if not hasattr(Callback, name):
                raise ValueError(f"Unknown callback hook {name!r}")
            setattr(self, name, fn)


class LearningRateMonitor(Callback):
    """Record the scheduled learning rate into ``callback_metrics``.

    PTL's ``LearningRateMonitor`` analog for the optax world: requires the
    module's ``configure_optimizers`` to return ``(tx, schedule_fn)`` (the
    schedule is baked into ``tx``; the handle is for observability).
    ``logging_interval``: "epoch" (default) records at each train-epoch
    end; "step" records every batch.
    """

    def __init__(self, logging_interval: str = "epoch",
                 key: str = "lr"):
        if logging_interval not in ("epoch", "step"):
            raise ValueError("logging_interval must be 'epoch' or 'step'")
        self.logging_interval = logging_interval
        self.key = key

    def _record(self, trainer) -> None:
        lr = trainer.current_lr
        if lr is not None:
            trainer.callback_metrics[self.key] = lr

    def on_train_batch_end(self, trainer, pl_module, outputs, batch,
                           batch_idx: int) -> None:
        if self.logging_interval == "step":
            self._record(trainer)

    def on_train_epoch_end(self, trainer, pl_module) -> None:
        if self.logging_interval == "epoch":
            self._record(trainer)
