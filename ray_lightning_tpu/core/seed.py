"""Deterministic-seed plumbing.

Parity with the reference's seed chain: ``PL_GLOBAL_SEED`` is forwarded from
driver to every worker (``ray_lightning/launchers/ray_launcher.py:170-173``)
and re-applied per worker via ``reset_seed()`` (``ray_ddp.py:177``). The env
var here is ``TPU_PL_GLOBAL_SEED``; JAX randomness additionally flows through
explicit PRNG keys derived from the seed, which is the actually-load-bearing
path for reproducibility under XLA.
"""
from __future__ import annotations

import os
import random
from typing import Optional

import numpy as np

GLOBAL_SEED_ENV = "TPU_PL_GLOBAL_SEED"


def seed_everything(seed: Optional[int] = None) -> int:
    """Seed python/numpy RNGs and record the seed for worker forwarding."""
    if seed is None:
        env = os.environ.get(GLOBAL_SEED_ENV)
        seed = int(env) if env is not None else random.randint(0, 2**31 - 1)
    seed = int(seed)
    os.environ[GLOBAL_SEED_ENV] = str(seed)
    random.seed(seed)
    np.random.seed(seed % (2**32))
    return seed


def reset_seed() -> Optional[int]:
    """Re-apply the driver's seed inside a worker (parity: ``reset_seed()``)."""
    env = os.environ.get(GLOBAL_SEED_ENV)
    if env is None:
        return None
    return seed_everything(int(env))
