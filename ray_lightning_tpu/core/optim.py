"""Memory-efficient optimizer factory — the HBM lever for large models.

On a single 16 GB chip, plain AdamW at GPT-2-medium scale (350M params)
spends 3x f32 per parameter on optimizer state + master weights
(~4.2 GB), which is exactly the memory that forces the model into its
slowest layouts (scanned layers, small chunked loss — see
``docs/performance.md``). Two standard, independently-toggleable levers
buy that memory back:

- **bf16 first moment** (``moment_dtype="bfloat16"``): ``optax.adamw``
  stores ``mu`` in bf16 — same algorithm, moments rounded at rest.
  Frees 2 bytes/param (~0.7 GB at 350M).
- **Factored second moment** (``factored=True``): Adafactor's rank-1
  factorization (Shazeer & Stern, 2018) replaces the full ``nu`` with
  per-row + per-column accumulators for every matrix parameter. Frees
  ~4 bytes/param (~1.4 GB at 350M). This changes the optimizer (adamw →
  adafactor-with-momentum), so it is a modeling decision, not a free
  system knob — the factory keeps adam-style LR semantics
  (``multiply_by_parameter_scale=False``, explicit learning rate) so
  configs transfer.

The reference delegates optimizer choice entirely to the user's torch
code (its strategies never build one; SURVEY.md §2.1), so this factory
is net-new surface, motivated by the TPU memory model: HBM is the
binding constraint long before FLOPs on one chip.
"""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
import optax

OPTIMIZER_NAMES = ("adamw", "adamw_bf16m", "adafactor")


def make_optimizer(name: str = "adamw",
                   learning_rate: float = 3e-4,
                   *,
                   weight_decay: float = 0.0,
                   b1: float = 0.9,
                   b2: Optional[float] = None,
                   moment_dtype: Optional[Any] = None,
                   factored: Optional[bool] = None
                   ) -> optax.GradientTransformation:
    """Build an optimizer by memory profile.

    ``name`` picks a preset; ``moment_dtype``/``factored`` override it:

    - ``"adamw"`` — full f32 state (8 bytes/param). The default.
    - ``"adamw_bf16m"`` — AdamW with bf16 first moment (6 bytes/param).
      Same update math; ``mu`` is rounded to bf16 at rest.
    - ``"adafactor"`` — factored second moment + bf16 momentum
      (~2 bytes/param + rank-1 vectors). Largest saving; different
      optimizer family (update-norm clipping instead of bias
      correction), so re-check convergence when switching.

    ``b2=None`` (the default) means "the preset's default" (0.999 for
    the adam presets; not applicable to the factored branch). An
    *explicit* ``b2`` is **ignored** on the adafactor/factored branch —
    adafactor's second-moment decay is its own step-dependent schedule
    (``1 - step**-0.8``), not an adam beta, so there is nothing for it
    to map onto — and warns rather than silently dropping it.
    """
    if name not in OPTIMIZER_NAMES:
        raise ValueError(
            f"unknown optimizer {name!r}; expected one of "
            f"{OPTIMIZER_NAMES}")
    if name == "adafactor" or factored:
        if weight_decay and callable(learning_rate):
            raise ValueError(
                "the adafactor preset scales weight_decay by the (scalar)"
                " learning rate for adamw parity (optax.adafactor applies"
                " decay after lr scaling); with an LR schedule that"
                " constant does not exist — pass weight_decay=0 and"
                " compose decay explicitly, or use a scalar learning rate")
        # NB: adafactor's decay_rate is the exponent of its step-dependent
        # second-moment schedule (1 - step^-0.8), NOT an adam beta — b2
        # deliberately does not map onto it
        if b2 is not None:
            import warnings

            warnings.warn(
                f"b2={b2} is ignored by the factored (adafactor) branch: "
                "its second-moment decay is the built-in step schedule "
                "1 - step**-0.8, not an adam beta",
                stacklevel=2)
        return optax.adafactor(
            learning_rate=learning_rate,
            momentum=b1,
            dtype_momentum=moment_dtype or jnp.bfloat16,
            factored=True if factored is None else factored,
            # adam-style LR semantics: no parameter-scale multiply, so
            # the same learning_rate works when switching from adamw
            multiply_by_parameter_scale=False,
            clipping_threshold=1.0,
            # optax.adafactor applies weight_decay_rate AFTER lr scaling
            # (adamw applies it before, i.e. effective decay = lr * wd);
            # scale here so the same weight_decay value means the same
            # per-step shrinkage in both presets
            weight_decay_rate=(weight_decay * learning_rate)
            if weight_decay else None)
    mu_dtype = moment_dtype
    if name == "adamw_bf16m" and mu_dtype is None:
        mu_dtype = jnp.bfloat16
    return optax.adamw(learning_rate, b1=b1,
                       b2=0.999 if b2 is None else b2, mu_dtype=mu_dtype,
                       weight_decay=weight_decay)


def opt_state_bytes(opt_state) -> int:
    """Total bytes of an optimizer state tree — the observability hook
    for the memory claims above (used by tests and examples)."""
    import jax

    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(opt_state)
        if hasattr(leaf, "dtype") and hasattr(leaf, "size"))
