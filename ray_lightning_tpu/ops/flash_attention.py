"""Blockwise (flash) attention: O(block²) VMEM instead of O(T²) HBM.

Online-softmax formulation over KV blocks — the memory-efficient attention
the reference never needed (its largest axis was parameter memory, SURVEY.md
§5 "long-context: entirely absent") but a TPU-native framework must own for
long sequences. This module is the XLA implementation (``lax.map`` over query
blocks, ``lax.scan`` over KV blocks — compiles to a tight fused loop); the
hand-tiled pallas kernel rides the same math (see ``ops/pallas_flash.py``)
and is selected via ``flash_attention(..., use_pallas=True)`` on TPU.

Falls back to :func:`dot_product_attention` for arbitrary additive masks or
attention dropout (neither fits the blockwise accumulator cheaply).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ray_lightning_tpu.ops.attention import dot_product_attention

_BIG_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _block_update(carry: Tuple[jax.Array, jax.Array, jax.Array],
                  qi: jax.Array, kj: jax.Array, vj: jax.Array,
                  qpos: jax.Array, kpos: jax.Array,
                  causal: bool, kv_len: int, scale: float):
    """One online-softmax accumulation step.

    carry: m (B,H,bq) running max, l (B,H,bq) running denom,
           acc (B,bq,H,D) running numerator (f32).
    qi: (B,bq,H,D); kj/vj: (B,bk,H,D); qpos (bq,), kpos (bk,) global
    positions (kpos may exceed kv_len for padding — masked out).
    Shared by the flash kernel and ring attention (one step per ring hop).
    """
    m, l, acc = carry
    s = jnp.einsum("bqhd,bkhd->bhqk", qi, kj,
                   preferred_element_type=jnp.float32) * scale
    allow = (kpos < kv_len)[None, :]
    if causal:
        allow = allow & (kpos[None, :] <= qpos[:, None])
    s = jnp.where(allow[None, None], s, _BIG_NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(allow[None, None], p, 0.0)
    alpha = jnp.exp(m - m_new)  # (B,H,bq)
    l_new = l * alpha + p.sum(axis=-1)
    alpha_t = jnp.transpose(alpha, (0, 2, 1))[..., None]  # (B,bq,H,1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vj.dtype), vj,
                    preferred_element_type=jnp.float32)
    acc_new = acc * alpha_t + pv
    return m_new, l_new, acc_new


def _finalize(l: jax.Array, acc: jax.Array, dtype) -> jax.Array:
    l_t = jnp.transpose(l, (0, 2, 1))[..., None]  # (B,bq,H,1)
    return jnp.where(l_t > 0, acc / jnp.maximum(l_t, 1e-30), 0.0).astype(
        dtype)


def flash_attention(q: jax.Array,
                    k: jax.Array,
                    v: jax.Array,
                    *,
                    causal: bool = False,
                    mask: Optional[jax.Array] = None,
                    dropout_rate: float = 0.0,
                    dropout_rng: Optional[jax.Array] = None,
                    block_q: int = 512,
                    block_k: int = 1024,
                    softmax_dtype=jnp.float32,
                    use_pallas: Optional[bool] = None) -> jax.Array:
    """Blockwise attention; signature-compatible with
    :func:`dot_product_attention`. Shapes (B, T, H, D)."""
    del softmax_dtype  # always f32 in the accumulator
    if mask is not None or (dropout_rate > 0.0 and dropout_rng is not None):
        return dot_product_attention(
            q, k, v, causal=causal, mask=mask, dropout_rate=dropout_rate,
            dropout_rng=dropout_rng)

    if use_pallas is None:
        # trace-safe platform probe (tracers have no .devices())
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        from ray_lightning_tpu.ops.pallas_flash import pallas_flash_attention
        return pallas_flash_attention(q, k, v, causal=causal,
                                      block_q=block_q, block_k=block_k)

    B, T, H, D = q.shape
    S = k.shape[1]
    bq, bk = min(block_q, T), min(block_k, S)
    n_q, n_k = -(-T // bq), -(-S // bk)
    Tp, Sp = n_q * bq, n_k * bk
    scale = D ** -0.5

    qp = jnp.pad(q, ((0, 0), (0, Tp - T), (0, 0), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, Sp - S), (0, 0), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, Sp - S), (0, 0), (0, 0)))

    q_blocks = jnp.moveaxis(qp.reshape(B, n_q, bq, H, D), 1, 0)
    k_blocks = jnp.moveaxis(kp.reshape(B, n_k, bk, H, D), 1, 0)
    v_blocks = jnp.moveaxis(vp.reshape(B, n_k, bk, H, D), 1, 0)

    # causal offset aligns the *ends* of q and kv (standard for S != T)
    pos_shift = S - T

    # python loop over q blocks: the block index stays *static*, so the
    # causal KV-block skip is a static slice and the inner scan remains
    # reverse-differentiable (a dynamic fori_loop bound would not be)
    out_blocks = []
    for ib in range(n_q):
        off = ib * bq
        qi = q_blocks[ib]
        qpos = off + jnp.arange(bq) + pos_shift
        if causal:
            # last key this q block may attend to is off + bq - 1 + pos_shift
            n_needed = max(0, min(n_k,
                                  (off + bq + pos_shift + bk - 1) // bk))
        else:
            n_needed = n_k

        def inner(carry, kv, qi=qi, qpos=qpos):
            kj, vj, koff = kv
            kpos = koff + jnp.arange(bk)
            return _block_update(carry, qi, kj, vj, qpos, kpos, causal, S,
                                 scale), None

        init = (jnp.full((B, H, bq), _BIG_NEG, jnp.float32),
                jnp.zeros((B, H, bq), jnp.float32),
                jnp.zeros((B, bq, H, D), jnp.float32))
        (m, l, acc), _ = jax.lax.scan(
            inner, init,
            (k_blocks[:n_needed], v_blocks[:n_needed],
             jnp.arange(n_needed) * bk))
        out_blocks.append(_finalize(l, acc, q.dtype))

    out = jnp.stack(out_blocks, axis=1).reshape(B, Tp, H, D)
    return out[:, :T]
