from ray_lightning_tpu.ops.attention import dot_product_attention
from ray_lightning_tpu.ops.flash_attention import flash_attention

__all__ = ["dot_product_attention", "flash_attention"]
