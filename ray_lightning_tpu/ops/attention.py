"""Attention ops: XLA-fused reference path + pallas flash-attention hook.

The reference framework has no kernels of its own (its hot loop is torch
DDP); a TPU-native framework owns its attention math. Two tiers:

- :func:`dot_product_attention` — plain jnp einsum formulation. XLA already
  fuses softmax chains well on TPU; this is the correctness baseline and the
  CPU/test path.
- :mod:`ray_lightning_tpu.ops.flash_attention` — blockwise online-softmax
  attention (XLA loop), with the hand-tiled pallas kernel in
  ``ops/pallas_flash.py``; chosen via ``TransformerConfig.attention_impl``.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def causal_mask(q_len: int, kv_len: int, dtype=jnp.float32) -> jax.Array:
    """Additive causal mask of shape (1, 1, q_len, kv_len)."""
    i = jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 0)
    j = jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 1)
    offset = kv_len - q_len
    allow = j <= i + offset
    mask = jnp.where(allow, 0.0, jnp.finfo(dtype).min).astype(dtype)
    return mask[None, None, :, :]


def dot_product_attention(q: jax.Array,
                          k: jax.Array,
                          v: jax.Array,
                          *,
                          causal: bool = False,
                          mask: Optional[jax.Array] = None,
                          dropout_rate: float = 0.0,
                          dropout_rng: Optional[jax.Array] = None,
                          softmax_dtype=jnp.float32) -> jax.Array:
    """Multi-head attention core. Shapes: (B, T, H, D) for q/k/v.

    Softmax runs in ``softmax_dtype`` (f32) regardless of input dtype —
    the standard bf16-safe formulation for the MXU.
    """
    *_, num_heads, head_dim = q.shape
    del num_heads
    scale = head_dim ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=softmax_dtype) * scale
    if causal:
        logits = logits + causal_mask(q.shape[1], k.shape[1],
                                      dtype=softmax_dtype)
    if mask is not None:
        logits = logits + mask.astype(softmax_dtype)
    weights = jax.nn.softmax(logits.astype(softmax_dtype), axis=-1)
    if (causal and q.shape[1] > k.shape[1]) or mask is not None:
        # Fully-masked rows (end-aligned causal with q_len > kv_len, or a
        # user mask): softmax of all -inf is uniform garbage; emit exactly
        # 0 instead — the same convention as the flash kernels, so impls
        # are swappable. Statically impossible when q_len <= kv_len and no
        # mask is given, so the hot path skips the reduction at trace time.
        all_masked = jnp.all(logits <= jnp.finfo(softmax_dtype).min * 0.5,
                             axis=-1, keepdims=True)
        weights = jnp.where(all_masked, 0.0, weights)
    weights = weights.astype(q.dtype)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v,
                      preferred_element_type=q.dtype)
