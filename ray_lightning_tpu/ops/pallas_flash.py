"""Hand-tiled pallas flash-attention kernels for TPU — forward AND backward.

Forward: grid ``(B, H, n_q, n_k)`` with the KV dimension innermost: for each
query block the kernel streams KV blocks through VMEM, maintaining the
online softmax state (running max ``m``, denominator ``l``, f32
accumulator) in scratch across grid steps, and writes the normalized output
plus the logsumexp on the last KV block. Matmuls hit the MXU at the input
dtype with f32 accumulation (``preferred_element_type``), per the TPU
kernel guide.

Backward (FlashAttention-2 scheme, the recompute form): probabilities are
rebuilt blockwise from the saved logsumexp instead of storing the (T, S)
matrix, so training memory stays O(T·D):

- ``delta = rowsum(dO ⊙ O)`` — cheap elementwise jnp precompute;
- dk/dv kernel, grid ``(B, H, n_k, n_q)`` (q innermost): for KV block j,
  accumulate ``dv += pᵀ dO`` and ``dk += dsᵀ q`` over the q blocks, where
  ``p = exp(q kᵀ·scale − lse)`` and ``ds = p ⊙ (dO vᵀ − delta)``;
- dq kernel, grid ``(B, H, n_q, n_k)`` (kv innermost): ``dq += ds k``.

The public entry is wrapped in ``jax.custom_vjp`` so ``attention_impl=
"flash"`` trains on TPU (round-2 find: differentiating through a bare
``pallas_call`` has no JVP rule and crashes every training step). Causal
runs skip fully-masked blocks in all three kernels (~2x on the causal path).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BIG_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)
# lse value for padded query rows: exp(s - big) == 0 for any finite s, so
# padding contributes exactly nothing to dk/dv.
_PAD_LSE = 1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                  acc_ref, *, scale: float, causal: bool, kv_len: int,
                  q_len: int, block_q: int, block_k: int):
    i = pl.program_id(2)   # q block
    j = pl.program_id(3)   # kv block (innermost, sequential)
    n_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _BIG_NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        qi = q_ref[0, 0]  # (bq, D)
        kj = k_ref[0, 0]  # (bk, D)
        vj = v_ref[0, 0]

        s = jax.lax.dot_general(
            qi, kj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)

        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        allow = kpos < kv_len
        if causal:
            # align ends when q_len != kv_len (standard decode convention)
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + (kv_len - q_len)
            allow = allow & (kpos <= qpos)
        s = jnp.where(allow, s, _BIG_NEG)

        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(allow, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, 0] * alpha + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(vj.dtype), vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, 0] = m_new
        l_ref[:, 0] = l_new

    if causal:
        # predicate away KV blocks entirely above the diagonal (~2x FLOPs
        # saved on the causal hot path; init/emit still run every step)
        first_key = j * block_k
        last_q = i * block_q + block_q - 1 + (kv_len - q_len)
        pl.when(first_key <= last_q)(_compute)
    else:
        _compute()

    @pl.when(j == n_k - 1)
    def _emit():
        l = l_ref[:, 0]
        safe_l = jnp.maximum(l, 1e-30)
        out = jnp.where(l[:, None] > 0, acc_ref[:] / safe_l[:, None], 0.0)
        o_ref[0, 0] = out.astype(o_ref.dtype)
        lse_ref[0, 0, :, 0] = jnp.where(
            l > 0, m_ref[:, 0] + jnp.log(safe_l), _PAD_LSE)


def _recomputed_p_ds(qi, kj, vj, doi, lse, delta, *, scale, causal, i, j,
                     kv_len, q_len, block_q, block_k):
    """Shared backward block math: rebuild p from lse, form ds.

    Returns (p, ds) as f32 ``(bq, bk)``; masked positions are exactly 0 in
    both, so padded/causal-forbidden entries contribute nothing to any of
    dq/dk/dv.
    """
    s = jax.lax.dot_general(
        qi, kj, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # (bq, bk)
    kpos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    allow = kpos < kv_len
    if causal:
        qpos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0) + (kv_len - q_len)
        allow = allow & (kpos <= qpos)
    p = jnp.where(allow, jnp.exp(s - lse[:, None]), 0.0)
    dp = jax.lax.dot_general(
        doi, vj, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (bq, bk)
    ds = p * (dp - delta[:, None])
    return p, ds


def _flash_dkv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                      causal: bool, kv_len: int, q_len: int, block_q: int,
                      block_k: int):
    j = pl.program_id(2)   # kv block
    i = pl.program_id(3)   # q block (innermost, sequential)
    n_i = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        qi = q_ref[0, 0]
        doi = do_ref[0, 0]
        kj = k_ref[0, 0]
        vj = v_ref[0, 0]
        p, ds = _recomputed_p_ds(
            qi, kj, vj, doi, lse_ref[0, 0, :, 0], delta_ref[0, 0, :, 0],
            scale=scale,
            causal=causal, i=i, j=j, kv_len=kv_len, q_len=q_len,
            block_q=block_q, block_k=block_k)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(doi.dtype), doi, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (bk, D)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(qi.dtype), qi, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        first_key = j * block_k
        last_q = i * block_q + block_q - 1 + (kv_len - q_len)
        pl.when(first_key <= last_q)(_compute)
    else:
        _compute()

    @pl.when(i == n_i - 1)
    def _emit():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_dq_kernel(k_ref, v_ref, do_ref, lse_ref, delta_ref, q_ref,
                     dq_ref, dq_acc, *, scale: float, causal: bool,
                     kv_len: int, q_len: int, block_q: int, block_k: int):
    i = pl.program_id(2)   # q block
    j = pl.program_id(3)   # kv block (innermost, sequential)
    n_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        qi = q_ref[0, 0]
        doi = do_ref[0, 0]
        kj = k_ref[0, 0]
        vj = v_ref[0, 0]
        _, ds = _recomputed_p_ds(
            qi, kj, vj, doi, lse_ref[0, 0, :, 0], delta_ref[0, 0, :, 0],
            scale=scale,
            causal=causal, i=i, j=j, kv_len=kv_len, q_len=q_len,
            block_q=block_q, block_k=block_k)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(kj.dtype), kj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        first_key = j * block_k
        last_q = i * block_q + block_q - 1 + (kv_len - q_len)
        pl.when(first_key <= last_q)(_compute)
    else:
        _compute()

    @pl.when(j == n_k - 1)
    def _emit():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _pad_bhtd(x, Tp):
    """(B, T, H, D) → padded (B, H, Tp, D)."""
    T = x.shape[1]
    return jnp.pad(x.transpose(0, 2, 1, 3),
                   ((0, 0), (0, 0), (0, Tp - T), (0, 0)))


def _blocks(block_q, block_k, T, S):
    bq, bk = min(block_q, T), min(block_k, S)
    n_q, n_k = -(-T // bq), -(-S // bk)
    return bq, bk, n_q, n_k


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    B, T, H, D = q.shape
    S = k.shape[1]
    bq, bk, n_q, n_k = _blocks(block_q, block_k, T, S)
    Tp, Sp = n_q * bq, n_k * bk

    # (B,T,H,D) → (B,H,T,D): heads become a parallel grid dim, sequence
    # tiles land on the (sublane, lane) layout the MXU wants.
    qt, kt, vt = _pad_bhtd(q, Tp), _pad_bhtd(k, Sp), _pad_bhtd(v, Sp)

    kernel = functools.partial(
        _flash_kernel, scale=D ** -0.5, causal=causal, kv_len=S, q_len=T,
        block_q=bq, block_k=bk)

    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tp, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),   # running max
            pltpu.VMEM((bq, 1), jnp.float32),   # running denom
            pltpu.VMEM((bq, D), jnp.float32),   # f32 accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out[:, :, :T].transpose(0, 2, 1, 3), lse


def _flash_bwd_impl(q, k, v, out, lse, do, causal, block_q, block_k,
                    interpret):
    B, T, H, D = q.shape
    S = k.shape[1]
    bq, bk, n_q, n_k = _blocks(block_q, block_k, T, S)
    Tp, Sp = n_q * bq, n_k * bk
    scale = D ** -0.5

    qt, dot_ = _pad_bhtd(q, Tp), _pad_bhtd(do, Tp)
    kt, vt = _pad_bhtd(k, Sp), _pad_bhtd(v, Sp)
    # lse is (B,H,Tp) already; padded rows carry _PAD_LSE so p == 0 there.
    delta = jnp.pad(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1).transpose(0, 2, 1),
        ((0, 0), (0, 0), (0, Tp - T)))[..., None]   # (B, H, Tp, 1)

    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0))
    row_spec = pl.BlockSpec((1, 1, bq, 1),
                            lambda b, h, j, i: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0))

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_dkv_kernel, scale=scale, causal=causal, kv_len=S,
            q_len=T, block_q=bq, block_k=bk),
        grid=(B, H, n_k, n_q),
        in_specs=[q_spec, q_spec, row_spec, row_spec, kv_spec, kv_spec],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sp, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, Sp, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(qt, dot_, lse, delta, kt, vt)

    q_spec2 = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0))
    row_spec2 = pl.BlockSpec((1, 1, bq, 1),
                             lambda b, h, i, j: (b, h, i, 0))
    kv_spec2 = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0))

    dq = pl.pallas_call(
        functools.partial(
            _flash_dq_kernel, scale=scale, causal=causal, kv_len=S,
            q_len=T, block_q=bq, block_k=bk),
        grid=(B, H, n_q, n_k),
        in_specs=[kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2,
                  q_spec2],
        out_specs=q_spec2,
        out_shape=jax.ShapeDtypeStruct((B, H, Tp, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(kt, vt, dot_, lse, delta, qt)

    dq = dq[:, :, :T].transpose(0, 2, 1, 3)
    dk = dk[:, :, :S].transpose(0, 2, 1, 3)
    dv = dv[:, :, :S].transpose(0, 2, 1, 3)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, residuals, do):
    q, k, v, out, lse = residuals
    return _flash_bwd_impl(q, k, v, out, lse, do, causal, block_q, block_k,
                           interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def pallas_flash_attention(q: jax.Array,
                           k: jax.Array,
                           v: jax.Array,
                           *,
                           causal: bool = False,
                           block_q: int = 512,
                           block_k: int = 1024,
                           interpret: bool = False) -> jax.Array:
    """Flash attention via pallas, differentiable. Shapes (B, T, H, D).

    Default tiles are from a v5e train-step (fwd+bwd) sweep: 512×1024
    beats both the 128×128 tiles this kernel started with (~2x) and XLA's
    fused attention — 1.8x at T=512 and ~20x at T=8192, where XLA's
    materialized scores stop scaling. Blocks clamp to the actual lengths,
    so short sequences are unaffected. ``interpret=True`` runs the same
    kernels in the pallas interpreter (CPU testing path, no TPU).
    """
    return _flash(q, k, v, causal, block_q, block_k, interpret)
