"""Hand-tiled pallas flash-attention kernel for TPU.

Grid ``(B, H, n_q, n_k)`` with the KV dimension innermost: for each query
block the kernel streams KV blocks through VMEM, maintaining the online
softmax state (running max ``m``, denominator ``l``, f32 accumulator) in
scratch across grid steps, and writes the normalized output on the last KV
block. Matmuls hit the MXU at the input dtype with f32 accumulation
(``preferred_element_type``), per the TPU kernel guide.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BIG_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, causal: bool, kv_len: int, q_len: int,
                  block_q: int, block_k: int):
    i = pl.program_id(2)   # q block
    j = pl.program_id(3)   # kv block (innermost, sequential)
    n_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _BIG_NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        qi = q_ref[0, 0]  # (bq, D)
        kj = k_ref[0, 0]  # (bk, D)
        vj = v_ref[0, 0]

        s = jax.lax.dot_general(
            qi, kj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)

        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        allow = kpos < kv_len
        if causal:
            # align ends when q_len != kv_len (standard decode convention)
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + (kv_len - q_len)
            allow = allow & (kpos <= qpos)
        s = jnp.where(allow, s, _BIG_NEG)

        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(allow, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, 0] * alpha + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(vj.dtype), vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, 0] = m_new
        l_ref[:, 0] = l_new

    if causal:
        # predicate away KV blocks entirely above the diagonal (~2x FLOPs
        # saved on the causal hot path; init/emit still run every step)
        first_key = j * block_k
        last_q = i * block_q + block_q - 1 + (kv_len - q_len)
        pl.when(first_key <= last_q)(_compute)
    else:
        _compute()

    @pl.when(j == n_k - 1)
    def _emit():
        l = l_ref[:, 0]
        safe_l = jnp.maximum(l, 1e-30)
        out = jnp.where(l[:, None] > 0, acc_ref[:] / safe_l[:, None], 0.0)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def pallas_flash_attention(q: jax.Array,
                           k: jax.Array,
                           v: jax.Array,
                           *,
                           causal: bool = False,
                           block_q: int = 128,
                           block_k: int = 128,
                           interpret: bool = False) -> jax.Array:
    """Flash attention via pallas. Shapes (B, T, H, D), any T/S.

    ``interpret=True`` runs the kernel in the pallas interpreter (CPU
    testing path — same kernel code, no TPU required).
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    bq, bk = min(block_q, T), min(block_k, S)
    n_q, n_k = -(-T // bq), -(-S // bk)
    Tp, Sp = n_q * bq, n_k * bk

    # (B,T,H,D) → (B,H,T,D): heads become a parallel grid dim, sequence
    # tiles land on the (sublane, lane) layout the MXU wants.
    qt = jnp.pad(q.transpose(0, 2, 1, 3),
                 ((0, 0), (0, 0), (0, Tp - T), (0, 0)))
    kt = jnp.pad(k.transpose(0, 2, 1, 3),
                 ((0, 0), (0, 0), (0, Sp - S), (0, 0)))
    vt = jnp.pad(v.transpose(0, 2, 1, 3),
                 ((0, 0), (0, 0), (0, Sp - S), (0, 0)))

    kernel = functools.partial(
        _flash_kernel, scale=D ** -0.5, causal=causal, kv_len=S, q_len=T,
        block_q=bq, block_k=bk)

    out = pl.pallas_call(
        kernel,
        grid=(B, H, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Tp, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),   # running max
            pltpu.VMEM((bq, 1), jnp.float32),   # running denom
            pltpu.VMEM((bq, D), jnp.float32),   # f32 accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out[:, :, :T].transpose(0, 2, 1, 3)
