"""Chunked LM-head cross-entropy: the memory lever for large-vocab LMs.

The naive path materializes the full ``(B*T, V)`` logits tensor — at GPT-2
scale (vocab 50k) that is gigabytes per step and becomes the batch-size
wall long before the transformer blocks do (measured on a v5e chip: the
flagship bench OOMs at batch 32 x seq 512 with materialized logits, while
the blocks alone fit comfortably at batch 64).

This op scans over token chunks: each chunk computes its logits slice on
the MXU (bf16 inputs, f32 accumulation), reduces it to a per-token loss,
and drops it. ``jax.checkpoint`` on the chunk body makes the backward pass
recompute each logits slice instead of saving it, so peak memory is
``O(chunk_size * V)`` instead of ``O(B*T*V)`` at the cost of one extra
LM-head matmul — a trade that wins whenever the saved HBM lets the batch
(and with it MXU utilization) grow.

No counterpart in the reference (it delegates the loss to user torch code);
this is TPU-native scope the framework owns.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def lm_head_xent(hidden: jax.Array,
                 embedding: jax.Array,
                 labels: jax.Array,
                 *,
                 compute_dtype=jnp.bfloat16) -> jax.Array:
    """Direct (unchunked) LM-head cross-entropy with bf16 logits.

    The obvious formulation — ``logits.astype(f32)`` then
    ``optax.softmax_cross_entropy...`` — makes XLA materialize the full
    f32 logits tensor *in addition to* the bf16 matmul output (measured in
    the v5e HLO: an 824 MB f32 + 412 MB bf16 pair of fusion outputs at
    batch 8 x seq 512 x vocab 50304, ~2 ms of pure HBM traffic). Here the
    logits stay bf16 — the only (N, V)-sized materialization — while the
    reductions (logsumexp, label gather) convert elementwise inside their
    fusions with f32 accumulators, so precision of the loss is preserved
    without the f32 tensor ever existing.

    Same contract as :func:`chunked_lm_head_xent` (which additionally
    bounds memory to O(chunk x V) for big-batch / big-vocab regimes; this
    direct variant is faster when the bf16 logits comfortably fit).
    """
    if hidden.ndim == 3:
        hidden = hidden.reshape(-1, hidden.shape[-1])
        labels = labels.reshape(-1)
    logits = jax.lax.dot_general(
        hidden.astype(compute_dtype), embedding.astype(compute_dtype),
        dimension_numbers=(((1,), (1,)), ((), ())))  # (N, V) bf16
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    label_logit = jnp.take_along_axis(
        logits, labels[:, None], axis=-1)[:, 0].astype(jnp.float32)
    return (lse - label_logit).mean()


def chunked_lm_head_xent(hidden: jax.Array,
                         embedding: jax.Array,
                         labels: jax.Array,
                         *,
                         chunk_size: int = 2048,
                         compute_dtype=jnp.bfloat16,
                         z_loss: float = 0.0) -> jax.Array:
    """Mean next-token cross-entropy without materializing full logits.

    Args:
      hidden: ``(B, T, D)`` (or ``(N, D)``) final hidden states (after the
        LM's last layernorm).
      embedding: ``(V, D)`` tied embedding table / LM-head weight. For an
        untied ``(D, V)`` kernel pass ``kernel.T``.
      labels: ``(B, T)`` (or ``(N,)``) int targets in ``[0, V)``.
      chunk_size: tokens per scanned chunk; peak extra memory is
        ``chunk_size * V * 4`` bytes (f32 logits slice).
      compute_dtype: matmul input dtype (MXU wants bf16); the logits
        accumulate and reduce in f32 regardless.
      z_loss: optional coefficient for the auxiliary ``log(Z)^2`` term
        (PaLM-style softmax normalizer regularizer); 0 disables.

    Returns:
      Scalar f32 mean loss over all tokens.
    """
    if hidden.ndim == 3:
        hidden = hidden.reshape(-1, hidden.shape[-1])
        labels = labels.reshape(-1)
    n_tokens, d = hidden.shape
    chunk = max(1, min(chunk_size, n_tokens))
    pad = (-n_tokens) % chunk
    if pad:
        hidden = jnp.pad(hidden, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, pad),))
    valid = (jnp.arange(n_tokens + pad) < n_tokens)
    xs = hidden.reshape(-1, chunk, d)
    ys = labels.reshape(-1, chunk)
    ms = valid.reshape(-1, chunk)

    @jax.checkpoint
    def chunk_loss(emb, x_c, y_c, m_c):
        # (C, V) f32 via bf16 MXU matmul with f32 accumulation
        logits = jax.lax.dot_general(
            x_c.astype(compute_dtype), emb.astype(compute_dtype),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        label_logit = jnp.take_along_axis(
            logits, y_c[:, None], axis=-1)[:, 0]
        loss = (lse - label_logit) * m_c
        if z_loss:
            loss = loss + z_loss * jnp.square(lse) * m_c
        return jnp.sum(loss)

    def body(total, inp):
        x_c, y_c, m_c = inp
        return total + chunk_loss(embedding, x_c, y_c, m_c), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xs, ys, ms))
    return total / n_tokens
