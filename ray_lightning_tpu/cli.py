"""Command-line interface: build Trainer + strategy + module from args/YAML.

Parity with the reference's LightningCLI compatibility
(``tests/test_lightning_cli.py:11-27``): the CLI must be able to instantiate
a strategy by name from CLI arguments, resolving constructor arguments
across the strategy's own signature *and* passthrough kwargs (the reference
resolves ``RayStrategy`` ctor args against DDP kwargs like
``bucket_cap_mb``; here unknown ``--strategy.*`` keys flow into the
strategy's ``**kwargs`` the same way).

jsonargparse is not a baked-in dependency, so the parser is plain argparse
with signature introspection: every ``--trainer.X``, ``--model.X``,
``--data.X`` and ``--strategy.X`` flag maps onto the matching constructor
parameter; a ``--config file.yaml`` merges a config tree with sections
``trainer`` / ``strategy`` / ``model`` / ``data`` (CLI flags win).
"""
from __future__ import annotations

import argparse
import inspect
from typing import Any, Dict, List, Optional, Type

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.strategies import (AllReduceStrategy, FSDPStrategy,
                                          HorovodRayStrategy, MeshStrategy,
                                          RayShardedStrategy, RayStrategy,
                                          SequenceParallelStrategy, Strategy)

#: name → class; keys are the strategies' ``strategy_name`` plus the
#: TPU-native aliases (parity: PTL's StrategyRegistry entries the reference
#: gets from ``strategy_name = "ddp_ray"`` etc.).
STRATEGY_REGISTRY: Dict[str, Type[Strategy]] = {}


def register_strategy(cls: Type[Strategy], *aliases: str) -> None:
    STRATEGY_REGISTRY[cls.strategy_name] = cls
    for a in aliases:
        STRATEGY_REGISTRY[a] = cls


register_strategy(RayStrategy, "ddp", "dp")
register_strategy(HorovodRayStrategy, "horovod", "allreduce")
if AllReduceStrategy is not HorovodRayStrategy:
    register_strategy(AllReduceStrategy)
register_strategy(RayShardedStrategy, "ddp_sharded", "zero1")
register_strategy(FSDPStrategy, "fsdp")
register_strategy(MeshStrategy, "mesh")
register_strategy(SequenceParallelStrategy, "sp", "sequence_parallel")


_TRUE = ("true", "1", "yes", "y", "on")
_FALSE = ("false", "0", "no", "n", "off")


def _parse_value(raw: str, default: Any) -> Any:
    """Coerce a CLI string to the parameter's type (inferred from default)."""
    if raw.lower() in ("null", "none"):
        return None
    if isinstance(default, bool):
        low = raw.lower()
        if low in _TRUE:
            return True
        if low in _FALSE:
            return False
        raise SystemExit(
            f"Expected a boolean (true/false), got {raw!r}")
    if isinstance(default, int):
        try:
            return int(raw)
        except ValueError:
            pass
    if isinstance(default, float):
        try:
            return float(raw)
        except ValueError:
            pass
    if isinstance(default, str):
        return raw
    if default is None:
        # untyped param: best effort — bool words, int, float, then string
        if raw.lower() in ("true", "false"):
            return raw.lower() == "true"
        for cast in (int, float):
            try:
                return cast(raw)
            except ValueError:
                continue
    return raw


def _signature_defaults(cls: type) -> Dict[str, Any]:
    out = {}
    for name, p in inspect.signature(cls.__init__).parameters.items():
        if name in ("self", "args", "kwargs"):
            continue
        out[name] = None if p.default is inspect.Parameter.empty \
            else p.default
    return out


class TpuLightningCLI:
    """Instantiate (strategy, trainer, model, datamodule) from CLI args.

    Usage::

        cli = TpuLightningCLI(MyModule, MyDataModule)
        # python train.py fit --trainer.max_epochs 3 \
        #     --strategy ddp_ray --strategy.num_workers 4 --model.lr 1e-3

    ``run=False`` only constructs the objects (the mode the parity test
    exercises, ``tests/test_lightning_cli.py:11-27``).
    """

    subcommands = ("fit", "validate", "test", "predict")

    def __init__(self,
                 model_class: type,
                 datamodule_class: Optional[type] = None,
                 args: Optional[List[str]] = None,
                 run: bool = True,
                 trainer_defaults: Optional[Dict[str, Any]] = None):
        self.model_class = model_class
        self.datamodule_class = datamodule_class
        ns, overrides = self._parse(args)
        config = self._load_config(ns.config)

        trainer_cfg = dict(trainer_defaults or {})
        trainer_cfg.update(config.get("trainer", {}))
        strategy_cfg = dict(config.get("strategy", {}))
        model_cfg = dict(config.get("model", {}))
        data_cfg = dict(config.get("data", {}))

        strategy_name = ns.strategy or strategy_cfg.pop("name", "ddp_ray")
        for section, key, raw in overrides:
            target = {
                "trainer": trainer_cfg,
                "strategy": strategy_cfg,
                "model": model_cfg,
                "data": data_cfg
            }[section]
            defaults = {
                "trainer": _signature_defaults(Trainer),
                "strategy": _signature_defaults(
                    STRATEGY_REGISTRY[strategy_name]),
                "model": _signature_defaults(model_class),
                "data": _signature_defaults(datamodule_class)
                if datamodule_class else {},
            }[section]
            target[key] = _parse_value(raw, defaults.get(key))

        self.strategy = self._instantiate_strategy(strategy_name,
                                                   strategy_cfg)
        self.trainer = Trainer(strategy=self.strategy, **trainer_cfg)
        self.model = model_class(**model_cfg)
        self.datamodule = (datamodule_class(**data_cfg)
                           if datamodule_class else None)
        self.subcommand = ns.subcommand

        if run:
            fn = getattr(self.trainer, self.subcommand)
            fn(self.model, datamodule=self.datamodule)

    # ------------------------------------------------------------------ #
    def _parse(self, args: Optional[List[str]]):
        import sys
        args = list(sys.argv[1:] if args is None else args)
        # Consume the subcommand by hand: an optional positional would
        # swallow the *value* of an unknown --section.param flag.
        subcommand = "fit"
        if args and args[0] in self.subcommands:
            subcommand = args.pop(0)
        parser = argparse.ArgumentParser(add_help=True)
        parser.add_argument("--config", default=None,
                            help="YAML config with trainer/strategy/"
                                 "model/data sections")
        parser.add_argument("--strategy", default=None,
                            help=f"one of {sorted(STRATEGY_REGISTRY)}")
        ns, rest = parser.parse_known_args(args)
        ns.subcommand = subcommand

        overrides = []
        i = 0
        while i < len(rest):
            tok = rest[i]
            if not tok.startswith("--") or "." not in tok:
                raise SystemExit(f"Unrecognized argument: {tok}")
            key = tok[2:]
            if "=" in key:
                key, raw = key.split("=", 1)
                i += 1
            else:
                if i + 1 >= len(rest):
                    raise SystemExit(f"Missing value for {tok}")
                raw = rest[i + 1]
                i += 2
            section, _, param = key.partition(".")
            if section not in ("trainer", "strategy", "model", "data"):
                raise SystemExit(
                    f"Unknown section {section!r} in {tok} (use trainer./"
                    "strategy./model./data.)")
            overrides.append((section, param, raw))
        return ns, overrides

    @staticmethod
    def _load_config(path: Optional[str]) -> Dict[str, Any]:
        if not path:
            return {}
        import yaml
        with open(path) as f:
            return yaml.safe_load(f) or {}

    @staticmethod
    def _instantiate_strategy(name: str, cfg: Dict[str, Any]) -> Strategy:
        if name not in STRATEGY_REGISTRY:
            raise SystemExit(
                f"Unknown strategy {name!r}; choose from "
                f"{sorted(STRATEGY_REGISTRY)}")
        cls = STRATEGY_REGISTRY[name]
        sig_params = set(_signature_defaults(cls))
        known = {k: v for k, v in cfg.items() if k in sig_params}
        passthrough = {k: v for k, v in cfg.items() if k not in sig_params}
        # Passthrough kwargs ride the strategy's **kwargs, the analog of
        # the reference resolving DDP kwargs like bucket_cap_mb
        # (tests/test_lightning_cli.py:15).
        return cls(**known, **passthrough)


def main(argv: Optional[List[str]] = None) -> None:
    """``python -m ray_lightning_tpu.cli --model-class pkg.Mod …``"""
    import importlib
    parser = argparse.ArgumentParser()
    parser.add_argument("--model-class", required=True,
                        help="dotted path to the TpuModule subclass")
    parser.add_argument("--datamodule-class", default=None)
    ns, rest = parser.parse_known_args(argv)

    def _resolve(path):
        mod, _, attr = path.rpartition(".")
        return getattr(importlib.import_module(mod), attr)

    TpuLightningCLI(_resolve(ns.model_class),
                    _resolve(ns.datamodule_class)
                    if ns.datamodule_class else None,
                    args=rest, run=True)


if __name__ == "__main__":
    main()
