"""Accelerator abstraction + the delayed-TPU shim.

Parity with ``ray_lightning/accelerators/delayed_gpu_accelerator.py:22-50``
and the registry wiring in ``accelerators/__init__.py:13-21``: the
reference's ``_GPUAccelerator`` exists so a **driver with no GPU** (laptop /
CPU head node / Ray-client session) can construct a GPU trainer — device
availability is asserted *inside the worker*, not at construction. The TPU
analog: :class:`DelayedTPUAccelerator.is_available` is hardcoded ``True``
and device setup defers to the worker, where
:meth:`~ray_lightning_tpu.strategies.base.Strategy.worker_setup` initializes
the runtime; it raises only when training actually starts on a host with no
TPU (parity: ``util.py:35-38``).

Strategies select by name the same way the reference does
(``accelerator="_gpu" if use_gpu else "cpu"``, ``ray_ddp.py:122-123``):
here ``"_tpu"`` when ``use_tpu`` else ``"cpu"``.
"""
from __future__ import annotations

from typing import Dict, List, Type


class Accelerator:
    name = "base"

    @staticmethod
    def is_available() -> bool:
        raise NotImplementedError

    @staticmethod
    def parse_devices(devices):
        return devices

    @staticmethod
    def get_devices() -> List:
        import jax
        return jax.local_devices()

    def setup_environment(self, root_device=None) -> None:
        """Driver-side setup. Default: assert availability."""
        if not self.is_available():
            raise RuntimeError(
                f"{type(self).__name__}: no {self.name} device available")

    def on_train_start(self) -> None:
        """Worker-side gate, called once training begins."""


class CPUAccelerator(Accelerator):
    name = "cpu"

    @staticmethod
    def is_available() -> bool:
        return True


class TPUAccelerator(Accelerator):
    """Strict TPU accelerator: requires chips visible *now*."""
    name = "tpu"

    @staticmethod
    def is_available() -> bool:
        import jax
        try:
            return any(d.platform == "tpu" for d in jax.devices())
        except RuntimeError:
            return False


class DelayedTPUAccelerator(TPUAccelerator):
    """TPU accelerator whose availability check is deferred to the worker.

    ``is_available() -> True`` unconditionally (parity:
    ``delayed_gpu_accelerator.py:47-50``) so a TPU-less driver — laptop,
    CPU-only head node, Ray-client session — can build the trainer; worker-
    side :meth:`on_train_start` raises if the actor landed somewhere with no
    TPU after all (parity: ``util.py:35-38``).
    """
    name = "_tpu"

    @staticmethod
    def is_available() -> bool:
        return True

    def setup_environment(self, root_device=None) -> None:
        # Deliberately no device touch on the driver
        # (parity: delayed_gpu_accelerator.py:30-36).
        return None

    def on_train_start(self) -> None:
        if not TPUAccelerator.is_available():
            raise RuntimeError(
                "DelayedTPUAccelerator: training started but no TPU device "
                "is visible in this worker process.")


ACCELERATOR_REGISTRY: Dict[str, Type[Accelerator]] = {}


def register_accelerator(cls: Type[Accelerator]) -> None:
    """Parity: PTL AcceleratorRegistry registration at import time
    (``accelerators/__init__.py:13-21``)."""
    ACCELERATOR_REGISTRY[cls.name] = cls


register_accelerator(CPUAccelerator)
register_accelerator(TPUAccelerator)
register_accelerator(DelayedTPUAccelerator)


def resolve_accelerator(name: str) -> Accelerator:
    if name not in ACCELERATOR_REGISTRY:
        raise KeyError(
            f"Unknown accelerator {name!r}; registered: "
            f"{sorted(ACCELERATOR_REGISTRY)}")
    return ACCELERATOR_REGISTRY[name]()
