"""Shared utilities: optional-dependency sentinels, state byte-streams, result plumbing.

TPU-native re-design of the reference's worker utilities
(``ray_lightning/util.py:42-102``): the ``Unavailable`` sentinel pattern is kept,
``to_state_stream``/``load_state_stream`` become msgpack byte-streams of numpy
pytrees (instead of ``torch.save`` of CUDA state dicts), and ``process_results``
polls executor futures while draining the driver-side callable queue.
"""
from __future__ import annotations

import io
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np
from flax import serialization


class Unavailable:
    """Sentinel for unavailable optional dependencies.

    Mirrors ``ray_lightning/util.py:42-46``: any attribute access or
    instantiation raises, so import-time references stay cheap while use
    fails loudly.
    """

    def __init__(self, *args, **kwargs):
        raise RuntimeError(
            "This class is not usable because an optional dependency "
            "(e.g. `ray`) is not installed.")

    def __getattr__(self, name):
        raise RuntimeError(
            "This object is a placeholder for an unavailable optional "
            "dependency.")


def _to_numpy_pytree(tree: Any) -> Any:
    """Convert every array leaf to host numpy (device → host, zero surprises)."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "__array__") else x, tree)


def to_state_stream(state: Any) -> bytes:
    """Serialize a pytree of arrays to an in-memory byte stream.

    TPU analog of ``ray_lightning/util.py:73-77``: the reference streams a
    ``torch`` state dict through ``io.BytesIO`` so weights survive a
    multi-node return (no shared filesystem needed). Here the state is a JAX
    pytree; device arrays are pulled to host and msgpack-encoded.
    """
    return serialization.msgpack_serialize(_to_numpy_pytree(state))


def load_state_stream(stream: bytes, target: Optional[Any] = None) -> Any:
    """Inverse of :func:`to_state_stream`.

    TPU analog of ``ray_lightning/util.py:80-92``. ``map_location`` has no
    TPU equivalent: arrays are restored as host numpy and re-placed onto
    devices by whichever sharding the caller applies next (device placement
    is a sharding decision under XLA, not a serialization one).

    Args:
        stream: bytes produced by :func:`to_state_stream`.
        target: optional pytree template; when given, the restored state
            keeps the template's treedef (msgpack alone cannot restore
            custom pytree node types).
    """
    restored = serialization.msgpack_restore(stream)
    if target is not None:
        return serialization.from_state_dict(target, restored)
    return restored


def tensor_metrics_to_numpy(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """Convert metric values (device scalars) to plain numpy for IPC.

    Parity with ``ray_lightning/launchers/ray_launcher.py:339-347``, where
    callback/logged metrics are converted tensor→numpy before crossing the
    worker→driver boundary.
    """
    out = {}
    for k, v in metrics.items():
        if hasattr(v, "__array__"):
            arr = np.asarray(v)
            out[k] = arr.item() if arr.ndim == 0 else arr
        else:
            out[k] = v
    return out


def numpy_metrics_to_device(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """Driver-side inverse of :func:`tensor_metrics_to_numpy`.

    Parity with ``ray_lightning/launchers/ray_launcher.py:375-380`` (numpy →
    tensor restore). Scalars stay Python floats — in JAX there is no benefit
    to re-wrapping them in device arrays on the driver.
    """
    return dict(metrics)


def process_results(futures: List[Any],
                    queue: Optional[Any] = None,
                    poll_interval_s: float = 0.05,
                    sleep: Callable[[float], None] = time.sleep
                    ) -> List[Any]:
    """Drive the driver-side event loop until every worker future resolves.

    Parity with ``ray_lightning/util.py:57-70``: busy-poll the outstanding
    futures while draining the session queue, executing any queued callables
    *in the driver process* (the mechanism Tune-style reporting rides on,
    ``ray_lightning/util.py:49-54``).

    ``futures`` are executor-agnostic: anything with ``.done()``/``.result()``
    (concurrent.futures) or resolved via the installed executor backend.
    ``sleep`` is injectable (the package sleep-lint contract) so tests can
    drive the poll loop without wall time.
    """
    pending = list(futures)
    while pending:
        _drain_queue(queue)
        not_done = []
        for f in pending:
            if _future_done(f):
                continue
            not_done.append(f)
        if not not_done:
            break
        pending = not_done
        sleep(poll_interval_s)
    _drain_queue(queue)
    return [_future_result(f) for f in futures]


def _future_done(f: Any) -> bool:
    if hasattr(f, "done"):
        return f.done()
    return True  # plain values are already "done"


def _future_result(f: Any) -> Any:
    if hasattr(f, "result"):
        return f.result()
    return f


def _drain_queue(queue: Optional[Any]) -> None:
    """Execute every callable currently sitting in the session queue.

    Parity with ``_handle_queue`` (``ray_lightning/util.py:49-54``): items
    are ``(actor_rank, item)``; callables run driver-side, everything else is
    ignored.
    """
    if queue is None:
        return
    while not queue.empty():
        (_rank, item) = queue.get()
        if isinstance(item, Callable):
            item()
