"""ray_lightning_tpu — a TPU-native distributed training framework.

Brand-new implementation of the capabilities of
`ray_lightning <https://github.com/ray-project/ray_lightning>`_ (reference
mounted at /root/reference), re-designed for TPU: strategies express
parallelism as ``jax.sharding.Mesh`` axes, XLA compiles the collectives over
ICI/DCN, and launchers host SPMD processes (one per TPU host) instead of
one-per-GPU CUDA workers.

Public API parity (``ray_lightning/__init__.py:1-5``): ``RayStrategy``,
``HorovodRayStrategy``, ``RayShardedStrategy`` — plus the TPU-native names
and the Trainer/module stack the reference borrows from PyTorch Lightning.
"""

import os as _os

import jax as _jax

# Sharding-invariant PRNG (the default on newer jax): without it, a jitted
# init whose out_shardings shard a leaf (e.g. pipeline_parallel_rule's
# pp-sharded block stacks) generates DIFFERENT random values than the same
# init replicated, so "same seed, any layout" equivalence silently breaks
# (caught by tests/test_pipeline.py::test_pipelined_lm_trains_on_dp_x_pp).
# This is process-global: on older jax it also changes the stream of the
# application's OWN jax.random draws (to the values newer jax produces by
# default). TL_THREEFRY_PARTITIONABLE=0 opts out, accepting
# layout-dependent init instead. No-op where the flag no longer exists
# (partitionable is then the only implementation).
if (_os.environ.get("TL_THREEFRY_PARTITIONABLE", "1") != "0"
        and hasattr(_jax.config, "jax_threefry_partitionable")):
    _jax.config.update("jax_threefry_partitionable", True)

from ray_lightning_tpu.strategies import (RayStrategy, DataParallelStrategy,
                                          RayShardedStrategy, ZeroOneStrategy,
                                          HorovodRayStrategy,
                                          AllReduceStrategy, FSDPStrategy,
                                          MeshStrategy,
                                          SequenceParallelStrategy)
from ray_lightning_tpu.core import (Trainer, TpuModule, TpuDataModule,
                                    Callback, EarlyStopping,
                                    EMAWeightAveraging, ModelCheckpoint,
                                    EpochStatsCallback, seed_everything)
from ray_lightning_tpu.launchers import RayLauncher, LocalLauncher
from ray_lightning_tpu.reliability import (FaultPlan, FitSupervisor,
                                           GangConfig, GangFailure,
                                           GangSupervisor, InjectedFault,
                                           NonFiniteError,
                                           RetriesExhausted, RetryPolicy,
                                           ServeSupervisor)
from ray_lightning_tpu.obs import StepStatsCallback, Telemetry

__version__ = "0.2.0"

__all__ = [
    "RayStrategy", "DataParallelStrategy", "RayShardedStrategy",
    "ZeroOneStrategy", "HorovodRayStrategy", "AllReduceStrategy",
    "FSDPStrategy", "MeshStrategy", "SequenceParallelStrategy", "Trainer",
    "TpuModule", "TpuDataModule",
    "Callback", "EarlyStopping", "EMAWeightAveraging", "ModelCheckpoint",
    "EpochStatsCallback", "seed_everything",
    "RayLauncher", "LocalLauncher",
    "FaultPlan", "FitSupervisor", "GangConfig", "GangFailure",
    "GangSupervisor", "InjectedFault", "NonFiniteError",
    "RetriesExhausted", "RetryPolicy", "ServeSupervisor",
    "StepStatsCallback", "Telemetry",
]
