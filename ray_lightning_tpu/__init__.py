"""ray_lightning_tpu — a TPU-native distributed training framework.

Brand-new implementation of the capabilities of
`ray_lightning <https://github.com/ray-project/ray_lightning>`_ (reference
mounted at /root/reference), re-designed for TPU: strategies express
parallelism as ``jax.sharding.Mesh`` axes, XLA compiles the collectives over
ICI/DCN, and launchers host SPMD processes (one per TPU host) instead of
one-per-GPU CUDA workers.

Public API parity (``ray_lightning/__init__.py:1-5``): ``RayStrategy``,
``HorovodRayStrategy``, ``RayShardedStrategy`` — plus the TPU-native names
and the Trainer/module stack the reference borrows from PyTorch Lightning.
"""

from ray_lightning_tpu.strategies import (RayStrategy, DataParallelStrategy,
                                          RayShardedStrategy, ZeroOneStrategy,
                                          HorovodRayStrategy,
                                          AllReduceStrategy, FSDPStrategy,
                                          MeshStrategy,
                                          SequenceParallelStrategy)
from ray_lightning_tpu.core import (Trainer, TpuModule, TpuDataModule,
                                    Callback, EarlyStopping,
                                    EMAWeightAveraging, ModelCheckpoint,
                                    EpochStatsCallback, seed_everything)
from ray_lightning_tpu.launchers import RayLauncher, LocalLauncher

__version__ = "0.2.0"

__all__ = [
    "RayStrategy", "DataParallelStrategy", "RayShardedStrategy",
    "ZeroOneStrategy", "HorovodRayStrategy", "AllReduceStrategy",
    "FSDPStrategy", "MeshStrategy", "SequenceParallelStrategy", "Trainer",
    "TpuModule", "TpuDataModule",
    "Callback", "EarlyStopping", "EMAWeightAveraging", "ModelCheckpoint",
    "EpochStatsCallback", "seed_everything",
    "RayLauncher", "LocalLauncher"
]
