"""Ray Tune integration: HPO over TPU-sharded trainings.

Parity with ``ray_lightning/tune.py:13-241``, re-founded on TPU resources:

- :func:`get_tune_resources` builds the trial ``PlacementGroupFactory`` —
  one CPU bundle for the trial driver plus one bundle per worker
  (``tune.py:32-56``; documented ``README.md:185``) — with the GPU slot
  replaced by the Ray ``TPU`` custom resource TPU-VM nodes advertise.
- :class:`TuneReportCallback` ships ``lambda: tune.report(**metrics)``
  thunks from the rank-0 worker to the trial process through the session
  queue (``tune.py:59-134``): ``tune.report`` must execute *in the trial
  process* while metrics originate in workers — the queue-of-callables is
  the load-bearing mechanism (SURVEY.md §3.4).
- :class:`TuneReportCheckpointCallback` additionally streams a full trainer
  checkpoint (bytes, multi-node safe) and writes it into
  ``tune.checkpoint_dir(step)`` on the driver (``tune.py:136-236``),
  checkpoint-before-report so the report registers the checkpoint.

Optional-dependency handling (parity with the ``Unavailable`` guards,
``tune.py:13-27``, ``:238-241``): importing this module always succeeds;
``TUNE_INSTALLED`` records availability and anything that actually needs
Tune fails loudly at call time. Gating at call time instead of swapping
classes for sentinels keeps the callback logic unit-testable against a fake
``tune`` module (set ``ray_lightning_tpu.tune.tune = fake``), which is how
the suite covers this file without a Ray install.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple, Union

from ray_lightning_tpu import session
from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.util import to_state_stream

try:
    from ray import tune
    TUNE_INSTALLED = True
except ImportError:
    tune = None
    TUNE_INSTALLED = False


def _require_tune():
    """The active tune module (the module attribute, so tests can fake it)."""
    if tune is None:
        raise RuntimeError(
            "`ray.tune` is required for this functionality but is not "
            "installed. Install ray[tune], or remove the Tune callbacks / "
            "placement factory from your setup.")
    return tune


def _is_legacy_tune(tune_mod) -> bool:
    """Ray 1.x exposes ``tune.is_session_enabled``; Ray 2.x removed it
    along with ``tune.report(**kw)`` and ``tune.checkpoint_dir`` — the
    presence of that attribute is the generation marker (ADVICE round 1:
    silently assuming 1.x made the session queue never initialize and the
    callbacks crash mid-trial on modern Ray)."""
    return hasattr(tune_mod, "is_session_enabled")


def is_session_enabled() -> bool:
    """True when running inside a Tune trial process (any Ray generation)."""
    if tune is None:
        return False
    if _is_legacy_tune(tune):
        try:
            return bool(tune.is_session_enabled())
        except Exception as exc:
            from ray_lightning_tpu.reliability import log_suppressed
            log_suppressed("tune.session_probe", exc,
                           "legacy is_session_enabled failed")
            return False
    # Ray >= 2.x: a live train/tune session context marks the trial
    # process. Public API first (round-2 review: the private-module probe
    # is the upgrade-fragile one; keep it as the fallback for ray
    # versions whose get_context() raises outside a session).
    try:
        ctx = tune.get_context()
        if ctx is not None and ctx.get_trial_id() is not None:
            return True
    except Exception as exc:
        from ray_lightning_tpu.reliability import log_suppressed
        log_suppressed("tune.session_probe", exc,
                       "get_context raised outside a session")
    try:
        from ray.train._internal.session import _get_session
        return _get_session() is not None
    except Exception as exc:
        from ray_lightning_tpu.reliability import log_suppressed
        log_suppressed("tune.session_probe", exc,
                       "private _get_session fallback failed")
        return False


def _report(tune_mod, metrics: Dict[str, Any],
            checkpoint_dir: Optional[str] = None) -> None:
    """Version-adaptive report: legacy kwargs API vs 2.x dict(+checkpoint).

    Runs *in the trial process* (shipped through the session queue).
    """
    if _is_legacy_tune(tune_mod):
        tune_mod.report(**metrics)
        return
    train_mod = None
    try:
        from ray import train as _train
        if hasattr(_train, "report"):
            train_mod = _train
    except ImportError:
        pass
    checkpoint = None
    if checkpoint_dir is not None:
        ckpt_cls = getattr(train_mod, "Checkpoint", None) or \
            getattr(tune_mod, "Checkpoint", None)
        if ckpt_cls is None:
            raise RuntimeError(
                "Cannot register the trial checkpoint: the installed ray "
                "exposes neither ray.train.Checkpoint nor "
                "ray.tune.Checkpoint. Upgrade ray[tune] or drop the "
                "checkpoint callback.")
        checkpoint = ckpt_cls.from_directory(checkpoint_dir)
    if train_mod is not None:
        train_mod.report(metrics, checkpoint=checkpoint)
    elif hasattr(tune_mod, "report"):
        tune_mod.report(metrics, checkpoint=checkpoint)
    else:
        raise RuntimeError(
            "No compatible Tune report API found: the installed ray has "
            "neither the legacy `tune.report(**kw)` nor `ray.train.report` "
            "/ `ray.tune.report(metrics, checkpoint=...)`.")


def _trial_bundles(
        num_workers: int,
        num_cpus_per_worker: int,
        use_gpu: bool,
        use_tpu: Optional[bool],
        resources_per_worker: Optional[Dict]) -> List[Dict[str, Any]]:
    """Pure bundle math for :func:`get_tune_resources` (unit-testable).

    ``resources_per_worker`` override semantics match the strategies
    (``ray_ddp.py:85-112``): ``CPU`` beats ``num_cpus_per_worker``, ``TPU``
    (or legacy ``GPU``) beats ``use_tpu``/``use_gpu``.
    """
    resources_per_worker = dict(resources_per_worker or {})
    num_cpus = resources_per_worker.pop("CPU", num_cpus_per_worker)
    chips = resources_per_worker.pop(
        "TPU", resources_per_worker.pop("GPU", None))
    if chips is None:
        chips = int(use_gpu if use_tpu is None else use_tpu)
    bundle: Dict[str, Any] = {"CPU": num_cpus}
    if chips:
        bundle["TPU"] = chips
    bundle.update(resources_per_worker)
    head_bundle = {"CPU": 1}  # the trial driver itself (README.md:185)
    return [head_bundle] + [bundle] * num_workers


def resume_ckpt_path(checkpoint_dir: Optional[str] = None,
                     filename: str = "checkpoint") -> Optional[str]:
    """The trial's restore point, or ``None`` if Tune scheduled a fresh
    start.

    Call inside a trainable and hand the result to
    ``Trainer.fit(..., ckpt_path=...)`` — this is what a PBT exploit step
    (clone a better trial's weights, perturb hparams, continue) or a
    failed-trial restore needs. Version-adaptive like :func:`_report`:
    on legacy Ray pass the trainable's ``checkpoint_dir`` argument; on
    Ray >= 2.x the checkpoint comes from ``tune.get_checkpoint()`` /
    ``train.get_checkpoint()`` and is materialized to a local directory.
    ``filename`` must match the ``TuneReportCheckpointCallback`` filename.
    """
    if checkpoint_dir is not None:  # legacy trainable argument
        path = os.path.join(checkpoint_dir, filename)
        return path if os.path.exists(path) else None
    tune_mod = _require_tune()
    get_ckpt = getattr(tune_mod, "get_checkpoint", None)
    if get_ckpt is None:
        try:
            from ray import train as _train
            get_ckpt = getattr(_train, "get_checkpoint", None)
        except ImportError:
            get_ckpt = None
    if get_ckpt is None:
        return None
    ckpt = get_ckpt()
    if ckpt is None:
        return None
    path = os.path.join(ckpt.to_directory(), filename)
    return path if os.path.exists(path) else None


def get_tune_resources(num_workers: int = 1,
                       num_cpus_per_worker: int = 1,
                       use_gpu: bool = False,
                       use_tpu: Optional[bool] = None,
                       resources_per_worker: Optional[Dict] = None):
    """Resources per Tune trial. Parity: ``tune.py:32-56`` — the extra
    ``{CPU: 1}`` head bundle hosts the trial driver (which launches the
    worker actors); PACK keeps a trial's workers co-scheduled."""
    _require_tune()
    from ray.tune import PlacementGroupFactory
    bundles = _trial_bundles(num_workers, num_cpus_per_worker, use_gpu,
                             use_tpu, resources_per_worker)
    return PlacementGroupFactory(bundles, strategy="PACK")


class TuneReportCallback(Callback):
    """Report trainer metrics to Tune at chosen hooks.

    Parity: ``tune.py:59-134``. ``metrics`` maps the name reported to Tune →
    the ``trainer.callback_metrics`` key (str/list = same-name passthrough,
    ``None`` = report everything). Fires on rank 0 only, never during the
    sanity-check phase (``tune.py:112-114``).
    """

    _allowed = [
        "fit_start", "train_start", "train_epoch_end", "validation_end",
        "validation_epoch_end", "test_epoch_end", "train_end",
    ]

    def __init__(self,
                 metrics: Union[None, str, List[str], Dict[str, str]] = None,
                 on: Union[str, List[str]] = "validation_end"):
        if isinstance(metrics, str):
            metrics = [metrics]
        self._metrics = metrics
        self._on = [on] if isinstance(on, str) else list(on)
        bad = [h for h in self._on if h not in self._allowed]
        if bad:
            raise ValueError(
                f"Invalid hook(s) {bad}; choose from {self._allowed}")

    # -- hook plumbing ---------------------------------------------------- #
    def on_fit_start(self, trainer, pl_module):
        if "fit_start" in self._on:
            self._handle(trainer, pl_module)

    def on_train_start(self, trainer, pl_module):
        if "train_start" in self._on:
            self._handle(trainer, pl_module)

    def on_train_epoch_end(self, trainer, pl_module):
        if "train_epoch_end" in self._on:
            self._handle(trainer, pl_module)

    def on_validation_end(self, trainer, pl_module):
        if "validation_end" in self._on:
            self._handle(trainer, pl_module)

    def on_validation_epoch_end(self, trainer, pl_module):
        if "validation_epoch_end" in self._on:
            self._handle(trainer, pl_module)

    def on_test_epoch_end(self, trainer, pl_module):
        if "test_epoch_end" in self._on:
            self._handle(trainer, pl_module)

    def on_train_end(self, trainer, pl_module):
        if "train_end" in self._on:
            self._handle(trainer, pl_module)

    def _get_report_dict(self, trainer, pl_module) -> Optional[Dict]:
        if trainer.sanity_checking:  # parity: tune.py:112-114
            return None
        metrics = self._metrics
        if not metrics:
            metrics = {k: k for k in trainer.callback_metrics}
        if isinstance(metrics, list):
            metrics = {k: k for k in metrics}
        report = {}
        for tune_key, metric_key in metrics.items():
            if metric_key in trainer.callback_metrics:
                v = trainer.callback_metrics[metric_key]
                report[tune_key] = float(v) if hasattr(v, "__float__") else v
        return report or None

    def _handle(self, trainer, pl_module) -> None:
        if trainer.global_rank != 0:
            return
        report = self._get_report_dict(trainer, pl_module)
        if report is None:
            return
        tune_mod = _require_tune()
        session.put_queue(lambda: _report(tune_mod, report))


class _TuneCheckpointCallback(Callback):
    """Stream a full trainer checkpoint to the trial driver.

    Parity: ``tune.py:136-178`` — the checkpoint is dumped *in the worker*
    (``trainer.dump_checkpoint()``), crosses as bytes (no shared filesystem
    assumed), and is written into ``tune.checkpoint_dir`` *in the trial
    process* via the callable queue.
    """

    def __init__(self, filename: str = "checkpoint",
                 on: Union[str, List[str]] = "validation_end"):
        self._filename = filename
        self._on = [on] if isinstance(on, str) else list(on)
        bad = [h for h in self._on if h not in TuneReportCallback._allowed]
        if bad:
            raise ValueError(f"Invalid hook(s) {bad}; choose from "
                             f"{TuneReportCallback._allowed}")

    @staticmethod
    def _create_checkpoint(tune_mod, stream: bytes, global_step: int,
                           filename: str,
                           report: Optional[Dict[str, Any]] = None) -> None:
        """Write the checkpoint in the trial process (queue thunk).

        Legacy Ray: bytes land in ``tune.checkpoint_dir(step)`` (parity
        ``tune.py:161-178``); an optional report follows. Ray >= 2.x has no
        standalone checkpoint registration — the checkpoint can only enter
        Tune attached to a report, so both travel in one ``train.report``.
        """
        if _is_legacy_tune(tune_mod):
            with tune_mod.checkpoint_dir(step=global_step) as checkpoint_dir:
                with open(os.path.join(checkpoint_dir, filename), "wb") as f:
                    f.write(stream)
            if report is not None:
                _report(tune_mod, report)
            return
        import tempfile
        with tempfile.TemporaryDirectory() as tmpdir:
            with open(os.path.join(tmpdir, filename), "wb") as f:
                f.write(stream)
            _report(tune_mod,
                    report if report is not None
                    else {"checkpoint_step": global_step},
                    checkpoint_dir=tmpdir)

    def _checkpoint(self, trainer,
                    report: Optional[Dict[str, Any]] = None) -> None:
        if trainer.sanity_checking or trainer.global_rank != 0:
            return
        tune_mod = _require_tune()
        stream = to_state_stream(trainer.dump_checkpoint())
        global_step = trainer.global_step
        session.put_queue(
            lambda: self._create_checkpoint(tune_mod, stream, global_step,
                                            self._filename, report))

    def on_fit_start(self, trainer, pl_module):
        if "fit_start" in self._on:
            self._checkpoint(trainer)

    def on_train_start(self, trainer, pl_module):
        if "train_start" in self._on:
            self._checkpoint(trainer)

    def on_train_epoch_end(self, trainer, pl_module):
        if "train_epoch_end" in self._on:
            self._checkpoint(trainer)

    def on_validation_end(self, trainer, pl_module):
        if "validation_end" in self._on:
            self._checkpoint(trainer)

    def on_validation_epoch_end(self, trainer, pl_module):
        if "validation_epoch_end" in self._on:
            self._checkpoint(trainer)

    def on_test_epoch_end(self, trainer, pl_module):
        if "test_epoch_end" in self._on:
            self._checkpoint(trainer)

    def on_train_end(self, trainer, pl_module):
        if "train_end" in self._on:
            self._checkpoint(trainer)


class TuneReportCheckpointCallback(Callback):
    """Checkpoint then report — composition parity ``tune.py:181-236``
    (checkpoint first so Tune associates it with the report)."""

    def __init__(self,
                 metrics: Union[None, str, List[str], Dict[str, str]] = None,
                 filename: str = "checkpoint",
                 on: Union[str, List[str]] = "validation_end"):
        self._checkpoint_cb = _TuneCheckpointCallback(filename, on)
        self._report_cb = TuneReportCallback(metrics, on)

    def _fan(self, hook: str, trainer, pl_module) -> None:
        if trainer.global_rank != 0:
            return
        tune_mod = _require_tune()
        if _is_legacy_tune(tune_mod):
            # legacy: checkpoint first, then report, as two queue thunks —
            # the report registers the just-written checkpoint_dir
            getattr(self._checkpoint_cb, hook)(trainer, pl_module)
            getattr(self._report_cb, hook)(trainer, pl_module)
            return
        # Ray >= 2.x: checkpoint + metrics must travel in ONE report call
        if not any(hook == "on_" + h for h in self._checkpoint_cb._on):
            return
        report = self._report_cb._get_report_dict(trainer, pl_module)
        self._checkpoint_cb._checkpoint(trainer, report=report or {})

    def on_fit_start(self, trainer, pl_module):
        self._fan("on_fit_start", trainer, pl_module)

    def on_train_start(self, trainer, pl_module):
        self._fan("on_train_start", trainer, pl_module)

    def on_train_epoch_end(self, trainer, pl_module):
        self._fan("on_train_epoch_end", trainer, pl_module)

    def on_validation_end(self, trainer, pl_module):
        self._fan("on_validation_end", trainer, pl_module)

    def on_validation_epoch_end(self, trainer, pl_module):
        self._fan("on_validation_epoch_end", trainer, pl_module)

    def on_test_epoch_end(self, trainer, pl_module):
        self._fan("on_test_epoch_end", trainer, pl_module)

    def on_train_end(self, trainer, pl_module):
        self._fan("on_train_end", trainer, pl_module)
