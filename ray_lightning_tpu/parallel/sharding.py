"""Sharding rules: pytree → NamedSharding trees for each parallelism flavor.

This is the TPU-native seat of the reference's gradient-sync machinery: where
DDP wraps the module and all-reduces grads (NCCL inside
``DistributedDataParallel``, bound at ``ray_lightning/ray_ddp.py:202-206``)
and FairScale shards optimizer state (via PTL's ``DDPSpawnShardedStrategy``,
``ray_lightning/ray_ddp_sharded.py:12-13``), we instead *annotate* where each
array lives on the mesh and let XLA insert psum / reduce-scatter /
all-gather. The strategy classes pick which rule applies to params vs
optimizer state vs batch.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (every device holds the whole array)."""
    return NamedSharding(mesh, P())


def data_axis_names(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh axes that carry batch-dim sharding (single source of
    truth for batch_sharding / pipelined_stack / sp_sharded_attention)."""
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)


def compose_rules(*rules):
    """Combine ``(path, leaf) -> PartitionSpec`` rules: the first rule
    returning a non-trivial spec wins, so e.g. MoE expert banks take the
    ``ep`` layout while the attention blocks around them take the
    Megatron ``tp`` layout::

        MeshStrategy(axes={"dp": 2, "ep": 2, "tp": 2},
                     param_rule=compose_rules(expert_parallel_rule,
                                              tensor_parallel_rule))
    """
    def rule(path, leaf):
        for r in rules:
            spec = r(path, leaf)
            if any(s is not None for s in spec):
                return spec
        return P()
    return rule


def leading_dim_rule(keyword: str, axis: str):
    """Build a ``(path, leaf) -> PartitionSpec`` rule sharding the leading
    dim of every param whose path contains ``keyword`` along ``axis`` —
    the shared shape of expert-parallel ('experts' → 'ep') and
    pipeline-parallel ('blocks' → 'pp') layouts."""
    def rule(path, leaf):
        names = [str(getattr(p, "key", getattr(p, "name", p)))
                 for p in path]
        if any(keyword in n for n in names):
            spec = [None] * getattr(leaf, "ndim", 0)
            if spec:
                spec[0] = axis
            return P(*spec)
        return P()
    return rule


def batch_sharding(mesh: Mesh,
                   data_axes: Optional[Sequence[str]] = None) -> NamedSharding:
    """Shard the leading (batch) dim across the data axes of the mesh.

    The analog of the reference's ``DistributedSampler`` kwargs
    (``ray_ddp.py:325-334``): instead of N dataloaders each reading 1/N of
    the data, one global batch is laid out with its batch dim split across
    ``dp``×``fsdp`` (and any other data-like axes present).
    """
    if data_axes is None:
        data_axes = data_axis_names(mesh)
    axes = tuple(a for a in data_axes if a in mesh.axis_names)
    if not axes:
        return replicated(mesh)
    return NamedSharding(mesh, P(axes))


def largest_divisible_dim(shape: Tuple[int, ...], size: int) -> Optional[int]:
    """Pick the best dim to shard ``size``-ways: largest dim divisible by it.

    Used for ZeRO-1 / FSDP parameter+optimizer-state sharding where no
    per-layer logical rule exists (flat sharding, matching FairScale's
    greedy parameter bucketing semantics but resolved per-array).
    """
    best, best_size = None, 0
    for i, d in enumerate(shape):
        if d % size == 0 and d >= size and d > best_size:
            best, best_size = i, d
    return best


def shard_leaf_spec(leaf: Any, axis_name: str, size: int) -> P:
    """PartitionSpec sharding one array along its best dim, else replicated."""
    shape = getattr(leaf, "shape", ())
    if size <= 1 or not shape:
        return P()
    dim = largest_divisible_dim(tuple(shape), size)
    if dim is None:
        return P()
    spec = [None] * len(shape)
    spec[dim] = axis_name
    return P(*spec)


def shard_pytree_along_axis(tree: Any, mesh: Mesh, axis_name: str) -> Any:
    """NamedSharding tree sharding every leaf along ``axis_name`` where possible.

    This is the FSDP/ZeRO rule: each array is split along its largest
    divisible dim over the axis; arrays too small to split stay replicated
    (their memory is negligible by construction).
    """
    size = mesh.shape[axis_name]

    def _leaf(leaf):
        return NamedSharding(mesh, shard_leaf_spec(leaf, axis_name, size))

    return jax.tree_util.tree_map(_leaf, tree)


def replicated_pytree(tree: Any, mesh: Mesh) -> Any:
    shard = replicated(mesh)
    return jax.tree_util.tree_map(lambda _: shard, tree)


def apply_rule(tree: Any, mesh: Mesh,
               rule: Callable[[Tuple[Any, ...], Any], P],
               fallback_replicate: bool = False) -> Any:
    """Map a ``(path, leaf) -> PartitionSpec`` rule over a pytree.

    Used by tensor-parallel strategies where sharding depends on the
    parameter's role (e.g. attention qkv vs mlp down-projection).

    ``fallback_replicate=True`` replicates any leaf whose shape cannot
    satisfy the rule's spec instead of letting pjit reject it. This is
    for DERIVED trees (optimizer state): name-matching rules see e.g.
    adafactor's factored ``v_row['...']['experts_down']`` — a ``(1,)``
    placeholder that matches the expert param rule by path but not by
    shape. Parameters themselves keep the loud failure (a rule that
    cannot shard a param is a bug, not a fallback case).
    """
    def _spec_fits(spec: P, leaf) -> bool:
        shape = getattr(leaf, "shape", None)
        if shape is None:
            return True
        if len(spec) > len(shape):
            return False
        for dim, names in zip(shape, spec):
            if names is None:
                continue
            size = 1
            for n in (names if isinstance(names, tuple) else (names,)):
                size *= mesh.shape[n]
            if dim % size:
                return False
        return True

    def _leaf(path, leaf):
        spec = rule(path, leaf)
        if fallback_replicate and not _spec_fits(spec, leaf):
            spec = P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(_leaf, tree)


def put_host_local_batch(local_batch: Any, sharding: Any) -> Any:
    """Assemble a global array from per-process host-LOCAL batch shards.

    The memory-lean alternative to :func:`put_global_batch` for multi-host
    jobs: each process loads only its own slice of the global batch (use
    ``strategy.distributed_sampler_kwargs`` to shard the loader — rank r
    of n replicas loads samples ``r, r+n, …`` or the r-th contiguous
    block, matching the batch sharding's dp layout), and
    ``jax.make_array_from_process_local_data`` stitches the global array
    without any host ever materializing the full batch. Single-process:
    plain ``device_put``. ``sharding`` may be one sharding or a pytree.
    """
    if jax.process_count() == 1:
        return jax.device_put(local_batch, sharding)
    is_tree = not isinstance(sharding, jax.sharding.Sharding)

    def _leaf(x, s):
        return jax.make_array_from_process_local_data(s, np.asarray(x))

    if is_tree:
        return jax.tree_util.tree_map(_leaf, local_batch, sharding)
    return jax.tree_util.tree_map(lambda x: _leaf(x, sharding),
                                  local_batch)


def put_global_batch(batch: Any, sharding: Any) -> Any:
    """Place a host-global batch onto a (possibly multi-process) mesh.

    Single-process: plain ``device_put``. Multi-controller SPMD: every
    process holds the same host-global batch (loaders are seeded
    identically), and ``jax.make_array_from_callback`` transfers **only the
    shards this process's devices own** — the per-host batch feeding the
    reference gets from ``DistributedSampler`` (``ray_ddp.py:325-334``),
    without N loaders needing rank-aware slicing. ``sharding`` may be a
    single sharding (applied to every leaf) or a matching pytree.
    """
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    is_tree = not isinstance(sharding, jax.sharding.Sharding)

    def _leaf(x, s):
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, s,
                                            lambda idx: x[idx])

    if is_tree:
        return jax.tree_util.tree_map(_leaf, batch, sharding)
    return jax.tree_util.tree_map(lambda x: _leaf(x, sharding), batch)
