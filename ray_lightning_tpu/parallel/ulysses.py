"""Ulysses-style sequence parallelism: all-to-all head-sharded attention.

The second long-context axis (DeepSpeed-Ulysses / megascale "context
parallelism by heads"), complementing ring attention
(:mod:`ray_lightning_tpu.parallel.ring_attention`):

- **ring**: K/V shards rotate around the ``sp`` ring (``ppermute``), each
  rank computes online-softmax partials for its *local queries*. Memory
  O(T/N) everywhere; N neighbor hops per attention; causal masking skips
  half the hops' work.
- **ulysses**: one all-to-all reshards activations from sequence-sharded
  ``(B, T/N, H, D)`` to head-sharded ``(B, T, H/N, D)``, each rank runs
  *full-sequence attention for its head subset*, and one all-to-all
  reshards back. Two collective hops total (cheaper than N ppermute hops
  when N is large and ICI all-to-all is fast), and — because every rank
  sees the whole sequence — arbitrary additive masks and attention
  dropout work unchanged, which the ring's blockwise accumulator cannot
  cheaply support.

TPU-native design: no explicit ``all_to_all`` calls. The arrays are
logically global under GSPMD; two ``with_sharding_constraint`` boundary
annotations (sequence-sharded → head-sharded → sequence-sharded) make XLA
insert the minimal resharding collectives over ICI. The rest of the model
keeps the sequence-sharded layout from ``SequenceParallelStrategy``
(LN/MLP are pointwise over tokens, so they stay perfectly sharded).

Constraint: ``n_heads`` must be divisible by ``sp`` (checked at trace
time, static shapes). The reference has no counterpart (SURVEY.md §2.3
"Ulysses: absent — not required"); this closes that inventory row anyway.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_tpu.ops.attention import dot_product_attention
from ray_lightning_tpu.parallel.ring_attention import SP_AXIS_NAME, \
    get_sp_mesh
from ray_lightning_tpu.parallel.sharding import data_axis_names


def _spec(mesh, *entries):
    names = mesh.axis_names

    def keep(e):
        if e is None or e in names:
            return e
        if isinstance(e, tuple):  # multi-axis dim, e.g. ("dp", "fsdp")
            kept = tuple(a for a in e if a in names)
            return kept or None
        return None

    return NamedSharding(mesh, P(*[keep(e) for e in entries]))


def ulysses_attention(q: jax.Array,
                      k: jax.Array,
                      v: jax.Array,
                      *,
                      causal: bool = False,
                      mask: Optional[jax.Array] = None,
                      dropout_rate: float = 0.0,
                      dropout_rng: Optional[jax.Array] = None,
                      softmax_dtype=None) -> jax.Array:
    """Attention with Ulysses sequence parallelism over the ``sp`` axis.

    Shapes ``(B, T, H, D)`` (global, GSPMD). With no ``sp`` mesh
    registered this is exactly :func:`dot_product_attention`, so models
    can set ``attention_impl='ulysses'`` unconditionally.
    """
    sd = {} if softmax_dtype is None else {"softmax_dtype": softmax_dtype}
    mesh = get_sp_mesh()
    if mesh is None:
        return dot_product_attention(q, k, v, causal=causal, mask=mask,
                                     dropout_rate=dropout_rate,
                                     dropout_rng=dropout_rng, **sd)
    sp = mesh.shape[SP_AXIS_NAME]
    n_heads = q.shape[2]
    if n_heads % sp != 0:
        raise ValueError(
            f"ulysses attention shards heads over sp={sp}, but n_heads="
            f"{n_heads} is not divisible; use a head count divisible by "
            "sp or attention_impl='ring' (which shards sequence, not "
            "heads)")

    # Resolve the batch axes the way sp_sharded_attention does, so custom
    # meshes that name their data axis "fsdp" (or shard batch over both)
    # keep the batch dim pinned at both resharding boundaries.
    batch = data_axis_names(mesh) or None
    seq_spec = _spec(mesh, batch, SP_AXIS_NAME, None, None)
    head_spec = _spec(mesh, batch, None, SP_AXIS_NAME, None)

    # boundary 1: sequence-sharded -> head-sharded (XLA emits all-to-all)
    q, k, v = (jax.lax.with_sharding_constraint(x, head_spec)
               for x in (q, k, v))
    out = dot_product_attention(q, k, v, causal=causal, mask=mask,
                                dropout_rate=dropout_rate,
                                dropout_rng=dropout_rng, **sd)
    # boundary 2: back to the model's sequence-sharded layout
    return jax.lax.with_sharding_constraint(out, seq_spec)
