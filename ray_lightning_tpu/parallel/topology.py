"""TPU slice topology: host/chip layout discovery and rank↔mesh alignment.

The reference maps nodes by comparing actor IP strings
(``ray_lightning/launchers/ray_launcher.py:131-158``) and brokers GPU
visibility by unioning ``CUDA_VISIBLE_DEVICES`` per node (``:178-220``) —
enough for NCCL, where a process owns exactly one CUDA device and peers
P2P within a node. TPU needs more structure:

- a **slice** has a fixed shape (e.g. v4-32 = 4 hosts × 4 chips, each chip
  a 2-core "megacore" presented as one XLA device), advertised to every
  TPU-VM through metadata env vars;
- **libtpu is single-owner**: exactly one process may drive a chip, so the
  launcher must schedule ONE actor per host that owns every chip on it —
  co-located XLA processes with overlapping visibility deadlock at init;
- the launcher's global rank must equal ``jax.process_index()`` and the
  mesh's flat device order must group processes contiguously, or per-host
  batch feeding (``sharding.put_global_batch``) silently feeds the wrong
  shard of the global batch to a host.

This module owns those three concerns. Detection prefers the TPU-VM
environment (authoritative on real slices), then Ray node resources, then
local device files; everything takes an injectable ``env`` / ``ray``
for the fake-topology tests (the analog of the reference's scripted
``Node1Actor``/``Node2Actor`` stubs, ``tests/test_ddp.py:80-114``).
"""
from __future__ import annotations

import dataclasses
import glob
import math
import os
import re
from typing import Any, List, Mapping, Optional, Tuple

# GCE TPU-VM metadata environment (set by the TPU runtime on every worker).
ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"
WORKER_ID_ENV = "TPU_WORKER_ID"
WORKER_HOSTNAMES_ENV = "TPU_WORKER_HOSTNAMES"
CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
HOST_BOUNDS_ENV = "TPU_HOST_BOUNDS"

# Per-generation physical layout. `count_unit` says what the number in the
# accelerator type string counts: TensorCores (v2-v4, v5p) or chips
# (v5e/v6e). Megacore generations fuse a chip's 2 cores into one XLA device.
_GENERATIONS = {
    #  gen        cores/chip  chips/host  megacore  count_unit
    "v2": (2, 4, False, "cores"),
    "v3": (2, 4, False, "cores"),
    "v4": (2, 4, True, "cores"),
    "v5p": (2, 4, True, "cores"),
    "v5litepod": (1, 8, False, "chips"),
    "v5e": (1, 8, False, "chips"),
    "v6e": (1, 8, False, "chips"),
}


@dataclasses.dataclass(frozen=True)
class TPUTopology:
    """Shape of the TPU slice this job runs on.

    ``devices_per_host`` is the number of XLA devices a single-owner
    process on that host will see — chips under megacore (v4/v5p) or on
    single-core chips (v5e), cores otherwise (v2/v3).
    """
    accelerator_type: str
    num_hosts: int
    chips_per_host: int
    cores_per_chip: int = 1
    megacore: bool = False
    worker_id: int = 0
    worker_hostnames: Tuple[str, ...] = ()

    @property
    def total_chips(self) -> int:
        return self.num_hosts * self.chips_per_host

    @property
    def devices_per_host(self) -> int:
        if self.megacore or self.cores_per_chip == 1:
            return self.chips_per_host
        return self.chips_per_host * self.cores_per_chip

    @property
    def total_devices(self) -> int:
        return self.num_hosts * self.devices_per_host

    def local_ranks(self) -> List[Tuple[int, int]]:
        """global rank → (local, node) for the one-process-per-host layout:
        rank h lives alone on host h. The shape ``RayLauncher.get_local_ranks``
        must reproduce from actor node IPs on a correctly spread slice."""
        return [(0, h) for h in range(self.num_hosts)]


def parse_accelerator_type(accel_type: str) -> Optional[TPUTopology]:
    """Topology from a TPU accelerator-type string (``v4-32``,
    ``v5litepod-16``, ``v3-8``...). Returns None if unparseable."""
    m = re.fullmatch(r"(v\d+[a-z]*)(?:pod)?-(\d+)", accel_type.strip())
    if not m:
        return None
    gen, count = m.group(1), int(m.group(2))
    if gen + "pod" in _GENERATIONS:  # "v5litepod-16" splits as v5lite+pod
        gen = gen + "pod"
    if gen not in _GENERATIONS:
        return None
    cores_per_chip, chips_per_host_max, megacore, unit = _GENERATIONS[gen]
    chips = count // cores_per_chip if unit == "cores" else count
    chips = max(chips, 1)
    chips_per_host = min(chips, chips_per_host_max)
    num_hosts = max(1, math.ceil(chips / chips_per_host))
    return TPUTopology(
        accelerator_type=accel_type,
        num_hosts=num_hosts,
        chips_per_host=chips_per_host,
        cores_per_chip=cores_per_chip,
        megacore=megacore)


def _parse_bounds(bounds: str) -> Optional[int]:
    """Product of a ``"2,2,1"``-style bounds triple."""
    try:
        parts = [int(p) for p in bounds.split(",") if p.strip()]
    except ValueError:
        return None
    return math.prod(parts) if parts else None


def topology_from_env(
        env: Optional[Mapping[str, str]] = None) -> Optional[TPUTopology]:
    """Topology from TPU-VM metadata env vars; None when not on a TPU-VM.

    ``TPU_HOST_BOUNDS``/``TPU_CHIPS_PER_HOST_BOUNDS`` are authoritative for
    the shape when present; the accelerator-type string fills in chip
    microarchitecture (cores, megacore)."""
    env = os.environ if env is None else env
    accel_type = env.get(ACCELERATOR_TYPE_ENV, "")
    parsed = parse_accelerator_type(accel_type) if accel_type else None

    hosts = _parse_bounds(env.get(HOST_BOUNDS_ENV, ""))
    chips_per_host = _parse_bounds(env.get(CHIPS_PER_HOST_BOUNDS_ENV, ""))
    hostnames = tuple(
        h.strip() for h in env.get(WORKER_HOSTNAMES_ENV, "").split(",")
        if h.strip())
    if hosts is None and hostnames:
        hosts = len(hostnames)
    if parsed is None and hosts is None and chips_per_host is None:
        return None

    try:
        worker_id = int(env.get(WORKER_ID_ENV, "0"))
    except ValueError:
        worker_id = 0
    return TPUTopology(
        accelerator_type=accel_type,
        num_hosts=hosts if hosts is not None else
        (parsed.num_hosts if parsed else 1),
        chips_per_host=chips_per_host if chips_per_host is not None else
        (parsed.chips_per_host if parsed else 1),
        cores_per_chip=parsed.cores_per_chip if parsed else 1,
        megacore=parsed.megacore if parsed else False,
        worker_id=worker_id,
        worker_hostnames=hostnames)


def chips_per_host_from_ray(ray_module: Any) -> Optional[int]:
    """Per-host chip count from Ray's node table: the smallest per-node
    ``TPU`` resource total among TPU nodes (requesting that many chips per
    actor makes Ray's bin-packing spread one actor per host — the
    scheduling-level fix for overlapping chip ownership; see ADVICE on
    ``_create_worker``). None if Ray exposes no TPU nodes."""
    nodes_fn = getattr(ray_module, "nodes", None)
    if nodes_fn is None:
        return None
    try:
        nodes = nodes_fn()
    except Exception as exc:
        from ray_lightning_tpu.reliability import log_suppressed
        log_suppressed("topology.node_table", exc,
                       "ray.nodes() unavailable; no per-host chip count")
        return None
    counts = []
    for node in nodes or []:
        if not node.get("Alive", True):
            continue
        tpu = node.get("Resources", {}).get("TPU")
        if tpu:
            counts.append(int(tpu))
    return min(counts) if counts else None


def local_chip_count() -> int:
    """Chips physically present on this host (``/dev/accel*`` / vfio)."""
    n = len(glob.glob("/dev/accel[0-9]*"))
    if n == 0:
        n = len(glob.glob("/dev/vfio/[0-9]*"))
    return n


def detect_topology(env: Optional[Mapping[str, str]] = None,
                    ray_module: Any = None) -> TPUTopology:
    """Best-effort topology: TPU-VM env → Ray node resources → local
    devices → single-host fallback."""
    topo = topology_from_env(env)
    if topo is not None:
        return topo
    if ray_module is not None:
        chips = chips_per_host_from_ray(ray_module)
        if chips:
            return TPUTopology(accelerator_type="", num_hosts=1,
                               chips_per_host=chips)
    chips = local_chip_count()
    return TPUTopology(accelerator_type="", num_hosts=1,
                       chips_per_host=max(chips, 1))


def multi_host_device_order(mesh: Any) -> List[int]:
    """Process index of each device in mesh-flat order."""
    return [d.process_index for d in mesh.devices.flat]


def assert_mesh_process_alignment(mesh: Any,
                                  global_rank: Optional[int] = None,
                                  process_index: Optional[int] = None) -> None:
    """Fail loudly if the launcher's rank model and the mesh disagree.

    Two invariants, both load-bearing for per-host batch feeding
    (``sharding.put_global_batch`` transfers the index-slices owned by each
    process, so slice→process assignment must match rank→host assignment):

    1. the mesh's flat device order groups each process's devices into one
       contiguous run, with first appearances in ascending process order —
       i.e. ``multi_host_device_order(mesh)`` looks like
       ``[0,0,..,1,1,..,N-1,..]``;
    2. this worker's launcher-assigned global rank equals its JAX process
       index (the launcher passed ``process_id=global_rank`` to
       ``jax.distributed.initialize``; anything else means the rendezvous
       handed out different ids).

    Accepts any mesh-like object whose ``devices.flat`` yields objects with
    ``process_index`` (fake meshes in tests).
    """
    order = multi_host_device_order(mesh)
    seen: List[int] = []
    for p in order:
        if seen and p == seen[-1]:
            continue
        if p in seen:
            raise AssertionError(
                f"Mesh device order interleaves processes: {order}. "
                "Per-host batch shards would not be contiguous; build the "
                "mesh with mesh_utils.create_device_mesh / contiguous "
                "process blocks.")
        seen.append(p)
    if seen != sorted(seen):
        raise AssertionError(
            f"Mesh first-appearance process order {seen} is not ascending; "
            "global rank r would not feed host r's devices.")
    if global_rank is not None and process_index is not None \
            and global_rank != process_index:
        raise AssertionError(
            f"Launcher global rank {global_rank} != jax process_index "
            f"{process_index}: the coordinator handed out a different "
            "process id than the launcher assigned. Check that every "
            "worker passed its launcher rank to worker_setup().")
