"""Device-mesh construction: the TPU-native replacement for process groups.

Where the reference bootstraps a flat ``torch.distributed`` world over NCCL
(``ray_lightning/ray_ddp.py:171-213``), the TPU design expresses *all*
parallelism as named axes of a ``jax.sharding.Mesh``; XLA inserts the
collectives (psum / all-gather / reduce-scatter) from sharding annotations,
riding ICI within a slice and DCN across slices.

Axis vocabulary (a superset of the reference's single DP axis — the
reference implements only DP / allreduce-DP / ZeRO-1, see SURVEY.md §2.3):

- ``dp``   data parallel (batch split; params replicated)
- ``fsdp`` fully-sharded data parallel (batch + params + opt-state split)
- ``tp``   tensor parallel (weight matrices split; activations gathered)
- ``sp``   sequence/context parallel (sequence dim split; ring attention)
- ``pp``   pipeline parallel (layer groups split)
- ``ep``   expert parallel (MoE experts split)

Mesh-axis *order* matters on hardware: the innermost (last) axes map to
physically closest devices. We order meshes ``(pp, dp, fsdp, ep, sp, tp)``
so that tensor-parallel collectives — the most latency-sensitive — ride the
tightest ICI loops, matching the standard scaling-book recipe.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
TP_AXIS = "tp"
SP_AXIS = "sp"
PP_AXIS = "pp"
EP_AXIS = "ep"

# Outer → inner physical ordering (inner = last = fastest ICI neighborhood).
_CANONICAL_ORDER: Tuple[str, ...] = (PP_AXIS, DP_AXIS, FSDP_AXIS, EP_AXIS,
                                     SP_AXIS, TP_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A named multi-axis parallelism layout.

    ``axes`` maps axis name → size. A size of ``-1`` on at most one axis
    means "absorb all remaining devices" (like a reshape wildcard).
    """
    axes: Dict[str, int]

    def __post_init__(self):
        unknown = [a for a in self.axes if a not in _CANONICAL_ORDER]
        if unknown:
            raise ValueError(
                f"Unknown mesh axes {unknown}; valid: {_CANONICAL_ORDER}")
        wildcards = [a for a, s in self.axes.items() if s == -1]
        if len(wildcards) > 1:
            raise ValueError("At most one mesh axis may be -1 (wildcard)")

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(a for a in _CANONICAL_ORDER if a in self.axes)

    def resolved_sizes(self, num_devices: int) -> Tuple[int, ...]:
        sizes = [self.axes[a] for a in self.axis_names]
        if -1 in sizes:
            known = math.prod(s for s in sizes if s != -1)
            if num_devices % known != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes "
                    f"product {known} for spec {self.axes}")
            sizes[sizes.index(-1)] = num_devices // known
        return tuple(sizes)

    def num_required_devices(self, num_devices: int) -> int:
        return math.prod(self.resolved_sizes(num_devices))

    @staticmethod
    def data_parallel(num_workers: int = -1) -> "MeshSpec":
        return MeshSpec({DP_AXIS: num_workers})

    @staticmethod
    def fsdp(num_workers: int = -1) -> "MeshSpec":
        return MeshSpec({FSDP_AXIS: num_workers})


def build_mesh(spec: MeshSpec,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``jax.sharding.Mesh`` for ``spec``.

    Replaces the reference's IP-derived flat rank world
    (``ray_lightning/launchers/ray_launcher.py:131-158``): device *topology*
    (which chips share ICI links) is what determines collective cost on TPU,
    so we delegate physical layout to ``mesh_utils.create_device_mesh`` which
    understands v4/v5 3D tori, and fall back to a plain reshape off-TPU.

    A spec smaller than the device count uses a prefix subset of devices —
    the analog of the reference launching fewer workers than the cluster has
    slots.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    sizes = spec.resolved_sizes(len(devices))
    needed = math.prod(sizes)
    if needed > len(devices):
        raise ValueError(
            f"Mesh spec {dict(zip(spec.axis_names, sizes))} needs {needed} "
            f"devices but only {len(devices)} are available")
    use = devices[:needed]
    if needed == len(devices) and use[0].platform == "tpu":
        try:
            dev_array = mesh_utils.create_device_mesh(
                sizes, devices=np.asarray(use))
        except (ValueError, AssertionError):
            dev_array = np.asarray(use).reshape(sizes)
    else:
        dev_array = np.asarray(use).reshape(sizes)
    return Mesh(dev_array, spec.axis_names)


def multi_host_device_order(mesh: Mesh) -> List[int]:
    """Process indices in mesh order — used by the launcher's rank mapping."""
    from ray_lightning_tpu.parallel.topology import multi_host_device_order
    return multi_host_device_order(mesh)
