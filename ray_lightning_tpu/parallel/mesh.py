"""Device-mesh construction: the TPU-native replacement for process groups.

Where the reference bootstraps a flat ``torch.distributed`` world over NCCL
(``ray_lightning/ray_ddp.py:171-213``), the TPU design expresses *all*
parallelism as named axes of a ``jax.sharding.Mesh``; XLA inserts the
collectives (psum / all-gather / reduce-scatter) from sharding annotations,
riding ICI within a slice and DCN across slices.

Axis vocabulary (a superset of the reference's single DP axis — the
reference implements only DP / allreduce-DP / ZeRO-1, see SURVEY.md §2.3):

- ``dp``   data parallel (batch split; params replicated)
- ``fsdp`` fully-sharded data parallel (batch + params + opt-state split)
- ``tp``   tensor parallel (weight matrices split; activations gathered)
- ``sp``   sequence/context parallel (sequence dim split; ring attention)
- ``pp``   pipeline parallel (layer groups split)
- ``ep``   expert parallel (MoE experts split)

Mesh-axis *order* matters on hardware: the innermost (last) axes map to
physically closest devices. We order meshes ``(pp, dp, fsdp, ep, sp, tp)``
so that tensor-parallel collectives — the most latency-sensitive — ride the
tightest ICI loops, matching the standard scaling-book recipe.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
TP_AXIS = "tp"
SP_AXIS = "sp"
PP_AXIS = "pp"
EP_AXIS = "ep"

# Outer → inner physical ordering (inner = last = fastest ICI neighborhood).
_CANONICAL_ORDER: Tuple[str, ...] = (PP_AXIS, DP_AXIS, FSDP_AXIS, EP_AXIS,
                                     SP_AXIS, TP_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A named multi-axis parallelism layout.

    ``axes`` maps axis name → size. A size of ``-1`` on at most one axis
    means "absorb all remaining devices" (like a reshape wildcard).

    ``dcn_axes`` (multi-slice pods): axis name → how many ways that axis
    crosses slice boundaries over DCN. Each entry must divide the axis's
    total size; the remaining factor stays inside a slice on ICI, with the
    DCN partition OUTER (slow links carry the outermost, least-frequent
    collectives — the scaling-book recipe; typically only ``dp`` or ``pp``
    belong here).
    """
    axes: Dict[str, int]
    dcn_axes: Dict[str, int] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        unknown = [a for a in self.axes if a not in _CANONICAL_ORDER]
        if unknown:
            raise ValueError(
                f"Unknown mesh axes {unknown}; valid: {_CANONICAL_ORDER}")
        wildcards = [a for a, s in self.axes.items() if s == -1]
        if len(wildcards) > 1:
            raise ValueError("At most one mesh axis may be -1 (wildcard)")
        for a, d in self.dcn_axes.items():
            if a not in self.axes:
                raise ValueError(
                    f"dcn_axes[{a!r}] has no matching entry in axes "
                    f"({sorted(self.axes)})")
            if d < 1:
                raise ValueError(f"dcn_axes[{a!r}] must be >= 1, got {d}")
            size = self.axes[a]
            if size != -1 and size % d != 0:
                raise ValueError(
                    f"dcn_axes[{a!r}]={d} does not divide axes[{a!r}]="
                    f"{size}")
        if self.dcn_axes and wildcards:
            raise ValueError(
                "dcn_axes cannot be combined with a -1 wildcard axis — "
                "resolve the axis sizes explicitly for multi-slice layouts")
        if self.dcn_axes:
            # Slice blocks must be contiguous in the mesh's flat device
            # order (multi-host feeding assumes process-contiguous order,
            # strategies/base.py assert_mesh_process_alignment): every
            # axis OUTSIDE the last DCN-bearing axis must itself be fully
            # DCN, otherwise iterating it re-visits slices (interleaving).
            names = self.axis_names
            last_dcn = max(i for i, a in enumerate(names)
                           if a in self.dcn_axes)
            for a in names[:last_dcn]:
                if self.axes[a] != self.dcn_axes.get(a, 1):
                    raise ValueError(
                        f"dcn_axes must occupy the outermost mesh axes: "
                        f"axis {a!r} (size {self.axes[a]}) lies outside "
                        f"DCN-bearing axis {names[last_dcn]!r} but is not "
                        f"fully DCN — either give {a!r} a dcn factor "
                        f"equal to its size or move the DCN split to the "
                        f"outermost axes (canonical order {names})")

    @property
    def num_slices(self) -> int:
        return math.prod(self.dcn_axes.values()) if self.dcn_axes else 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(a for a in _CANONICAL_ORDER if a in self.axes)

    def resolved_sizes(self, num_devices: int) -> Tuple[int, ...]:
        sizes = [self.axes[a] for a in self.axis_names]
        if -1 in sizes:
            known = math.prod(s for s in sizes if s != -1)
            if num_devices % known != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes "
                    f"product {known} for spec {self.axes}")
            sizes[sizes.index(-1)] = num_devices // known
        return tuple(sizes)

    def num_required_devices(self, num_devices: int) -> int:
        return math.prod(self.resolved_sizes(num_devices))

    @staticmethod
    def data_parallel(num_workers: int = -1) -> "MeshSpec":
        return MeshSpec({DP_AXIS: num_workers})

    @staticmethod
    def fsdp(num_workers: int = -1) -> "MeshSpec":
        return MeshSpec({FSDP_AXIS: num_workers})


def build_mesh(spec: MeshSpec,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``jax.sharding.Mesh`` for ``spec``.

    Replaces the reference's IP-derived flat rank world
    (``ray_lightning/launchers/ray_launcher.py:131-158``): device *topology*
    (which chips share ICI links) is what determines collective cost on TPU,
    so we delegate physical layout to ``mesh_utils.create_device_mesh`` which
    understands v4/v5 3D tori, and fall back to a plain reshape off-TPU.

    A spec smaller than the device count uses a prefix subset of devices —
    the analog of the reference launching fewer workers than the cluster has
    slots.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    sizes = spec.resolved_sizes(len(devices))
    needed = math.prod(sizes)
    if needed > len(devices):
        raise ValueError(
            f"Mesh spec {dict(zip(spec.axis_names, sizes))} needs {needed} "
            f"devices but only {len(devices)} are available")
    use = devices[:needed]
    if spec.dcn_axes:
        return Mesh(_hybrid_device_array(spec, sizes, use),
                    spec.axis_names)
    if needed == len(devices) and use[0].platform == "tpu":
        try:
            dev_array = mesh_utils.create_device_mesh(
                sizes, devices=np.asarray(use))
        except (ValueError, AssertionError):
            dev_array = np.asarray(use).reshape(sizes)
    else:
        dev_array = np.asarray(use).reshape(sizes)
    return Mesh(dev_array, spec.axis_names)


def _hybrid_device_array(spec: MeshSpec, sizes: Sequence[int],
                         use: Sequence[jax.Device]) -> np.ndarray:
    """Device array for a multi-slice layout: DCN factors outer, ICI
    factors inner, so within-slice neighbors differ only along ICI.

    On real multislice TPU (devices carry ``slice_index``) this delegates
    to ``mesh_utils.create_hybrid_device_mesh``. Off-TPU the slice
    structure is EMULATED by chunking the device list into ``num_slices``
    equal contiguous groups — the layout invariants (tested on the CPU
    mesh) are identical, which is what makes multi-slice shardings
    compile-checkable without a real pod.
    """
    names = spec.axis_names
    dcn_sizes = [spec.dcn_axes.get(a, 1) for a in names]
    ici_sizes = [s // d for s, d in zip(sizes, dcn_sizes)]
    num_slices = math.prod(dcn_sizes)
    if all(getattr(d, "slice_index", None) is not None for d in use):
        # real multislice hardware: never fall back to emulation — a
        # pseudo-slice chunking that straddles true slice boundaries would
        # silently put ICI-only axes (tp/sp) on DCN
        try:
            return mesh_utils.create_hybrid_device_mesh(
                ici_sizes, dcn_sizes, devices=np.asarray(use))
        except (ValueError, AssertionError) as exc:
            raise ValueError(
                f"create_hybrid_device_mesh failed for ici={ici_sizes} "
                f"dcn={dcn_sizes} over {len(use)} devices "
                f"({num_slices} slices expected): {exc}") from exc
    # emulated slices: contiguous chunks of the device list. Build the
    # array so that indexing along axis k decomposes as
    # (dcn_k outer, ici_k inner): first lay devices out as
    # [slice grid (dcn_sizes)] x [per-slice grid (ici_sizes)], then
    # interleave each axis's (dcn, ici) pair into one dimension.
    arr = np.asarray(use).reshape(tuple(dcn_sizes) + tuple(ici_sizes))
    n = len(names)
    # permute (d0..dn-1, i0..in-1) -> (d0, i0, d1, i1, ...)
    perm = [x for k in range(n) for x in (k, n + k)]
    arr = arr.transpose(perm)
    return arr.reshape(tuple(sizes))


def multi_host_device_order(mesh: Mesh) -> List[int]:
    """Process indices in mesh order — used by the launcher's rank mapping."""
    from ray_lightning_tpu.parallel.topology import multi_host_device_order
    return multi_host_device_order(mesh)
