"""Ring attention: sequence-parallel attention over the ``sp`` mesh axis.

Net-new beyond the reference (SURVEY.md §5: long-context "entirely absent"),
first-class here per the TPU design brief. Each of the N ``sp`` ranks holds a
sequence shard of Q/K/V; K/V shards rotate around the ring via
``lax.ppermute`` (ICI neighbor hops) for N steps while each rank accumulates
online-softmax partial results for its local queries — attention over the
full sequence with O(T/N) activation memory per chip and communication
overlapped across steps.

:func:`ring_attention` must run inside ``shard_map`` with the ``sp`` axis
bound; called with no axis bound it falls back to plain attention, so models
can enable ``attention_impl='ring'`` unconditionally.
:func:`sp_sharded_attention` is the training-path entry
(``TransformerConfig.attention_impl='ring'`` resolves to it): when the
trainer has registered a mesh with an ``sp`` axis (``set_sp_mesh``, done by
``Trainer._setup_state``), it nests a ``shard_map`` over just the attention
call inside the jitted train step — the rest of the model stays GSPMD
(positions, embeddings, loss all see global shapes) while K/V genuinely
rotate around the ring via ``ppermute``. ``SequenceParallelStrategy``
provides the matching ``dp×sp`` batch layout.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_lightning_tpu._compat import axis_size, shard_map
from ray_lightning_tpu.ops.attention import dot_product_attention
from ray_lightning_tpu.ops.flash_attention import (_BIG_NEG, _block_update,
                                                   _finalize)

SP_AXIS_NAME = "sp"

# Mesh registered by the trainer (worker-side, at step-build time) so model
# code can nest a shard_map without threading the mesh through configs —
# configs stay pure data and client-mode drivers never build a mesh.
_SP_MESH: Optional[Mesh] = None


def set_sp_mesh(mesh: Optional[Mesh]) -> None:
    global _SP_MESH
    _SP_MESH = mesh


def get_sp_mesh() -> Optional[Mesh]:
    if _SP_MESH is not None and SP_AXIS_NAME in _SP_MESH.axis_names \
            and _SP_MESH.shape[SP_AXIS_NAME] > 1:
        return _SP_MESH
    return None


def sp_sharded_attention(q: jax.Array,
                         k: jax.Array,
                         v: jax.Array,
                         *,
                         causal: bool = False,
                         mask: Optional[jax.Array] = None,
                         dropout_rate: float = 0.0,
                         dropout_rng: Optional[jax.Array] = None) -> jax.Array:
    """Ring attention over the registered sp mesh; plain attention without
    one. Global shapes (B, T, H, D) — the shard_map is internal."""
    mesh = get_sp_mesh()
    if mesh is None:
        return ring_attention(q, k, v, causal=causal, mask=mask,
                              dropout_rate=dropout_rate,
                              dropout_rng=dropout_rng)
    if mask is not None or (dropout_rate > 0.0 and dropout_rng is not None):
        # Falling back to full attention here would silently re-materialize
        # O(T) per-chip attention memory — an OOM, not a slowdown, at the
        # lengths sequence parallelism targets. Fail loudly instead.
        raise NotImplementedError(
            "attention_impl='ring' under a sequence-parallel mesh supports "
            "neither attention dropout nor custom masks (K/V shards "
            "rotate; no global score matrix exists to mask). Set "
            "dropout=0.0 / drop the mask, or use attention_impl='dot'.")
    if q.shape[1] % mesh.shape[SP_AXIS_NAME] != 0:
        return ring_attention(q, k, v, causal=causal)
    from ray_lightning_tpu.parallel.sharding import data_axis_names
    data_axes = data_axis_names(mesh)
    data_size = 1
    for a in data_axes:
        data_size *= mesh.shape[a]
    if data_size > 1 and q.shape[0] % data_size != 0:
        return ring_attention(q, k, v, causal=causal)
    # keep heads tp-sharded through the ring when a tp axis exists (ring
    # attention is independent per head) — otherwise the shard_map boundary
    # all-gathers the heads dim and every tp peer redundantly runs the ring
    head_axis = None
    if "tp" in mesh.axis_names and mesh.shape["tp"] > 1 \
            and q.shape[2] % mesh.shape["tp"] == 0:
        head_axis = "tp"
    spec = P(data_axes if data_axes else None, SP_AXIS_NAME, head_axis)
    fn = shard_map(
        lambda a, b, c: ring_attention(a, b, c, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ring_attention(q: jax.Array,
                   k: jax.Array,
                   v: jax.Array,
                   *,
                   causal: bool = False,
                   mask: Optional[jax.Array] = None,
                   dropout_rate: float = 0.0,
                   dropout_rng: Optional[jax.Array] = None,
                   axis_name: str = SP_AXIS_NAME,
                   softmax_dtype=jnp.float32) -> jax.Array:
    """Sequence-parallel attention. Local shapes (B, T_local, H, D).

    Sequence positions are assumed contiguous per rank (rank r owns
    ``[r*T_local, (r+1)*T_local)``), which is how the batch sharding lays
    out a ``P(..., 'sp', ...)`` sequence dim.
    """
    del softmax_dtype
    try:
        my_rank = jax.lax.axis_index(axis_name)
        n = axis_size(axis_name)
    except NameError:
        return dot_product_attention(
            q, k, v, causal=causal, mask=mask, dropout_rate=dropout_rate,
            dropout_rng=dropout_rng)
    if mask is not None or (dropout_rate > 0.0 and dropout_rng is not None):
        raise NotImplementedError(
            "ring_attention supports causal/full attention without "
            "attention-dropout or custom masks; use attention_impl='dot' "
            "for those.")

    B, T_local, H, D = q.shape
    scale = D ** -0.5
    total = n * T_local
    qpos = my_rank * T_local + jnp.arange(T_local)

    perm = [(r, (r + 1) % n) for r in range(n)]

    def step(carry, t):
        m, l, acc, kv = carry
        kj, vj = kv
        # at step t we hold the shard originally owned by rank (my - t) % n
        src = jax.lax.rem(my_rank - t + n, n)
        kpos = src * T_local + jnp.arange(T_local)
        m, l, acc = _block_update((m, l, acc), q, kj, vj, qpos, kpos,
                                  causal, total, scale)
        # rotate kv to the next rank; overlap with the next step's compute
        kv = jax.lax.ppermute((kj, vj), axis_name, perm)
        return (m, l, acc, kv), None

    init = (jnp.full((B, H, T_local), _BIG_NEG, jnp.float32),
            jnp.zeros((B, H, T_local), jnp.float32),
            jnp.zeros((B, T_local, H, D), jnp.float32),
            (k, v))
    (m, l, acc, _), _ = jax.lax.scan(step, init, jnp.arange(n))
    return _finalize(l, acc, q.dtype)
