"""Ring attention: sequence-parallel attention over the ``sp`` mesh axis.

Net-new beyond the reference (SURVEY.md §5: long-context "entirely absent"),
first-class here per the TPU design brief. Each of the N ``sp`` ranks holds a
sequence shard of Q/K/V; K/V shards rotate around the ring via
``lax.ppermute`` (ICI neighbor hops) for N steps while each rank accumulates
online-softmax partial results for its local queries — attention over the
full sequence with O(T/N) activation memory per chip and communication
overlapped across steps.

Must run inside ``shard_map`` with the ``sp`` axis bound (the
SequenceParallelStrategy does this); called with no axis bound it falls back
to plain attention, so models can enable ``attention_impl='ring'``
unconditionally.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ray_lightning_tpu.ops.attention import dot_product_attention
from ray_lightning_tpu.ops.flash_attention import (_BIG_NEG, _block_update,
                                                   _finalize)

SP_AXIS_NAME = "sp"


def ring_attention(q: jax.Array,
                   k: jax.Array,
                   v: jax.Array,
                   *,
                   causal: bool = False,
                   mask: Optional[jax.Array] = None,
                   dropout_rate: float = 0.0,
                   dropout_rng: Optional[jax.Array] = None,
                   axis_name: str = SP_AXIS_NAME,
                   softmax_dtype=jnp.float32) -> jax.Array:
    """Sequence-parallel attention. Local shapes (B, T_local, H, D).

    Sequence positions are assumed contiguous per rank (rank r owns
    ``[r*T_local, (r+1)*T_local)``), which is how the batch sharding lays
    out a ``P(..., 'sp', ...)`` sequence dim.
    """
    del softmax_dtype
    try:
        my_rank = jax.lax.axis_index(axis_name)
        n = jax.lax.axis_size(axis_name)
    except NameError:
        return dot_product_attention(
            q, k, v, causal=causal, mask=mask, dropout_rate=dropout_rate,
            dropout_rng=dropout_rng)
    if mask is not None or (dropout_rate > 0.0 and dropout_rng is not None):
        raise NotImplementedError(
            "ring_attention supports causal/full attention without "
            "attention-dropout or custom masks; use attention_impl='dot' "
            "for those.")

    B, T_local, H, D = q.shape
    scale = D ** -0.5
    total = n * T_local
    qpos = my_rank * T_local + jnp.arange(T_local)

    perm = [(r, (r + 1) % n) for r in range(n)]

    def step(carry, t):
        m, l, acc, kv = carry
        kj, vj = kv
        # at step t we hold the shard originally owned by rank (my - t) % n
        src = jax.lax.rem(my_rank - t + n, n)
        kpos = src * T_local + jnp.arange(T_local)
        m, l, acc = _block_update((m, l, acc), q, kj, vj, qpos, kpos,
                                  causal, total, scale)
        # rotate kv to the next rank; overlap with the next step's compute
        kv = jax.lax.ppermute((kj, vj), axis_name, perm)
        return (m, l, acc, kv), None

    init = (jnp.full((B, H, T_local), _BIG_NEG, jnp.float32),
            jnp.zeros((B, H, T_local), jnp.float32),
            jnp.zeros((B, T_local, H, D), jnp.float32),
            (k, v))
    (m, l, acc, _), _ = jax.lax.scan(step, init, jnp.arange(n))
    return _finalize(l, acc, q.dtype)
