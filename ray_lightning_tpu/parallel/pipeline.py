"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pp`` axis.

Net-new beyond the reference (SURVEY.md §2.3: PP absent upstream). The
``pp`` mesh axis splits the *layer* dimension: stage ``s`` owns layers
``[s·L/S, (s+1)·L/S)``. :func:`pipeline_apply` runs the classic
collective-permute schedule inside ``shard_map``:

- the loop runs ``M + S - 1`` ticks for ``M`` microbatches over ``S``
  stages; at each tick every stage applies its layer block to the
  activation it holds, then the activations rotate one hop along the ring
  (``lax.ppermute``) — stage 0 injects microbatch ``t``, the last stage
  retires microbatch ``t - (S-1)``;
- the schedule is a ``lax.scan``, so **jax autodiff derives the pipelined
  backward automatically** (the transpose of ppermute is the reverse hop;
  the backward bubble mirrors the forward one);
- warm-up/drain ticks compute on garbage activations (static shapes — the
  TPU way); their outputs are masked out of the result and, because the
  output selects only retired ticks, autodiff sends exactly zero cotangent
  back through them.

The bubble fraction is ``(S-1)/(M+S-1)`` — pick ``M ≫ S``. Communication
is one activation-sized neighbor hop per tick, riding ICI.

This is the building block: it is pure jax (params in, activations out), so
it slots under any step built with ``shard_map`` — see
``tests/test_pipeline.py`` for a full pipelined training step (loss +
grads + psum across dp×pp) driven this way.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

PP_AXIS_NAME = "pp"


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any,
                   microbatches: jax.Array,
                   *,
                   axis_name: str = PP_AXIS_NAME) -> jax.Array:
    """Run ``microbatches`` through an ``S``-stage pipeline.

    Must be called inside ``shard_map`` with ``axis_name`` bound.

    Args:
        stage_fn: ``(stage_params, x) -> y`` applying THIS stage's layer
            block; ``y`` must have ``x``'s shape (residual-style stacks).
        stage_params: this stage's parameters (already pp-sharded by the
            caller's in_specs).
        microbatches: ``(M, mb, ...)`` — the full microbatched input,
            replicated across stages (only stage 0 reads it).

    Returns:
        ``(M, mb, ...)`` outputs, replicated across the pp group (a single
        psum selects the last stage's retired activations).
    """
    stage = jax.lax.axis_index(axis_name)
    n_stages = jax.lax.axis_size(axis_name)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    total_ticks = M + n_stages - 1

    # The scan carry circulates stage outputs, so the buffers (and the
    # injected input) must share one dtype. Promote the input to the
    # params' result type up front (bf16 batches through f32 params run at
    # f32 — the bf16-mixed convention), then confirm via eval_shape.
    leaves = jax.tree_util.tree_leaves(stage_params)
    compute_dtype = jnp.result_type(
        microbatches.dtype, *[l.dtype for l in leaves]) if leaves \
        else microbatches.dtype
    microbatches = microbatches.astype(compute_dtype)
    out_aval = jax.eval_shape(
        stage_fn, stage_params,
        jax.ShapeDtypeStruct(mb_shape, compute_dtype))
    if out_aval.shape != mb_shape:
        raise ValueError(
            f"stage_fn must preserve the activation shape (pipeline "
            f"stages chain): got {out_aval.shape} from {mb_shape}")
    out_dtype = out_aval.dtype
    microbatches = microbatches.astype(out_dtype)

    # ring: stage s sends to s+1; the wrap-around link carries no live data
    perm = [(s, (s + 1) % n_stages) for s in range(n_stages)]

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 injects microbatch t (clamped during drain ticks; the
        # extra compute is masked out of `outputs` below)
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), keepdims=False)
        x = jnp.where(stage == 0, inject, recv)
        y = stage_fn(stage_params, x).astype(out_dtype)
        # last stage retires microbatch t-(S-1) at ticks t >= S-1
        out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
        live = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(live, y,
                      jax.lax.dynamic_index_in_dim(outputs, out_idx,
                                                   keepdims=False)),
            out_idx, axis=0)
        recv = jax.lax.ppermute(y, axis_name, perm)
        return (recv, outputs), None

    init = (jnp.zeros(mb_shape, out_dtype),
            jnp.zeros((M,) + mb_shape, out_dtype))
    (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(total_ticks))
    # only the last stage holds real outputs; one psum replicates them
    outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
    return jax.lax.psum(outputs, axis_name)


def split_microbatches(batch: jax.Array, n_microbatches: int) -> jax.Array:
    """``(B, ...) -> (M, B/M, ...)`` leading-dim microbatch split."""
    B = batch.shape[0]
    if B % n_microbatches != 0:
        raise ValueError(
            f"batch size {B} not divisible by {n_microbatches} microbatches")
    return batch.reshape((n_microbatches, B // n_microbatches)
                         + batch.shape[1:])
