"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pp`` axis.

Net-new beyond the reference (SURVEY.md §2.3: PP absent upstream). The
``pp`` mesh axis splits the *layer* dimension: stage ``s`` owns layers
``[s·L/S, (s+1)·L/S)``. :func:`pipeline_apply` runs the classic
collective-permute schedule inside ``shard_map``:

- the loop runs ``M + S - 1`` ticks for ``M`` microbatches over ``S``
  stages; at each tick every stage applies its layer block to the
  activation it holds, then the activations rotate one hop along the ring
  (``lax.ppermute``) — stage 0 injects microbatch ``t``, the last stage
  retires microbatch ``t - (S-1)``;
- the schedule is a ``lax.scan``, so **jax autodiff derives the pipelined
  backward automatically** (the transpose of ppermute is the reverse hop;
  the backward bubble mirrors the forward one);
- warm-up/drain ticks compute on garbage activations (static shapes — the
  TPU way); their outputs are masked out of the result and, because the
  output selects only retired ticks, autodiff sends exactly zero cotangent
  back through them.

The bubble fraction is ``(S-1)/(M+S-1)`` — pick ``M ≫ S``. Communication
is one activation-sized neighbor hop per tick, riding ICI.

This is the building block: it is pure jax (params in, activations out), so
it slots under any step built with ``shard_map`` — see
``tests/test_pipeline.py`` for a full pipelined training step (loss +
grads + psum across dp×pp) driven this way.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_lightning_tpu._compat import axis_size, shard_map

PP_AXIS_NAME = "pp"

# Mesh registered by the trainer (worker-side, at step-build time) — the
# same pattern as ring attention's sp mesh: model code nests a shard_map
# without threading the mesh through configs, so configs stay pure data
# and client-mode drivers never build a mesh.
_PP_MESH: Optional[Mesh] = None


def set_pp_mesh(mesh: Optional[Mesh]) -> None:
    global _PP_MESH
    _PP_MESH = mesh


def get_pp_mesh() -> Optional[Mesh]:
    if _PP_MESH is not None and PP_AXIS_NAME in _PP_MESH.axis_names \
            and _PP_MESH.shape[PP_AXIS_NAME] > 1:
        return _PP_MESH
    return None


def _pipeline_parallel_rule():
    from ray_lightning_tpu.parallel.sharding import leading_dim_rule
    return leading_dim_rule("blocks", PP_AXIS_NAME)


def pipeline_parallel_rule(path, leaf):
    """``MeshStrategy(param_rule=...)`` rule: stacked layer params (leading
    layers dim, path containing ``blocks``) shard over ``pp``; embeddings /
    head / norms replicate. Pairs with :func:`pipelined_stack`."""
    return _pipeline_parallel_rule()(path, leaf)


def pipelined_stack(layer_fn: Callable[[Any, jax.Array], jax.Array],
                    stacked_params: Any,
                    x: jax.Array,
                    *,
                    n_microbatches: Optional[int] = None) -> jax.Array:
    """Apply a stacked layer sequence, pipelined over a registered pp mesh.

    ``stacked_params`` leaves have a leading layers dim; ``layer_fn(p, x)``
    applies ONE layer. Without a registered pp mesh (or a too-small batch)
    this is a plain serial ``lax.scan`` — models can call it
    unconditionally, exactly like ring attention's sp entry point. With a
    mesh, layers shard over ``pp`` (use :func:`pipeline_parallel_rule` so
    the params already live there), the batch dim splits over the mesh's
    data axes, and each data group runs the GPipe schedule.
    """
    def serial(params, x):
        def body(x, p):
            return layer_fn(p, x), None
        out, _ = jax.lax.scan(body, x, params)
        return out

    mesh = get_pp_mesh()
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if mesh is None:
        return serial(stacked_params, x)
    S = mesh.shape[PP_AXIS_NAME]
    if n_layers % S != 0:
        return serial(stacked_params, x)
    from ray_lightning_tpu.parallel.sharding import data_axis_names
    data_axes = data_axis_names(mesh)
    data_size = 1
    for a in data_axes:
        data_size *= mesh.shape[a]
    B = x.shape[0]
    if n_microbatches is not None:
        M = n_microbatches
        if B % (data_size * M) != 0:
            # an explicit request that cannot be honored is a
            # misconfiguration — surface it, never silently reschedule
            raise ValueError(
                f"batch size {B} is not divisible by data_size "
                f"{data_size} x n_microbatches {M}; adjust the batch or "
                "the microbatch count")
    else:
        M = 2 * S
        if B % (data_size * M) != 0:
            M = max(1, B // data_size)
            if B % (data_size * M) != 0:
                return serial(stacked_params, x)

    def local(params, xb):
        mb = split_microbatches(xb, M)
        out = pipeline_apply(lambda p, z: serial(p, z), params, mb)
        return out.reshape(xb.shape)

    spec_x = P(data_axes if data_axes else None)
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(PP_AXIS_NAME), spec_x), out_specs=spec_x,
        check_vma=False)
    return fn(stacked_params, x)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any,
                   microbatches: jax.Array,
                   *,
                   axis_name: str = PP_AXIS_NAME) -> jax.Array:
    """Run ``microbatches`` through an ``S``-stage pipeline.

    Must be called inside ``shard_map`` with ``axis_name`` bound.

    Args:
        stage_fn: ``(stage_params, x) -> y`` applying THIS stage's layer
            block; ``y`` must have ``x``'s shape (residual-style stacks).
        stage_params: this stage's parameters (already pp-sharded by the
            caller's in_specs).
        microbatches: ``(M, mb, ...)`` — the full microbatched input,
            replicated across stages (only stage 0 reads it).

    Returns:
        ``(M, mb, ...)`` outputs, replicated across the pp group (a single
        psum selects the last stage's retired activations).
    """
    stage = jax.lax.axis_index(axis_name)
    n_stages = axis_size(axis_name)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    total_ticks = M + n_stages - 1

    # The scan carry circulates stage outputs, so the buffers (and the
    # injected input) must share one dtype. Promote the input to the
    # params' result type up front (bf16 batches through f32 params run at
    # f32 — the bf16-mixed convention), then confirm via eval_shape.
    leaves = jax.tree_util.tree_leaves(stage_params)
    compute_dtype = jnp.result_type(
        microbatches.dtype, *[l.dtype for l in leaves]) if leaves \
        else microbatches.dtype
    microbatches = microbatches.astype(compute_dtype)
    out_aval = jax.eval_shape(
        stage_fn, stage_params,
        jax.ShapeDtypeStruct(mb_shape, compute_dtype))
    if out_aval.shape != mb_shape:
        raise ValueError(
            f"stage_fn must preserve the activation shape (pipeline "
            f"stages chain): got {out_aval.shape} from {mb_shape}")
    out_dtype = out_aval.dtype
    microbatches = microbatches.astype(out_dtype)

    # ring: stage s sends to s+1; the wrap-around link carries no live data
    perm = [(s, (s + 1) % n_stages) for s in range(n_stages)]

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 injects microbatch t (clamped during drain ticks; the
        # extra compute is masked out of `outputs` below)
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), keepdims=False)
        x = jnp.where(stage == 0, inject, recv)
        y = stage_fn(stage_params, x).astype(out_dtype)
        # last stage retires microbatch t-(S-1) at ticks t >= S-1
        out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
        live = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(live, y,
                      jax.lax.dynamic_index_in_dim(outputs, out_idx,
                                                   keepdims=False)),
            out_idx, axis=0)
        recv = jax.lax.ppermute(y, axis_name, perm)
        return (recv, outputs), None

    init = (jnp.zeros(mb_shape, out_dtype),
            jnp.zeros((M,) + mb_shape, out_dtype))
    (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(total_ticks))
    # only the last stage holds real outputs; one psum replicates them
    outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
    return jax.lax.psum(outputs, axis_name)


def split_microbatches(batch: jax.Array, n_microbatches: int) -> jax.Array:
    """``(B, ...) -> (M, B/M, ...)`` leading-dim microbatch split."""
    B = batch.shape[0]
    if B % n_microbatches != 0:
        raise ValueError(
            f"batch size {B} not divisible by {n_microbatches} microbatches")
    return batch.reshape((n_microbatches, B // n_microbatches)
                         + batch.shape[1:])
