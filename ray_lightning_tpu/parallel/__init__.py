from ray_lightning_tpu.parallel.mesh import (MeshSpec, build_mesh,
                                             DP_AXIS, FSDP_AXIS, TP_AXIS,
                                             SP_AXIS, PP_AXIS, EP_AXIS)
from ray_lightning_tpu.parallel.sharding import (replicated, batch_sharding,
                                                 compose_rules,
                                                 shard_pytree_along_axis,
                                                 largest_divisible_dim,
                                                 put_global_batch,
                                                 put_host_local_batch)
from ray_lightning_tpu.parallel.pipeline import (pipeline_apply,
                                                 pipeline_parallel_rule,
                                                 pipelined_stack,
                                                 split_microbatches)

__all__ = [
    "MeshSpec", "build_mesh", "DP_AXIS", "FSDP_AXIS", "TP_AXIS", "SP_AXIS",
    "PP_AXIS", "EP_AXIS", "replicated", "batch_sharding",
    "compose_rules", "shard_pytree_along_axis", "largest_divisible_dim",
    "put_global_batch",
    "put_host_local_batch", "pipeline_apply", "pipeline_parallel_rule",
    "pipelined_stack", "split_microbatches"
]
