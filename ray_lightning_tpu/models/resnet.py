"""ResNet family (BASELINE.json configs: ResNet-18/CIFAR-10 DDP,
ResNet-50 + Tune PBT).

Flax implementation with BatchNorm — exercises the trainer's mutable
``model_state`` (``batch_stats``) path end-to-end. NHWC layout (TPU-native
conv layout); f32 by default, pass ``dtype=jnp.bfloat16`` to ``ResNetModule``
for MXU-rate bf16 compute (params and batch stats stay f32 either way).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import optax

from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.data.loader import ArrayDataset, DataLoader
from ray_lightning_tpu.data.synthetic import synthetic_images


class ResNetBlock(nn.Module):
    filters: int
    conv: Any
    norm: Any
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    conv: Any
    norm: Any
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Any
    num_classes: int = 10
    num_filters: int = 64
    dtype: Any = jnp.float32
    small_images: bool = True  # CIFAR-style stem (3x3, no max-pool)

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        if self.small_images:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2 ** i, conv=conv, norm=norm,
                    strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def resnet10(num_classes=10, **kw):
    """One block per stage — the CI/debug tier: same stem, BatchNorm
    and residual topology as resnet18 at half the trace/compile cost."""
    return ResNet([1, 1, 1, 1], ResNetBlock, num_classes=num_classes, **kw)


def resnet18(num_classes=10, **kw):
    return ResNet([2, 2, 2, 2], ResNetBlock, num_classes=num_classes, **kw)


def resnet50(num_classes=10, **kw):
    return ResNet([3, 4, 6, 3], BottleneckBlock, num_classes=num_classes,
                  **kw)


class ResNetModule(TpuModule):
    """CIFAR-10-style classification with BatchNorm state updates."""

    def __init__(self,
                 depth: int = 18,
                 num_classes: int = 10,
                 batch_size: int = 32,
                 image_size: int = 32,
                 num_samples: int = 512,
                 lr: float = 0.1,
                 momentum: float = 0.9,
                 dtype: Any = jnp.float32,
                 config: Optional[dict] = None):
        super().__init__()
        config = config or {}
        self.depth = depth
        self.num_classes = num_classes
        self.batch_size = int(config.get("batch_size", batch_size))
        self.image_size = image_size
        self.num_samples = num_samples
        self.lr = config.get("lr", lr)
        self.momentum = config.get("momentum", momentum)
        self.dtype = dtype

    def configure_model(self):
        factory = {10: resnet10, 18: resnet18, 50: resnet50}[self.depth]
        return factory(self.num_classes, dtype=self.dtype)

    def configure_optimizers(self):
        return optax.sgd(self.lr, momentum=self.momentum, nesterov=True)

    def _loader(self, seed: int, shuffle: bool = False):
        x, y = synthetic_images(self.num_samples, self.num_classes,
                                self.image_size, seed=seed)
        return DataLoader(ArrayDataset((x, y)), batch_size=self.batch_size,
                          shuffle=shuffle)

    def train_dataloader(self):
        return self._loader(0, shuffle=True)

    def val_dataloader(self):
        return self._loader(1)

    def test_dataloader(self):
        return self._loader(2)

    def init_variables(self, model, rng, batch):
        return model.init(rng, batch[0], train=False)

    def training_step(self, model, variables, batch, rng):
        x, y = batch
        logits, mutated = model.apply(variables, x, train=True,
                                      mutable=["batch_stats"])
        loss = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, y))
        acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        self.log("train_acc", acc)
        return loss, {}, mutated

    def validation_step(self, model, variables, batch, rng):
        x, y = batch
        logits = model.apply(variables, x, train=False)
        loss = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, y))
        acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        return {"val_loss": loss, "val_acc": acc}

    def test_step(self, model, variables, batch, rng):
        x, y = batch
        logits = model.apply(variables, x, train=False)
        acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        return {"acc": acc}
