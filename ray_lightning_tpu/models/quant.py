"""Weight-only int8/int4 quantization: cut the decode param stream.

Decode is parameter-bandwidth-bound: every target pass streams the full
parameter set once (the bench's param-bandwidth honesty floor measures
exactly this), so at-rest weight bytes ARE per-token bytes. This module
shrinks them with **storage-only** quantization — the same contract as
the int8 KV arena (``serve/pages.py``): weights live in HBM as integer
codes + f32 scales and are dequantized back to their original dtype on
the way into every compiled program, so compute stays at ``cfg.dtype``
and the model math is unchanged up to one bounded rounding of each
weight.

Two formats, both absmax-scaled (symmetric, no zero points — the extra
code of asymmetric schemes buys little on weight distributions centered
at 0, and symmetric keeps dequant one fused multiply):

- ``"int8"`` — per-output-channel: one f32 scale per slice along the
  leaf's LAST axis (the output-features axis of every kernel in this
  model family: ``(in, out)`` Dense kernels, the ``(d_model, 3, H, Dh)``
  qkv kernel's head_dim, embedding columns). Error per weight is
  bounded by half a quantization step of its channel's absmax:
  ``|deq - w| <= amax / 254``.
- ``"int4"`` — group-wise: the last axis is cut into ``group_size``
  element groups, each with its own f32 scale (codes in [-7, 7], so
  ``|deq - w| <= group_amax / 14``); two codes pack into one int8
  (low nibble first), halving storage again. Per-channel scaling is
  too coarse at 4 bits — group-wise is the standard remedy (GPTQ/AWQ
  lineage).

Quantized leaves are :class:`QTensor` pytree nodes — codes and scales
are the children, so a quantized tree flows through ``jax.jit``
boundaries, donation and ``tree_map`` exactly like a plain one, and the
(bits, group_size, shape, dtype) metadata rides in the static aux data
(hashable: re-quantized trees hit the same compiled programs).
:func:`materialize_for_program` is the one program-entry guard every
serve/generate program calls (see ``models/generate.py``) — a no-op on
plain trees, a once-per-dispatch :func:`dequantize_params` under
``matmul_kernel="xla"``, a pass-through of the codes under
``matmul_kernel="pallas"`` (the fused dequant-matmul kernel,
``models/pallas_matmul.py``, then consumes them in place): callers
never need to know whether the params they hold are quantized.

Eligibility: floating-point leaves with ``ndim >= 2`` (matmul kernels
and embedding tables — together >99% of a transformer's bytes). Biases
and LayerNorm vectors stay at their original dtype: they are O(d) of
the stream and their precision is disproportionately load-bearing.

:func:`param_bytes` is the exact at-rest byte accounting for either
representation, computed from shapes/dtypes only (works on
``jax.eval_shape`` outputs — pure accounting callers never allocate),
and is what the bench's equal-byte comparisons and param-bandwidth
honesty floor are required to cite instead of dtype arithmetic.

KV-cache quantization (:func:`kv_scales` / :func:`kv_quantize` /
:func:`kv_dequantize`) lives here too: it is the same absmax machinery
applied to cache leaves, and the serve layer (``serve/pages.py``)
re-exports it — models must not depend on serve.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["QTensor", "quantize_params", "dequantize_params",
           "is_quantized", "param_bytes", "check_weight_dtype",
           "pack_int4", "unpack_int4", "kv_scales", "kv_quantize",
           "kv_dequantize", "matmul_view", "materialize_for_program"]

#: default int4 group length along the last axis — 64 divides every
#: features dim in this model family (head_dim, d_model, d_ff, the
#: 64-padded vocab) and keeps the scale tax at one f32 per 32 packed
#: bytes (~6%)
DEFAULT_GROUP_SIZE = 64


def check_weight_dtype(weight_dtype) -> bool:
    """Normalize/validate a ``weight_dtype`` option; returns True for
    the quantized paths (mirrors ``check_kv_dtype``)."""
    if weight_dtype is None:
        return False
    if weight_dtype in ("int8", "int4"):
        return True
    raise ValueError(
        f"weight_dtype must be None, 'int8' or 'int4', got "
        f"{weight_dtype!r}")


# ------------------------------------------------------------ kv helpers
# absmax quantization shared by the KV arena (serve/pages.py re-exports
# these — the serve layer depends on models, never the reverse)

def kv_scales(values: jax.Array, reduce_axes: Tuple[int, ...]) -> jax.Array:
    """Absmax scales over ``reduce_axes`` (keepdims), guarded so an
    all-zero group dequantizes to exact zeros instead of NaN."""
    amax = jnp.max(jnp.abs(values.astype(jnp.float32)), axis=reduce_axes,
                   keepdims=True)
    return jnp.where(amax > 0, amax / 127.0, 1.0)


def kv_quantize(values: jax.Array, scales: jax.Array) -> jax.Array:
    return jnp.clip(jnp.round(values.astype(jnp.float32) / scales),
                    -127, 127).astype(jnp.int8)


def kv_dequantize(q: jax.Array, scales: jax.Array, dtype) -> jax.Array:
    return (q.astype(jnp.float32) * scales).astype(dtype)


# ---------------------------------------------------------- int4 packing
def pack_int4(codes: jax.Array) -> jax.Array:
    """Pack int4 codes (int8 values in [-8, 7], even-length last axis)
    two nibbles per int8 — low nibble first: ``packed[..., i]`` holds
    ``codes[..., 2i]`` (low) and ``codes[..., 2i+1]`` (high)."""
    lo = codes[..., 0::2]
    hi = codes[..., 1::2]
    return ((lo & 0x0F) | (hi << 4)).astype(jnp.int8)


def unpack_int4(packed: jax.Array) -> jax.Array:
    """Inverse of :func:`pack_int4`: sign-extend both nibbles and
    re-interleave to the doubled last axis."""
    lo = jnp.right_shift(jnp.left_shift(packed, 4), 4)  # arithmetic
    hi = jnp.right_shift(packed, 4)
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(*packed.shape[:-1], 2 * packed.shape[-1])


# ---------------------------------------------------------------- QTensor
@jax.tree_util.register_pytree_node_class
class QTensor:
    """One quantized weight leaf: integer codes + f32 scales.

    ``bits == 8``: ``q`` has the original shape (int8 codes), ``scale``
    is per-output-channel (all-but-last axes reduced, keepdims).
    ``bits == 4``: ``q`` is nibble-packed — original shape with the last
    axis halved — and ``scale`` is ``(..., last/group_size, 1)`` over
    the grouped view. ``shape``/``dtype`` record the original leaf so
    :meth:`dequantize` is exact-shape and byte accounting stays honest.
    """

    __slots__ = ("q", "scale", "bits", "group_size", "shape", "dtype")

    def __init__(self, q, scale, bits: int, group_size: Optional[int],
                 shape: Tuple[int, ...], dtype):
        self.q = q
        self.scale = scale
        self.bits = bits
        self.group_size = group_size
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)

    def tree_flatten(self):
        return ((self.q, self.scale),
                (self.bits, self.group_size, self.shape, self.dtype))

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        bits, group_size, shape, dtype = aux
        return cls(q, scale, bits, group_size, shape, dtype)

    @property
    def nbytes(self) -> int:
        """Exact at-rest bytes (codes + scales) from shapes alone —
        valid on concrete arrays and ``ShapeDtypeStruct``\\ s alike."""
        return (int(np.prod(self.q.shape)) *
                np.dtype(self.q.dtype).itemsize
                + int(np.prod(self.scale.shape)) *
                np.dtype(self.scale.dtype).itemsize)

    def dequantize(self) -> jax.Array:
        """Codes x scales -> the original-dtype weight (one bounded
        rounding away from the value that was quantized)."""
        if self.bits == 8:
            w = self.q.astype(jnp.float32) * self.scale
            return w.astype(self.dtype)
        codes = unpack_int4(self.q).astype(jnp.float32)
        grouped = codes.reshape(*self.shape[:-1], -1, self.group_size)
        w = grouped * self.scale
        return w.reshape(self.shape).astype(self.dtype)

    def __repr__(self):
        return (f"QTensor(int{self.bits}, shape={self.shape}, "
                f"group_size={self.group_size})")


def _is_qtensor(x) -> bool:
    return isinstance(x, QTensor)


def _quantize_leaf_int8(w) -> QTensor:
    wf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=tuple(range(wf.ndim - 1)),
                   keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q, scale, 8, None, w.shape, w.dtype)


def _quantize_leaf_int4(w, group_size: int) -> QTensor:
    last = w.shape[-1]
    if last % group_size:
        raise ValueError(
            f"group_size ({group_size}) must divide every quantized "
            f"leaf's last axis — got a {tuple(w.shape)} leaf "
            f"({last} % {group_size} != 0); pick a group_size that "
            "divides the model's feature dims")
    wf = jnp.asarray(w).astype(jnp.float32)
    grouped = wf.reshape(*w.shape[:-1], last // group_size, group_size)
    amax = jnp.max(jnp.abs(grouped), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 7.0, 1.0)
    codes = jnp.clip(jnp.round(grouped / scale), -7, 7).astype(jnp.int8)
    packed = pack_int4(codes.reshape(w.shape))
    return QTensor(packed, scale, 4, group_size, w.shape, w.dtype)


def quantize_params(params, weight_dtype: str = "int8",
                    group_size: Optional[int] = None):
    """Quantize every eligible leaf of ``params`` (floating, ndim >= 2)
    to :class:`QTensor` storage. ``group_size`` applies to the int4
    grouped scales (default :data:`DEFAULT_GROUP_SIZE`); int8 is
    per-output-channel and refuses an explicit group_size (nothing
    would consume it — a silently-ignored knob is a bug magnet).

    Deterministic and pure: re-quantizing the same params produces
    bit-identical codes/scales, which is what makes crash-rebuilt
    engines (``ServeSupervisor`` re-quantizes from the raw params it
    holds) token-identical to the uninterrupted run.
    """
    if not check_weight_dtype(weight_dtype):
        raise ValueError(
            "quantize_params needs weight_dtype='int8' or 'int4' "
            "(None means no quantization — don't call it)")
    if weight_dtype == "int8":
        if group_size is not None:
            raise ValueError(
                "group_size is an int4 option (int8 scales are "
                "per-output-channel); drop it or use weight_dtype='int4'")
    else:
        group_size = (DEFAULT_GROUP_SIZE if group_size is None
                      else group_size)
        if group_size < 2 or group_size % 2:
            raise ValueError(
                f"int4 group_size must be an even integer >= 2 (two "
                f"codes pack per byte inside each group), got "
                f"{group_size}")
    if is_quantized(params):
        raise ValueError(
            "params are already quantized — quantizing codes would "
            "silently destroy the weights; pass the original params")

    def q_leaf(path, leaf):
        name = str(getattr(path[-1], "key", getattr(path[-1], "name",
                                                    path[-1]))) \
            if path else ""
        # biases stay full precision even when ndim >= 2 (the
        # DenseGeneral qkv bias is (3, H, Dh)): O(d) of the stream,
        # disproportionately precision-load-bearing. LoRA adapter
        # banks (models/lora.py) stay full precision too: rank-r
        # deltas are O(r*d) of the stream and hot load/unload writes
        # per-slot slices in place — quantized codes would round every
        # co-resident adapter on each install
        if (name == "bias" or name.startswith("lora_")
                or not hasattr(leaf, "ndim") or leaf.ndim < 2
                or not jnp.issubdtype(leaf.dtype, jnp.floating)):
            return leaf
        if weight_dtype == "int8":
            return _quantize_leaf_int8(leaf)
        return _quantize_leaf_int4(leaf, group_size)

    return jax.tree_util.tree_map_with_path(q_leaf, params)


def is_quantized(params) -> bool:
    """True when any leaf of ``params`` is a :class:`QTensor`."""
    return any(_is_qtensor(leaf) for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=_is_qtensor))


def dequantize_params(params):
    """Materialize original-dtype weights from a quantized tree; the
    identity on plain trees. Every serve/generate program calls this at
    its entry (a trace-time no-op when nothing is quantized), so the
    dequant happens ONCE per dispatch, outside the step scans — XLA
    sees int8/int4 codes stream from HBM and the dequantized tree as
    dispatch-scoped scratch."""
    if not is_quantized(params):
        return params
    return jax.tree_util.tree_map(
        lambda leaf: leaf.dequantize() if _is_qtensor(leaf) else leaf,
        params, is_leaf=_is_qtensor)


def matmul_view(qt: "QTensor", transpose: bool = False):
    """Kernel-input views of one quantized leaf for the fused
    dequant-matmul kernel (``models/pallas_matmul.py``): the stored
    codes and scales reshaped to the 2-D tile-friendly layout the
    kernel's BlockSpecs slice, WITHOUT materializing any dequantized
    weight (reshapes of the at-rest arrays, plus — int8 dense
    orientation only — an ``N``-float tile of the per-channel scale
    vector, negligible next to the codes).

    Two orientations, matching the two ways this model family consumes
    a weight leaf:

    - ``transpose=False`` (Dense/DenseGeneral kernels, stored
      ``(K, *features)``): contraction runs over axis 0, the output
      axes flatten to ``N``. Codes view ``(K, N)`` int8 (int4:
      ``(K, N/2)`` packed — nibble pairs flatten contiguously because
      ``group_size`` divides the stored last axis). Scales: int8
      per-output-channel expands to a ``(1, N)`` per-column vector
      (the stored scale repeats per leading output index — exact, no
      arithmetic); int4 group scales view as ``(K, N/group_size)``
      where flattened column ``n`` belongs to group ``n //
      group_size``.
    - ``transpose=True`` (the tied LM head: ``wte.attend`` contracts
      ``x @ E.T`` over the EMBEDDING's last axis): codes view
      ``(N, K)`` (int4: ``(N, K/2)``), int8 scales ``(1, K)`` (they
      ride the contraction axis — the kernel dequantizes element-wise
      before the dot, never folds scales into activations, which is
      what keeps it bitwise the dequantize-then-matmul path), int4
      scales ``(N, K/group_size)``.

    Returns ``(codes2d, scales2d, K, N)``.
    """
    shape = qt.shape
    if transpose:
        K = shape[-1]
        N = int(np.prod(shape[:-1], dtype=np.int64))
        if qt.bits == 8:
            return qt.q.reshape(N, K), qt.scale.reshape(1, K), K, N
        return (qt.q.reshape(N, K // 2),
                qt.scale.reshape(N, K // qt.group_size), K, N)
    K = shape[0]
    N = int(np.prod(shape[1:], dtype=np.int64))
    if qt.bits == 8:
        last = shape[-1]
        scales = jnp.tile(qt.scale.reshape(1, last), (1, N // last))
        return qt.q.reshape(K, N), scales, K, N
    return (qt.q.reshape(K, N // 2),
            qt.scale.reshape(K, N // qt.group_size), K, N)


def materialize_for_program(params, cfg=None):
    """The ONE shared program-entry guard every serve/generate program
    calls on its params (the single seam the entry points cannot drift
    from): a trace-time no-op on plain trees; on weight-quantized trees
    it is **kernel-aware**:

    - ``cfg.matmul_kernel == "xla"`` (or no cfg): materialize the
      original-dtype weights once per dispatch, outside the step scans
      (:func:`dequantize_params` — the PR 11 behavior: codes stream
      from HBM, the dequantized tree is dispatch-scoped scratch).
    - ``cfg.matmul_kernel == "pallas"``: the codes/scales flow through
      the jit boundary AS the param leaves (``QTensor`` is a
      registered pytree) and every consuming layer dispatches the
      fused dequant-matmul kernel — no dense dequantized weight arena
      exists anywhere, so the per-dispatch param byte stream is the
      codes+scales floor :func:`param_bytes` accounts.

    ``cfg`` is the consuming model's ``TransformerConfig`` (callers
    pass ``model.cfg``); model families without a ``matmul_kernel``
    field always materialize.
    """
    if not is_quantized(params):
        return params
    if cfg is not None and getattr(cfg, "matmul_kernel", "xla") == "pallas":
        if getattr(cfg, "scan_layers", False):
            raise ValueError(
                "matmul_kernel='pallas' cannot run quantized weights "
                "through scanned layers: nn.scan slices every param "
                "leaf along the layer axis, and a QTensor's broadcast-"
                "shaped scales have no such axis. Serving wants "
                "scan_layers=False anyway (docs/performance.md decode "
                "section) — unstack_scan_params the weights first")
        return params
    return dequantize_params(params)


def param_bytes(params) -> int:
    """Exact at-rest parameter bytes for a plain OR quantized tree,
    from shapes/dtypes only (no device reads — pass ``jax.eval_shape``
    structs for configs that were never materialized). This is the
    number the bench's param-bandwidth honesty floor and equal-byte
    comparisons must cite: dtype arithmetic (``2 * n_params``) goes
    stale the moment storage and compute dtypes diverge."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params, is_leaf=_is_qtensor):
        if _is_qtensor(leaf):
            total += leaf.nbytes
        else:
            total += (int(np.prod(np.asarray(leaf.shape, np.int64)))
                      * np.dtype(leaf.dtype).itemsize
                      if hasattr(leaf, "shape") else 0)
    return int(total)
