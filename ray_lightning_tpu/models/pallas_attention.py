"""Pallas paged-attention kernel: fused page gather + in-kernel int8
dequant + tiled softmax on the decode/verify hot path.

This is the hand-tiled half of the paged serving story
(``docs/serving.md``): PR 11's page-native attention already reads and
writes K/V through the page table in pure XLA, but that path still
materializes page-sized score/output temporaries between HLO ops, and
int8 KV codes are dequantized into compute-dtype blocks the compiler
schedules as ordinary tensors. This kernel does the whole read side of
cached attention in ONE ``pallas_call`` per layer, in the mold of
PagedAttention (Kwon et al. 2023) with FlashAttention-style tiling
(Dao et al. 2022):

- **page-table-indexed block loads** — the page table is a
  scalar-prefetch operand (``PrefetchScalarGridSpec``), so each grid
  step's ``BlockSpec`` index map picks the ARENA page to stream into
  VMEM directly from the table (unmapped −1 entries clamp to page 0,
  the same finite-junk-the-mask-never-admits argument as the XLA
  paths). Only occupied pages are ever touched; nothing shaped like
  ``num_slots x max_seq_len`` exists anywhere.
- **in-kernel int8 dequant** — int8 arenas stream CODES (int8) and
  per-page-per-head scales (f32) through the block pipeline; the
  ``codes x scales`` multiply happens on the (page_size, H, D) VMEM
  block right before the dot. No dense dequantized K/V arena is ever
  materialized — the only full-precision K/V in existence is one
  page's worth of VMEM scratch per grid step.
- **tiled softmax, f32 accumulators** — scores are computed blockwise
  per page column (MXU dots with ``preferred_element_type=f32``) into
  a VMEM-resident ``(H, T, max_seq_len)`` logits tile with the per-row
  block-causal mask (``key_pos <= kv_positions[row, q]``) fused into
  the same step; the softmax then runs ONCE, exactly, over the
  completed tile (grid phase 2), and the output accumulates blockwise
  over V page columns in f32. Exact softmax — not the online
  approximation — is deliberate: it keeps the kernel's math
  term-for-term identical to the XLA page-native path, which is what
  lets the serve tests ENFORCE greedy token identity rather than fall
  back to an agreement gate (see ``docs/serving.md`` for which config
  gets which contract).

Grid: ``(B, 2 * pages_per_slot)`` with the page axis innermost and
sequential — steps ``0..pp-1`` score K pages, steps ``pp..2pp-1``
accumulate V pages (the softmax fires on the first output step). The
logits tile and the ``(H, T, D)`` accumulator live in VMEM scratch and
persist across the inner grid, exactly the scheme
``ops/pallas_flash.py`` uses. VMEM cost per slot is
``H * T * max_seq_len`` f32 for the tile (a few hundred KB at serving
shapes) — far under the ~16 MB budget.

On hosts without a TPU the kernel runs under **pallas interpret mode**
(the same lowering, executed by XLA CPU), which is how the CPU tier-1
suite pins token identity; wall-clock there is honestly worse than the
XLA path (interpretation tax), the byte floor is the claim
(``bench.py`` ``extras["serve"]["pallas"]``, ``docs/performance.md``
round 12).

Engines select this path with ``ServeEngine(...,
attention_kernel="pallas")`` on top of ``page_native=True`` — see
``MultiHeadAttention._page_native_attention`` for the call site (the
write half stays in XLA: T tokens' K/V land in their owning pages
through the page table before the kernel reads).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_attention", "interpret_default"]

_BIG_NEG = float(jnp.finfo(jnp.float32).min)


def interpret_default() -> bool:
    """Run the kernel in pallas interpret mode off-TPU (the CPU tier-1
    correctness path); compile it for real on TPU backends."""
    return jax.default_backend() != "tpu"


def _kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
            logits_ref, acc_ref, *, page_size: int, pages_per_slot: int,
            scale: float, compute_dtype):
    """One grid step; see the module docstring for the two-phase plan.

    ``ks_ref``/``vs_ref`` are None on full-precision arenas (the plain
    wrapper below drops them from the signature — pallas passes refs
    positionally).
    """
    j = pl.program_id(1)
    pp = pages_per_slot
    ps = page_size
    T = q_ref.shape[1]

    def load(ref, sref):
        blk = ref[0]                                     # (ps, H, D)
        if sref is None:
            return blk
        # kv_dequantize, blockwise: codes (int8) x per-page-per-head
        # f32 scales -> compute dtype, on VMEM scratch only
        return (blk.astype(jnp.float32) * sref[0]).astype(compute_dtype)

    @pl.when(j < pp)
    def _scores():
        kb = load(k_ref, ks_ref)
        qb = q_ref[0]                                    # (T, H, D)
        s = jax.lax.dot_general(
            qb, kb, (((2,), (2,)), ((1,), (1,))),
            preferred_element_type=jnp.float32)          # (H, T, ps)
        s = s * scale
        # per-row block-causal mask fused into the score step: page j
        # covers absolute positions j*ps .. j*ps+ps-1
        kpos = j * ps + jax.lax.broadcasted_iota(jnp.int32, (T, ps), 1)
        pos = pos_ref[0]                                 # (T,)
        bias = jnp.where(kpos <= pos[:, None], 0.0, _BIG_NEG)
        logits_ref[:, :, pl.ds(j * ps, ps)] = s + bias[None]

    @pl.when(j == pp)
    def _softmax():
        # the tile is complete: ONE exact f32 softmax over every key
        # position, term-for-term the XLA page-native path's
        # jax.nn.softmax — weights overwrite the tile in place
        lg = logits_ref[:]                               # (H, T, S)
        w = jax.nn.softmax(lg, axis=-1)
        all_masked = jnp.all(lg <= _BIG_NEG * 0.5, axis=-1, keepdims=True)
        logits_ref[:] = jnp.where(all_masked, 0.0, w)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(j >= pp)
    def _accumulate():
        jj = j - pp
        vb = load(v_ref, vs_ref)
        wb = logits_ref[:, :, pl.ds(jj * ps, ps)]        # (H, T, ps) f32
        acc_ref[:] += jax.lax.dot_general(
            wb.astype(compute_dtype), vb, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)          # (H, T, D)

    @pl.when(j == 2 * pp - 1)
    def _emit():
        o_ref[0] = jnp.moveaxis(acc_ref[:], 0, 1).astype(o_ref.dtype)


def _kernel_plain(pt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref, logits_ref,
                  acc_ref, **kw):
    _kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, None, None, o_ref,
            logits_ref, acc_ref, **kw)


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    k_scales: Optional[jax.Array],
                    v_scales: Optional[jax.Array],
                    kv_positions: jax.Array, page_table: jax.Array, *,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Cached paged attention for one layer's decode/verify read side.

    - ``q`` (B, T, H, D) — T = 1 (decode step) or k+1 (spec verify).
    - ``k_pages``/``v_pages`` (num_pages, page_size, H, D) — the arena
      leaves (int8 codes when quantized; the block's own T tokens must
      already be written — the caller's write half runs first).
    - ``k_scales``/``v_scales`` (num_pages, 1, H, 1) f32 per-page
      absmax scales, or None for full-precision arenas.
    - ``kv_positions`` (B, T) — each row's absolute positions (the mask
      admits ``key <= kv_positions[row, t]``, block-causal).
    - ``page_table`` (B, pages_per_slot) int32, −1 = unmapped (reads
      clamp to page 0; the mask never admits a position without a
      mapped page on any row whose output is consumed).

    Returns (B, T, H, D) in ``q.dtype``, matching the XLA page-native
    path's output bit-for-bit up to per-block dot scheduling.
    """
    B, T, H, D = q.shape
    ps = k_pages.shape[1]
    pp = page_table.shape[1]
    quantized = k_scales is not None
    if interpret is None:
        interpret = interpret_default()

    page_table = page_table.astype(jnp.int32)
    kv_positions = kv_positions.astype(jnp.int32)

    def q_map(b, j, pt):
        return (b, 0, 0, 0)

    def pos_map(b, j, pt):
        return (b, 0)

    # K streams pages during the score phase and parks on its last page
    # through the output phase (an unchanged block index is not
    # re-fetched); V parks on the first output page through the score
    # phase — each occupied page crosses HBM→VMEM once per pass.
    def k_map(b, j, pt):
        col = jnp.minimum(j, pp - 1)
        return (jnp.maximum(pt[b, col], 0), 0, 0, 0)

    def v_map(b, j, pt):
        col = jnp.maximum(j - pp, 0)
        return (jnp.maximum(pt[b, col], 0), 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, T), pos_map),
        pl.BlockSpec((1, T, H, D), q_map),
        pl.BlockSpec((1, ps, H, D), k_map),
        pl.BlockSpec((1, ps, H, D), v_map),
    ]
    operands = [kv_positions, q, k_pages, v_pages]
    if quantized:
        in_specs += [pl.BlockSpec((1, 1, H, 1), k_map),
                     pl.BlockSpec((1, 1, H, 1), v_map)]
        operands += [k_scales, v_scales]
        kernel = _kernel
    else:
        kernel = _kernel_plain
    kernel = functools.partial(
        kernel, page_size=ps, pages_per_slot=pp, scale=D ** -0.5,
        compute_dtype=q.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, 2 * pp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, T, H, D), q_map),
        scratch_shapes=[
            pltpu.VMEM((H, T, pp * ps), jnp.float32),   # logits tile
            pltpu.VMEM((H, T, D), jnp.float32),         # f32 accumulator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, T, H, D), q.dtype),
        interpret=interpret,
    )(page_table, *operands)
