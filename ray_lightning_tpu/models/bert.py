"""BERT-base fine-tune module (BASELINE.json: "BERT-base fine-tune,
RayStrategy multi-host (v4-32, 4 Ray actors)").

Sequence-classification head over the shared bidirectional encoder; synthetic
token data with class-dependent token distributions so fine-tuning is
learnable in tests.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.data.loader import ArrayDataset, DataLoader
from ray_lightning_tpu.models.transformer import (TransformerConfig,
                                                  TransformerEncoder)


def bert_config(size: str = "base", vocab_size: int = 30522,
                max_seq_len: int = 512, **overrides) -> TransformerConfig:
    sizes = {
        "tiny": (2, 128, 2),
        "base": (12, 768, 12),    # 110M
        "large": (24, 1024, 16),  # 340M
    }
    n_layers, d_model, n_heads = sizes[size]
    base = dict(vocab_size=vocab_size, max_seq_len=max_seq_len,
                d_model=d_model, n_heads=n_heads, n_layers=n_layers,
                d_ff=4 * d_model, causal=False, num_segments=2)
    base.update(overrides)
    return TransformerConfig(**base)


class BertClassifier(nn.Module):
    cfg: TransformerConfig
    num_classes: int = 2

    @nn.compact
    def __call__(self, tokens, attention_mask=None, deterministic=True):
        x = TransformerEncoder(self.cfg, name="encoder")(
            tokens, attention_mask=attention_mask,
            deterministic=deterministic)
        pooled = nn.tanh(nn.Dense(self.cfg.d_model, dtype=self.cfg.dtype,
                                  name="pooler")(x[:, 0]))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="classifier")(pooled)


def _synthetic_classification_tokens(num_samples: int, seq_len: int,
                                     vocab_size: int, num_classes: int,
                                     seed: int):
    """Class c draws tokens from a class-specific slice of the vocab."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=num_samples)
    span = vocab_size // (num_classes + 1)
    toks = np.empty((num_samples, seq_len), dtype=np.int32)
    for i, c in enumerate(labels):
        lo = (c + 1) * span
        toks[i] = rng.integers(lo, lo + span, size=seq_len)
    # mix in class-agnostic noise tokens
    noise = rng.integers(0, span, size=(num_samples, seq_len))
    noise_mask = rng.random((num_samples, seq_len)) < 0.5
    toks = np.where(noise_mask, noise, toks)
    return toks, labels.astype(np.int32)


class BertModule(TpuModule):
    def __init__(self,
                 config: Optional[TransformerConfig] = None,
                 size: str = "tiny",
                 num_classes: int = 2,
                 batch_size: int = 8,
                 seq_len: Optional[int] = None,
                 num_samples: int = 256,
                 lr: float = 5e-5,
                 vocab_size: int = 1024):
        super().__init__()
        if config is None:
            seq_len = 128 if seq_len is None else seq_len
            config = bert_config(size, vocab_size=vocab_size,
                                 max_seq_len=seq_len)
        self.cfg = config
        seq_len = config.max_seq_len if seq_len is None else seq_len
        if seq_len > config.max_seq_len:
            raise ValueError(
                f"seq_len={seq_len} exceeds config.max_seq_len="
                f"{config.max_seq_len}; positions would silently clamp")
        self.num_classes = num_classes
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.num_samples = num_samples
        self.lr = lr

    def configure_model(self):
        return BertClassifier(self.cfg, self.num_classes)

    def configure_optimizers(self):
        return optax.adamw(self.lr, weight_decay=0.01)

    def _loader(self, seed: int, shuffle: bool = False):
        x, y = _synthetic_classification_tokens(
            self.num_samples, self.seq_len, self.cfg.vocab_size,
            self.num_classes, seed)
        return DataLoader(ArrayDataset((x, y)), batch_size=self.batch_size,
                          shuffle=shuffle)

    def train_dataloader(self):
        return self._loader(0, shuffle=True)

    def val_dataloader(self):
        return self._loader(1)

    def test_dataloader(self):
        return self._loader(2)

    def init_variables(self, model, rng, batch):
        return model.init(rng, batch[0])

    def training_step(self, model, variables, batch, rng):
        tokens, labels = batch
        deterministic = self.cfg.dropout == 0.0
        rngs = None if deterministic else {"dropout": rng}
        logits = model.apply(variables, tokens,
                             deterministic=deterministic, rngs=rngs)
        loss = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, labels))
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        self.log("train_acc", acc)
        return loss

    def validation_step(self, model, variables, batch, rng):
        tokens, labels = batch
        logits = model.apply(variables, tokens, deterministic=True)
        loss = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, labels))
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return {"val_loss": loss, "val_acc": acc}
