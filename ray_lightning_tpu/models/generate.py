"""Autoregressive generation: batched single-pass prefill + tokens-only scan.

TPU-native serving decomposition — the prefill/decode split every
production LM server (vLLM, TGI, JetStream) made canonical:

1. **Prefill** (:func:`prefill`): the whole ``(B, P)`` prompt runs
   through the decode-mode model in ONE compiled forward — a length-P
   block lands in the KV cache via ``dynamic_update_slice`` under an
   intra-prompt causal mask, and the last-position logits come back.
   Prompt cost is one matmul-rich pass instead of P sequential
   ~per-token dispatches (measured ≥5× at P=512; see
   ``docs/performance.md`` decode section and ``bench.py``'s
   ``prefill_tokens_per_sec``).
2. **Decode** (tokens-only ``lax.scan``): exactly ``max_new_tokens - 1``
   cached single-token steps (the first new token is sampled from the
   prefill logits), jitted with ``donate_argnums`` on the cache and
   tokens buffers so the carry updates alias in place instead of
   copying.

Static shapes throughout (prompt and generation lengths are baked into
the two compiled programs; same shapes reuse the cache). Each decode
step attends over the KV cache (O(T) per token instead of O(T²)
re-encoding).

Usage::

    cfg = gpt2_config("small", decode=True)     # decode variant
    model = TransformerLM(cfg)
    out = generate(model, params, prompt_tokens, max_new_tokens=64,
                   rng=jax.random.PRNGKey(0), temperature=0.8, top_k=40)

``params`` come from the *training* config (same architecture, decode
off); the decode flag only switches the attention to its cached path.

Batched variable-length prompts: left-align each row, pad the tail to a
common P, and pass ``prompt_lengths`` (B,). Prefill needs no extra
masking for the pad tail — the intra-prompt causal mask already hides
later keys from every valid query, and the pad positions' K/V are
overwritten by the per-row decode scan before any step can attend them
(each row's step *s* writes cache slot ``lengths[row] + s`` and masks
keys beyond it). Each row emits exactly ``max_new_tokens`` tokens at
positions ``lengths[row]..lengths[row]+max_new_tokens-1``; a short
row's positions beyond its window keep whatever pad values the caller
supplied there (the appended region past P is zero-initialized, the
prompt pad is passed through untouched) — slice each row by its own
window, don't sentinel on the tail. ``eos_id`` stops a row once
sampled: every
later position in its window repeats the eos token (the scan still runs
full length — static shapes).

The legacy single-program path (prompt teacher-forced through the same
one-token-at-a-time scan used for sampling) is kept as
:func:`generate_full_scan` — it is the reference the prefill+scan
equivalence tests compare against, and ``generate(...,
use_prefill=False)`` selects it.

Serving tip (measured, ``docs/performance.md`` decode section): build
the decode config with ``scan_layers=False`` and convert scanned
training weights with
:func:`ray_lightning_tpu.models.transformer.unstack_scan_params`.
Scanned layers nest a layer loop inside the token scan, which the TPU
compiler emits far slower per decode step: GPT-2-small/v5e measures
1.66 ms/step scanned vs 0.60 ms/step unrolled (device-differential,
2.8x). Training's compile-time economics favor the scan, serving's do
not — recompilation is paid once per shape.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ray_lightning_tpu.models.quant import materialize_for_program
from ray_lightning_tpu.models.transformer import latch_eos


def sample_logits(logits: jax.Array, rng: jax.Array,
                  temperature: float = 1.0,
                  top_k: Optional[int] = None) -> jax.Array:
    """Sample token ids from (B, V) logits.

    ``temperature=0`` is greedy argmax; ``top_k`` restricts sampling to
    the k highest-probability tokens.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, jnp.finfo(jnp.float32).min,
                           logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def sample_logits_rows(logits: jax.Array, keys: jax.Array,
                       temperature: jax.Array,
                       top_k: jax.Array) -> jax.Array:
    """Per-row sampling from (B, V) logits — the batched-heterogeneous
    sibling of :func:`sample_logits` for the serving engine, where every
    slot carries its own request's sampling params.

    ``keys`` (B, 2) is one explicit PRNG key per row (the engine derives
    row r's key as ``fold_in(fold_in(base, request_seed), step)``, so a
    request's sample stream depends only on its seed and step index —
    reproducible across slot assignments and batch compositions, and never
    shared between co-resident slots). ``temperature`` (B,) with 0 = greedy
    argmax for that row (bit-identical to :func:`sample_logits`'s greedy).
    ``top_k`` (B,) int with 0 = unrestricted; a *traced* per-row k cannot
    use ``lax.top_k`` (static k), so the mask comes from ranks of a
    descending argsort — same "keep the k highest" semantics with k dynamic
    (ties broken by sort order rather than kept, which only reweights
    exactly-tied tail logits).

    The expensive machinery is gated at the BATCH level with ``lax.cond``
    (outside the vmap, so XLA executes one branch at runtime): an
    all-greedy batch — the tracked serving bench, and any temperature=0
    deployment — pays one argmax, no per-row categorical; the full-vocab
    argsort additionally engages only when some row actually restricts
    top_k. Per-row greedy/sampled mixing stays inside the sampled branch.
    """
    temperature = jnp.asarray(temperature, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)

    def rows_greedy():
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def rows_sampled(use_topk: bool):
        def row(l, k, t, tk):
            greedy = jnp.argmax(l).astype(jnp.int32)
            scaled = l / jnp.where(t > 0, t, 1.0)
            if use_topk:
                order = jnp.argsort(-l)
                ranks = jnp.zeros_like(order).at[order].set(
                    jnp.arange(l.shape[0], dtype=order.dtype))
                scaled = jnp.where((tk > 0) & (ranks >= tk),
                                   jnp.finfo(jnp.float32).min, scaled)
            sampled = jax.random.categorical(k, scaled).astype(jnp.int32)
            return jnp.where(t > 0, sampled, greedy)

        return jax.vmap(row)(logits, keys, temperature, top_k)

    return jax.lax.cond(
        jnp.any(temperature > 0.0),
        lambda: jax.lax.cond(jnp.any(top_k > 0),
                             lambda: rows_sampled(True),
                             lambda: rows_sampled(False)),
        rows_greedy)


def _check_decode_model(model, P: int, max_new_tokens: int = 0) -> None:
    cfg = model.cfg
    if not cfg.decode:
        raise ValueError(
            "generate() needs a decode-mode model: rebuild the config "
            "with decode=True (params are compatible)")
    if P + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt ({P}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({cfg.max_seq_len})")


def _logits_only(outputs):
    # MoE LMs return (logits, aux_loss); serving only needs the logits
    return outputs[0] if isinstance(outputs, tuple) else outputs


def _row_update(rows: jax.Array, vals: jax.Array,
                starts: jax.Array) -> jax.Array:
    """Per-row ``dynamic_update_slice`` along axis 1: write ``vals``
    (B, 1) into ``rows`` (B, T) at each row's own ``starts`` (B,)."""
    return jax.vmap(
        lambda row, val, i: jax.lax.dynamic_update_slice(row, val, (i,)))(
            rows, vals, starts)


def _adapter_kw(adapter_ids):
    """Kwargs guard for the per-row LoRA adapter ids: ``None`` adds
    nothing (model families without the kwarg — MoE, encoders — and
    unadapted engines never see it, and None-vs-array are different
    pytree structures so unadapted programs never recompile)."""
    return {} if adapter_ids is None else {"adapter_ids": adapter_ids}


def decode_step(model, params, cache, tokens: jax.Array,
                kv_positions: jax.Array, adapter_ids=None):
    """ONE cached single-token decode step at explicit per-row positions —
    the shared core between :func:`generate`'s ragged decode scan and the
    serving engine's continuous-batching step
    (:mod:`ray_lightning_tpu.serve.engine`), so the two paths cannot
    drift.

    ``tokens`` (B, 1) holds each row's current token, ``kv_positions``
    (B, 1) its absolute sequence position: the step writes each row's K/V
    at its own slot (the per-row ``_decode_cache`` mode) and masks keys
    beyond it — rows at *different* sequence lengths share one compiled
    program, which is what lets the engine swap requests in and out of
    batch rows without recompiling.

    Returns ``(last_logits (B, V), cache)``. Sampling stays outside (the
    scan and the engine consume the logits differently — shared rng for a
    homogeneous batch vs per-request keys and sampling params).

    ``params`` may be weight-quantized (:mod:`..models.quant`): the
    shared entry guard (``materialize_for_program`` — a trace-time
    no-op on plain trees) dequantizes under ``matmul_kernel="xla"``
    and passes the codes through to the fused kernel under
    ``"pallas"``. The serve programs guard once at THEIR entry
    (outside the step scans), so this only fires for direct callers.
    """
    params = materialize_for_program(params, model.cfg)
    outputs, updated = model.apply(
        {"params": params, "cache": cache}, tokens,
        positions=kv_positions, kv_positions=kv_positions,
        deterministic=True, mutable=["cache"],
        **_adapter_kw(adapter_ids))
    return _logits_only(outputs)[:, -1], updated["cache"]


def _arena_apply(model, params, arena, tokens, kv_positions, page_table,
                 adapter_ids=None):
    """Shared page-native ``model.apply`` plumbing: the arena's cache
    tree rides as the ``cache`` collection (int8 arenas split their
    ``(codes, scales)`` tuple across ``cache`` + ``kvscale``), and the
    updated arena comes back in the same storage layout."""
    quantized = isinstance(arena, tuple)
    variables = {"params": params}
    if quantized:
        variables["cache"], variables["kvscale"] = arena
        mutable = ["cache", "kvscale"]
    else:
        variables["cache"] = arena
        mutable = ["cache"]
    outputs, updated = model.apply(
        variables, tokens, positions=kv_positions,
        kv_positions=kv_positions, page_table=page_table,
        deterministic=True, mutable=mutable,
        **_adapter_kw(adapter_ids))
    new_arena = ((updated["cache"], updated["kvscale"]) if quantized
                 else updated["cache"])
    return _logits_only(outputs), new_arena


def decode_step_paged(model, params, arena, tokens: jax.Array,
                      kv_positions: jax.Array, page_table: jax.Array,
                      adapter_ids=None):
    """Page-native sibling of :func:`decode_step`: ONE cached
    single-token step whose K/V reads and writes go straight through
    the serving engine's page arena — no dense per-slot view is
    gathered or scattered (see
    ``MultiHeadAttention._page_native_attention``).

    ``arena`` is the paged KV tree (``(num_pages, page_size, H, D)``
    leaves; int8 arenas are the usual ``(codes, scales)`` tuple) and
    ``page_table`` (B, pages_per_slot) maps each row to its pages — the
    engine passes its write-masked table, so retired/chunking rows'
    parked writes drop. Returns ``(last_logits (B, V), arena)``.
    """
    params = materialize_for_program(params, model.cfg)
    logits, arena = _arena_apply(model, params, arena, tokens,
                                 kv_positions, page_table, adapter_ids)
    return logits[:, -1], arena


def verify_step_paged(model, params, arena, tokens: jax.Array,
                      kv_positions: jax.Array, page_table: jax.Array,
                      adapter_ids=None):
    """Page-native sibling of :func:`verify_step`: the speculative
    verify's per-row (B, T) block scoring, reading/writing K/V through
    the page table. Returns ``(logits (B, T, V), arena)`` — every
    offset's logits, as the accept rule requires."""
    params = materialize_for_program(params, model.cfg)
    return _arena_apply(model, params, arena, tokens, kv_positions,
                        page_table, adapter_ids)


def verify_step(model, params, cache, tokens: jax.Array,
                kv_positions: jax.Array, adapter_ids=None):
    """ONE cached block-scoring step at per-row positions — the target
    side of speculative decoding (:mod:`ray_lightning_tpu.serve.spec`).

    ``tokens`` (B, T) holds each row's current token followed by its
    T-1 draft proposals; ``kv_positions`` (B, T) their absolute
    positions (the contiguous run ``pos..pos+T-1`` per row). The step
    block-writes each row's K/V at its own positions (the per-row block
    mode of ``_decode_cache``) under a block-causal mask, so ONE
    dispatch scores every draft token exactly as T sequential
    :func:`decode_step` calls would: offset ``j``'s logits are the
    target's next-token distribution given the row's context plus
    drafts ``< j``.

    Returns ``(logits (B, T, V), cache)`` — all T positions' logits
    (the accept rule needs every offset, not just the last). Rejected
    drafts' K/V stays in the cache at positions past the commit point;
    that is deliberate rollback-by-position-decrement: later writes
    land at or before those positions before any mask re-admits them
    (same argument as the chunk-prefill path).
    """
    params = materialize_for_program(params, model.cfg)
    outputs, updated = model.apply(
        {"params": params, "cache": cache}, tokens,
        positions=kv_positions, kv_positions=kv_positions,
        deterministic=True, mutable=["cache"],
        **_adapter_kw(adapter_ids))
    return _logits_only(outputs), updated["cache"]


def _prefill_impl(model, params, prompt_tokens, prompt_lengths,
                  adapter_ids=None):
    params = materialize_for_program(params, model.cfg)
    B, P = prompt_tokens.shape
    prompt_tokens = prompt_tokens.astype(jnp.int32)
    cache = model.init(jax.random.PRNGKey(0),
                       jnp.zeros((B, 1), jnp.int32),
                       positions=jnp.zeros((B, 1), jnp.int32))["cache"]
    positions = jax.lax.broadcasted_iota(jnp.int32, (1, P), 1)
    outputs, updated = model.apply(
        {"params": params, "cache": cache}, prompt_tokens,
        positions=positions, deterministic=True, mutable=["cache"],
        **_adapter_kw(adapter_ids))
    logits = _logits_only(outputs)
    if prompt_lengths is None:
        last = logits[:, -1]
    else:
        lengths = jnp.asarray(prompt_lengths, jnp.int32)
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    return updated["cache"], last


@partial(jax.jit, static_argnames=("model",))
def prefill(model, params, prompt_tokens: jax.Array,
            prompt_lengths: Optional[jax.Array] = None):
    """Single-pass prompt fill: run the full ``(B, P)`` prompt through
    the decode-mode model in one forward, writing cache slots ``0..P-1``.

    Returns ``(cache, last_logits)`` where ``last_logits`` (B, V) are the
    logits at each row's final prompt position (``prompt_lengths[i]-1``
    when lengths are given, else ``P-1``) — sample the first generated
    token from them, then continue with per-token cached decode steps.
    Causality makes them exact for left-aligned ragged rows: position
    ``L-1`` never attends past itself, so the pad tail cannot leak in.

    Ragged continuation contract: after a ragged prefill the cache slots
    ``lengths[i]..P-1`` of short rows hold pad-tail K/V, so the decode
    steps MUST use per-row ``kv_positions`` (each row's step *s* writes
    slot ``lengths[i] + s`` and masks keys beyond it, overwriting the
    garbage before it can be attended) — exactly what :func:`generate`
    does. A plain shared-index step after a ragged prefill would write at
    slot P and let short rows attend their pad-tail slots: silently
    wrong. Uniform prompts (``prompt_lengths=None``) may continue with
    plain shared-index steps.
    """
    _check_decode_model(model, prompt_tokens.shape[1])
    return _prefill_impl(model, params, prompt_tokens, prompt_lengths)


@partial(jax.jit,
         static_argnames=("model", "max_new_tokens", "temperature",
                          "top_k", "eos_id", "ragged"))
def _prefill_start(model, params, prompt_tokens, lengths, rng, *,
                   max_new_tokens, temperature, top_k, eos_id, ragged):
    """Program 1 of the split: prefill + first-token sample + output
    buffer assembly, fused so generate() costs exactly two dispatches."""
    B, P = prompt_tokens.shape
    cache, last = _prefill_impl(model, params, prompt_tokens,
                                lengths if ragged else None)
    rng, sub = jax.random.split(rng)
    first = sample_logits(last, sub, temperature, top_k)
    done = (first == eos_id) if eos_id is not None \
        else jnp.zeros((B,), jnp.bool_)
    tokens = jnp.concatenate(
        [prompt_tokens.astype(jnp.int32),
         jnp.zeros((B, max_new_tokens), jnp.int32)], axis=1)
    if ragged:
        tokens = _row_update(tokens, first[:, None], lengths)
    else:
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, first[:, None], P, axis=1)
    return cache, tokens, rng, done


def _decode_scan(model, cache, tokens, params, lengths, rng, done0, *,
                 steps, temperature, top_k, eos_id, ragged):
    """Program 2 of the split: ``steps`` cached single-token decode steps
    starting from the prefill cache. The cache and tokens buffers are
    donated — the scan carry updates them in place, no per-call copies.
    """
    B, total = tokens.shape

    def step(carry, s):
        cache, tokens, rng, done = carry
        if ragged:
            # rows sit at different lengths: read/write at per-row
            # positions — the shared decode_step (also the serving
            # engine's model step) does the per-row kv_positions write
            pos = (lengths + s)[:, None]
            cur = jnp.take_along_axis(tokens, pos, axis=1)
            last, cache = decode_step(model, params, cache, cur, pos)
        else:
            t = total - steps - 1 + s
            cur = jax.lax.dynamic_slice_in_dim(tokens, t, 1, axis=1)
            pos = jnp.full((B, 1), t, jnp.int32)
            outputs, cache_vars = model.apply(
                {"params": params, "cache": cache}, cur, positions=pos,
                deterministic=True, mutable=["cache"])
            last, cache = _logits_only(outputs)[:, -1], cache_vars["cache"]
        rng, sub = jax.random.split(rng)
        nxt = sample_logits(last, sub, temperature, top_k)
        if eos_id is not None:
            # every scanned step samples strictly past the prompt, so
            # (unlike the teacher-forced legacy scan) latching needs no
            # "generating" gate
            nxt, done = latch_eos(nxt, done, eos_id)
        if ragged:
            tokens = _row_update(tokens, nxt[:, None], lengths + s + 1)
        else:
            tokens = jax.lax.dynamic_update_slice_in_dim(
                tokens, nxt[:, None], total - steps + s, axis=1)
        return (cache, tokens, rng, done), None

    (_, tokens, _, _), _ = jax.lax.scan(
        step, (cache, tokens, rng, done0), jnp.arange(steps))
    return tokens


_SCAN_STATICS = ("model", "steps", "temperature", "top_k", "eos_id",
                 "ragged")
_decode_scan_donated = partial(
    jax.jit, static_argnames=_SCAN_STATICS,
    donate_argnums=(1, 2))(_decode_scan)
_decode_scan_plain = partial(
    jax.jit, static_argnames=_SCAN_STATICS)(_decode_scan)


def _decode_scan_jit():
    """Donate the cache/tokens carry wherever the backend honors it; the
    CPU backend ignores donation with a warning per buffer, so tests stay
    quiet on the plain variant (the programs are otherwise identical)."""
    return (_decode_scan_plain if jax.default_backend() == "cpu"
            else _decode_scan_donated)


def generate(model, params, prompt_tokens: jax.Array,
             max_new_tokens: int, rng: jax.Array,
             temperature: float = 1.0,
             top_k: Optional[int] = None,
             prompt_lengths: Optional[jax.Array] = None,
             eos_id: Optional[int] = None,
             use_prefill: bool = True) -> jax.Array:
    """Generate ``max_new_tokens`` past ``prompt_tokens`` (B, P).

    Returns (B, P + max_new_tokens) int32. ``model.cfg.decode`` must be
    True and ``cfg.max_seq_len >= P + max_new_tokens``.

    Two compiled programs: a batched prompt prefill (one forward for all
    P positions) and a tokens-only decode scan of ``max_new_tokens - 1``
    steps with donated cache/tokens buffers — see the module docstring.
    ``use_prefill=False`` selects the legacy single-program path
    (:func:`generate_full_scan`); greedy outputs are token-identical
    either way (pinned by tests/test_prefill.py). Sampling
    (``temperature > 0``) is equivalent in distribution but consumes the
    rng stream differently from the legacy path (which burned one split
    per teacher-forced prompt position).
    """
    if not use_prefill:
        return generate_full_scan(model, params, prompt_tokens,
                                  max_new_tokens, rng, temperature, top_k,
                                  prompt_lengths, eos_id)
    B, P = prompt_tokens.shape
    _check_decode_model(model, P, max_new_tokens)
    ragged = prompt_lengths is not None
    prompt_tokens = jnp.asarray(prompt_tokens, jnp.int32)
    lengths = (jnp.asarray(prompt_lengths, jnp.int32) if ragged
               else jnp.full((B,), P, jnp.int32))
    cache, tokens, rng, done = _prefill_start(
        model, params, prompt_tokens, lengths, rng,
        max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, eos_id=eos_id, ragged=ragged)
    if max_new_tokens == 1:
        return tokens
    return _decode_scan_jit()(
        model, cache, tokens, params, lengths, rng, done,
        steps=max_new_tokens - 1, temperature=temperature,
        top_k=top_k, eos_id=eos_id, ragged=ragged)


@partial(jax.jit,
         static_argnames=("model", "max_new_tokens", "temperature",
                          "top_k", "eos_id"))
def generate_full_scan(model, params, prompt_tokens: jax.Array,
                       max_new_tokens: int, rng: jax.Array,
                       temperature: float = 1.0,
                       top_k: Optional[int] = None,
                       prompt_lengths: Optional[jax.Array] = None,
                       eos_id: Optional[int] = None) -> jax.Array:
    """Legacy one-program path: the prompt is teacher-forced through the
    same one-token-at-a-time scan used for sampling (P sequential steps
    before the first new token). Kept as the equivalence reference for
    the prefill+scan split; prefer :func:`generate`.

    Variable-length note: this path fills every row to the common
    ``P + max_new_tokens`` length (short rows keep generating past their
    ``prompt_lengths[i] + max_new_tokens`` window), where the split path
    stops each row after exactly ``max_new_tokens`` tokens.
    """
    B, P = prompt_tokens.shape
    _check_decode_model(model, P, max_new_tokens)
    total = P + max_new_tokens
    lengths = (jnp.full((B,), P, jnp.int32) if prompt_lengths is None
               else jnp.asarray(prompt_lengths, jnp.int32))

    cache = model.init(jax.random.PRNGKey(0),
                       jnp.zeros((B, 1), jnp.int32),
                       positions=jnp.zeros((B, 1), jnp.int32))["cache"]

    tokens0 = jnp.concatenate(
        [prompt_tokens.astype(jnp.int32),
         jnp.zeros((B, max_new_tokens), jnp.int32)], axis=1)
    done0 = jnp.zeros((B,), jnp.bool_)

    def step(carry, t):
        cache, tokens, rng, done = carry
        cur = jax.lax.dynamic_slice_in_dim(tokens, t, 1, axis=1)
        pos = jnp.full((B, 1), t, jnp.int32)
        outputs, updated = model.apply(
            {"params": params, "cache": cache}, cur, positions=pos,
            deterministic=True, mutable=["cache"])
        logits = _logits_only(outputs)
        rng, sub = jax.random.split(rng)
        nxt = sample_logits(logits[:, -1], sub, temperature, top_k)
        if eos_id is not None:
            # done can only be set while a row is actually GENERATING —
            # throwaway samples during another row's teacher-forced
            # prompt region must not latch it
            generating = (t + 1) >= lengths
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (generating & (nxt == eos_id))
        # teacher-force each row's own prompt; sampling starts at its end
        forced = jnp.where(t + 1 < lengths, tokens[:, t + 1], nxt)
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, forced[:, None], t + 1, axis=1)
        return (updated["cache"], tokens, rng, done), None

    (cache, tokens, rng, _done), _ = jax.lax.scan(
        step, (cache, tokens0, rng, done0), jnp.arange(total - 1))
    return tokens
