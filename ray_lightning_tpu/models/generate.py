"""Autoregressive generation with a KV cache, compiled as one program.

TPU-native decode: the whole prompt-feed + sample loop is a single
``lax.scan`` under ``jit`` — no per-token Python dispatch, static shapes
throughout (prompt and generation lengths are baked into the compiled
program; re-generating with the same shapes reuses the cache). Each step
attends over the KV cache (O(T) per token instead of O(T²) re-encoding),
the pattern every production LM server uses.

Usage::

    cfg = gpt2_config("small", decode=True)     # decode variant
    model = TransformerLM(cfg)
    out = generate(model, params, prompt_tokens, max_new_tokens=64,
                   rng=jax.random.PRNGKey(0), temperature=0.8, top_k=40)

``params`` come from the *training* config (same architecture, decode
off); the decode flag only switches the attention to its cached path.

Serving tip (measured, ``docs/performance.md`` decode section): build
the decode config with ``scan_layers=False`` and convert scanned
training weights with
:func:`ray_lightning_tpu.models.transformer.unstack_scan_params`.
Scanned layers nest a layer loop inside the token scan, which the TPU
compiler emits far slower per decode step: GPT-2-small/v5e measures
1.66 ms/step scanned vs 0.60 ms/step unrolled (device-differential,
2.8x). Training's compile-time economics favor the scan, serving's do
not — recompilation is paid once per shape.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(logits: jax.Array, rng: jax.Array,
                  temperature: float = 1.0,
                  top_k: Optional[int] = None) -> jax.Array:
    """Sample token ids from (B, V) logits.

    ``temperature=0`` is greedy argmax; ``top_k`` restricts sampling to
    the k highest-probability tokens.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, jnp.finfo(jnp.float32).min,
                           logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


@partial(jax.jit,
         static_argnames=("model", "max_new_tokens", "temperature",
                          "top_k", "eos_id"))
def generate(model, params, prompt_tokens: jax.Array,
             max_new_tokens: int, rng: jax.Array,
             temperature: float = 1.0,
             top_k: Optional[int] = None,
             prompt_lengths: Optional[jax.Array] = None,
             eos_id: Optional[int] = None) -> jax.Array:
    """Generate ``max_new_tokens`` past ``prompt_tokens`` (B, P).

    Returns (B, P + max_new_tokens) int32. ``model.cfg.decode`` must be
    True and ``cfg.max_seq_len >= P + max_new_tokens``.

    Batched variable-length prompts: left-align each row, pad the tail to
    a common P (pad values are never read), and pass ``prompt_lengths``
    (B,) — row *i* starts sampling at position ``prompt_lengths[i]``, so
    no padding ever enters the cache and no attention mask is needed.
    ``eos_id`` stops a row once sampled: every later position repeats the
    eos token (the scan still runs full length — static shapes).
    """
    cfg = model.cfg
    if not cfg.decode:
        raise ValueError(
            "generate() needs a decode-mode model: rebuild the config "
            "with decode=True (params are compatible)")
    B, P = prompt_tokens.shape
    total = P + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt ({P}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({cfg.max_seq_len})")
    lengths = (jnp.full((B,), P, jnp.int32) if prompt_lengths is None
               else jnp.asarray(prompt_lengths, jnp.int32))

    cache = model.init(jax.random.PRNGKey(0),
                       jnp.zeros((B, 1), jnp.int32),
                       positions=jnp.zeros((B, 1), jnp.int32))["cache"]

    tokens0 = jnp.concatenate(
        [prompt_tokens.astype(jnp.int32),
         jnp.zeros((B, max_new_tokens), jnp.int32)], axis=1)
    done0 = jnp.zeros((B,), jnp.bool_)

    def step(carry, t):
        cache, tokens, rng, done = carry
        cur = jax.lax.dynamic_slice_in_dim(tokens, t, 1, axis=1)
        pos = jnp.full((B, 1), t, jnp.int32)
        logits, updated = model.apply(
            {"params": params, "cache": cache}, cur, positions=pos,
            deterministic=True, mutable=["cache"])
        if isinstance(logits, tuple):  # MoE LMs return (logits, aux_loss)
            logits = logits[0]
        rng, sub = jax.random.split(rng)
        nxt = sample_logits(logits[:, -1], sub, temperature, top_k)
        if eos_id is not None:
            # done can only be set while a row is actually GENERATING —
            # throwaway samples during another row's teacher-forced
            # prompt region must not latch it
            generating = (t + 1) >= lengths
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (generating & (nxt == eos_id))
        # teacher-force each row's own prompt; sampling starts at its end
        forced = jnp.where(t + 1 < lengths, tokens[:, t + 1], nxt)
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, forced[:, None], t + 1, axis=1)
        return (updated["cache"], tokens, rng, done), None

    (cache, tokens, rng, _done), _ = jax.lax.scan(
        step, (cache, tokens0, rng, done0), jnp.arange(total - 1))
    return tokens
