"""Encoder-decoder (seq2seq) transformer: cross-attention topology.

Net-new beyond the reference (its model zoo is user-supplied torch code;
SURVEY.md lists no encoder-decoder requirement) — this rounds out the
transformer core's topologies: decoder blocks attend causally over their
own prefix AND bidirectionally over a separately-encoded source sequence
(T5/BART shape). Built from the same TPU-first pieces as the rest of the
family (bf16 compute via ``TransformerConfig.dtype``, the pluggable
attention impls for self-attention, shared ``MlpBlock``), with
cross-attention as its own module so the hot decoder-only path
(`transformer.py`) stays untouched.

Training task (zero-egress): sequence reversal — the decoder must copy the
source backwards, which is impossible without functioning cross-attention
(self-attention alone cannot see the source), so the learning test is a
behavioral gate on the new topology, not just a shape check.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.data.loader import ArrayDataset, DataLoader
from ray_lightning_tpu.models.transformer import (MlpBlock,
                                                  MultiHeadAttention,
                                                  TransformerConfig,
                                                  TransformerStack,
                                                  check_seq_len,
                                                  maybe_remat)
from ray_lightning_tpu.ops.attention import dot_product_attention


class CrossAttention(nn.Module):
    """Decoder-side attention over encoder outputs (bidirectional).

    Queries come from the decoder stream ``x``; keys/values from the
    encoder output ``memory``. Separate q / kv projections (the fused qkv
    of self-attention cannot serve two streams).
    """
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, memory, memory_mask=None, deterministic=True):
        cfg = self.cfg
        B, T, _ = x.shape
        q = nn.DenseGeneral(features=(cfg.n_heads, cfg.head_dim), axis=-1,
                            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            name="q")(x)
        kv = nn.DenseGeneral(features=(2, cfg.n_heads, cfg.head_dim),
                             axis=-1, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="kv")(memory)
        k, v = kv[:, :, 0], kv[:, :, 1]
        # same precision/dropout policy as self-attention
        kw = {}
        if cfg.attention_softmax_dtype != jnp.float32:
            kw["softmax_dtype"] = cfg.attention_softmax_dtype
        drop_rng = None
        if cfg.dropout > 0.0 and not deterministic:
            drop_rng = self.make_rng("dropout")
        out = dot_product_attention(
            q, k, v, causal=False, mask=memory_mask,
            dropout_rate=cfg.dropout if not deterministic else 0.0,
            dropout_rng=drop_rng, **kw)
        out = out.reshape(B, T, cfg.n_heads * cfg.head_dim)
        return nn.DenseGeneral(features=cfg.d_model, dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype, name="out")(out)


class DecoderBlock(nn.Module):
    """Pre-LN decoder block: causal self-attn → cross-attn → MLP."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, memory, memory_mask=None, deterministic=True):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x)
        x = x + MultiHeadAttention(cfg, name="self_attn")(
            h, deterministic=deterministic)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_cross")(x)
        x = x + CrossAttention(cfg, name="cross_attn")(
            h, memory, memory_mask=memory_mask,
            deterministic=deterministic)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x)
        x = x + MlpBlock(cfg, name="mlp")(h, deterministic=deterministic)
        return x


class Seq2SeqTransformer(nn.Module):
    """Encoder-decoder LM: bidirectional encoder, causal decoder with
    cross-attention, tied decoder embedding as the output head.

    ``cfg.causal`` must be True (the decoder's self-attention); the
    encoder stack runs bidirectional regardless. ``src_mask`` (B, S) with
    1 = attend, 0 = padding, applies to the encoder's self-attention and
    the decoder's cross-attention.
    """
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, src_tokens, tgt_tokens, src_mask=None,
                 deterministic: bool = True):
        cfg = self.cfg
        if not cfg.causal:
            raise ValueError(
                "Seq2SeqTransformer needs cfg.causal=True (the decoder's "
                "self-attention); a non-causal decoder would read future "
                "target tokens and train on the answer")
        B, S = src_tokens.shape
        _, T = tgt_tokens.shape
        check_seq_len(cfg, S, what="source")
        check_seq_len(cfg, T, what="target")
        enc_cfg = dataclasses.replace(cfg, causal=False)

        additive = None
        if src_mask is not None:
            big_neg = jnp.finfo(jnp.float32).min
            additive = jnp.where(src_mask[:, None, None, :] > 0, 0.0,
                                 big_neg)

        # encoder: the shared TransformerStack — scan_layers/remat and the
        # tensor-parallel param naming (block/attn/qkv...) apply here too
        src_embed = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="src_embed")
        pos = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
        h = src_embed(src_tokens) + nn.Embed(
            cfg.max_seq_len, cfg.d_model, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="src_pos")(pos)
        h = TransformerStack(enc_cfg, name="encoder")(
            h, mask=additive, deterministic=deterministic)
        memory = nn.LayerNorm(dtype=cfg.dtype, name="enc_ln_f")(h)

        # decoder
        tgt_embed = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="tgt_embed")
        tpos = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
        x = tgt_embed(tgt_tokens) + nn.Embed(
            cfg.max_seq_len, cfg.d_model, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="tgt_pos")(tpos)
        # cfg.remat applies to the decoder half too (the encoder gets it
        # via TransformerStack); scan_layers is encoder-only here — the
        # decoder's two-stream signature (x, memory) would need its own
        # scan carry, and seq2seq depth hasn't justified it.
        block_cls = maybe_remat(DecoderBlock, cfg, deterministic_argnum=4)
        for i in range(cfg.n_layers):
            x = block_cls(cfg, name=f"dec_{i}")(
                x, memory, additive, deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, name="dec_ln_f")(x)
        logits = tgt_embed.attend(x)
        return logits.astype(jnp.float32)


def _reversal_pairs(num_samples: int, seq_len: int, vocab_size: int,
                    seed: int):
    """Source sequences + their reversals (teacher-forced targets).

    Token 0 is reserved as BOS for the shifted decoder input.
    """
    rng = np.random.default_rng(seed)
    src = rng.integers(1, vocab_size, size=(num_samples, seq_len))
    tgt = src[:, ::-1].copy()
    return src.astype(np.int32), tgt.astype(np.int32)


class Seq2SeqModule(TpuModule):
    """Sequence-reversal trainer: cross-attention's behavioral gate."""

    def __init__(self, config: Optional[TransformerConfig] = None,
                 batch_size: int = 16, seq_len: int = 16,
                 num_samples: int = 512, vocab_size: int = 64,
                 lr: float = 3e-3):
        super().__init__()
        if config is None:
            config = TransformerConfig(
                vocab_size=vocab_size, max_seq_len=seq_len, d_model=128,
                n_heads=4, n_layers=2, d_ff=256, causal=True)
        if seq_len > config.max_seq_len:
            raise ValueError(
                f"seq_len={seq_len} exceeds config.max_seq_len="
                f"{config.max_seq_len}; positions would silently clamp")
        self.cfg = config
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.num_samples = num_samples
        self.lr = lr

    def configure_model(self):
        return Seq2SeqTransformer(self.cfg)

    def configure_optimizers(self):
        return optax.adamw(self.lr, weight_decay=0.01)

    def _loader(self, seed: int):
        src, tgt = _reversal_pairs(self.num_samples, self.seq_len,
                                   self.cfg.vocab_size, seed)
        return DataLoader(ArrayDataset((src, tgt)),
                          batch_size=self.batch_size)

    def train_dataloader(self):
        return self._loader(0)

    def val_dataloader(self):
        return self._loader(1)

    def init_variables(self, model, rng, batch):
        src, tgt = batch
        return model.init(rng, src, self._shift_right(tgt))

    @staticmethod
    def _shift_right(tgt):
        return jnp.concatenate(
            [jnp.zeros_like(tgt[:, :1]), tgt[:, :-1]], axis=1)

    def _loss_acc(self, model, variables, batch, rng=None):
        src, tgt = batch
        deterministic = rng is None or self.cfg.dropout == 0.0
        rngs = None if deterministic else {"dropout": rng}
        logits = model.apply(variables, src, self._shift_right(tgt),
                             deterministic=deterministic, rngs=rngs)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).mean()
        acc = jnp.mean((jnp.argmax(logits, -1) == tgt).astype(jnp.float32))
        return loss, acc

    def training_step(self, model, variables, batch, rng):
        loss, acc = self._loss_acc(model, variables, batch, rng=rng)
        self.log("train_loss", loss)
        self.log("train_acc", acc)
        return loss

    def validation_step(self, model, variables, batch, rng):
        loss, acc = self._loss_acc(model, variables, batch)
        return {"val_loss": loss, "val_acc": acc}
