"""Vision Transformer family.

Model-zoo breadth beyond the reference (whose examples cover MLP/CNN/GPT
seats): a ViT classifier built from the same ``TransformerStack`` the
BERT/GPT families use, so every parallelism rule that works there
(tensor-parallel layouts, FSDP largest-dim sharding, remat, scanned
layers) applies to vision unchanged. Patch embedding is a single strided
conv — one big MXU matmul per image, no host-side patch extraction.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.data.loader import ArrayDataset, DataLoader
from ray_lightning_tpu.models.transformer import (TransformerConfig,
                                                  TransformerStack)


def vit_config(size: str = "tiny", image_size: int = 32,
               patch_size: int = 4, **overrides) -> TransformerConfig:
    sizes = {
        "tiny": (4, 192, 3),
        "small": (12, 384, 6),
        "base": (12, 768, 12),   # ViT-B
    }
    if size not in sizes:
        raise ValueError(f"Unknown ViT size {size!r}; choose from "
                         f"{sorted(sizes)}")
    n_layers, d_model, n_heads = sizes[size]
    if image_size % patch_size != 0:
        raise ValueError(
            f"image_size={image_size} must be divisible by "
            f"patch_size={patch_size} (non-overlapping square patches)")
    n_patches = (image_size // patch_size) ** 2
    base = dict(vocab_size=1,  # unused: inputs are pixels, not tokens
                max_seq_len=n_patches + 1,  # +1 CLS
                d_model=d_model, n_heads=n_heads, n_layers=n_layers,
                d_ff=4 * d_model, causal=False,
                # remat + save_attn ships as the ViT default: measured
                # +30% samples/s at base/224/bs32 on v5e (interleaved
                # A/B, tools/ab_sweep.py — saving every activation costs
                # more HBM write traffic than the backward recompute) and
                # is semantics-preserving. Override with remat=False to
                # trade throughput for compile simplicity.
                remat=True,
                remat_policy="dots_with_no_batch_dims_save_attn")
    base.update(overrides)
    if not base["remat"] and "remat_policy" not in overrides:
        # opting out via remat=False must not trip the config's
        # remat_policy-without-remat guard on the default policy
        base["remat_policy"] = None
    return TransformerConfig(**base)


class ViTClassifier(nn.Module):
    """ViT: conv patch embed + CLS token + bidirectional transformer."""
    cfg: TransformerConfig
    num_classes: int = 10
    patch_size: int = 4

    @nn.compact
    def __call__(self, images, deterministic: bool = True):
        cfg = self.cfg
        B = images.shape[0]
        p = self.patch_size
        x = nn.Conv(cfg.d_model, kernel_size=(p, p), strides=(p, p),
                    padding="VALID", dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype,
                    name="patch_embed")(images.astype(cfg.dtype))
        x = x.reshape(B, -1, cfg.d_model)  # (B, n_patches, D)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.d_model), cfg.param_dtype)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (B, 1, cfg.d_model)).astype(cfg.dtype),
             x], axis=1)
        T = x.shape[1]
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, cfg.max_seq_len, cfg.d_model),
                         cfg.param_dtype)
        x = x + pos[:, :T].astype(cfg.dtype)
        x = TransformerStack(cfg, name="stack")(
            x, deterministic=deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, name="head_ln")(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x[:, 0])


def _synthetic_images(num_samples: int, image_size: int, num_classes: int,
                      seed: int = 0):
    """Class-conditioned noisy images so accuracy is learnable quickly.

    The class prototypes are drawn from a FIXED seed so train/val/test
    splits (different ``seed``) share one distribution and only differ in
    sampling noise — otherwise validation measures a different task.
    """
    protos = np.random.default_rng(1234).standard_normal(
        (num_classes, image_size, image_size, 3))
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, size=(num_samples,)).astype(np.int32)
    x = protos[y] + 0.3 * rng.standard_normal(
        (num_samples, image_size, image_size, 3))
    return x.astype(np.float32), y


class ViTModule(TpuModule):
    """Image classification on synthetic class-prototype data."""

    def __init__(self,
                 size: str = "tiny",
                 image_size: int = 32,
                 patch_size: int = 4,
                 num_classes: int = 10,
                 batch_size: int = 32,
                 num_samples: int = 512,
                 lr: float = 1e-3,
                 config: Optional[TransformerConfig] = None):
        super().__init__()
        self.cfg = config or vit_config(size, image_size, patch_size)
        if image_size % patch_size != 0:
            raise ValueError(f"image_size={image_size} not divisible by "
                             f"patch_size={patch_size}")
        seq = (image_size // patch_size) ** 2 + 1  # patches + CLS
        if seq > self.cfg.max_seq_len:
            raise ValueError(
                f"config.max_seq_len={self.cfg.max_seq_len} is too small "
                f"for image_size={image_size}/patch_size={patch_size} "
                f"({seq} tokens incl. CLS) — build the config with "
                "vit_config(image_size=..., patch_size=...) matching the "
                "module arguments")
        self.image_size = image_size
        self.patch_size = patch_size
        self.num_classes = num_classes
        self.batch_size = batch_size
        self.num_samples = num_samples
        self.lr = lr

    def configure_model(self):
        return ViTClassifier(self.cfg, self.num_classes, self.patch_size)

    def configure_optimizers(self):
        return optax.adamw(self.lr, weight_decay=0.05)

    def _loader(self, seed: int, shuffle: bool = False):
        x, y = _synthetic_images(self.num_samples, self.image_size,
                                 self.num_classes, seed)
        return DataLoader(ArrayDataset(x, y), batch_size=self.batch_size,
                          shuffle=shuffle)

    def train_dataloader(self):
        return self._loader(seed=0, shuffle=True)

    def val_dataloader(self):
        return self._loader(seed=1)

    def test_dataloader(self):
        return self._loader(seed=2)

    def init_variables(self, model, rng, batch):
        return model.init(rng, batch[0])

    def training_step(self, model, variables, batch, rng):
        images, labels = batch
        deterministic = self.cfg.dropout == 0.0
        rngs = None if deterministic else {"dropout": rng}
        logits = model.apply(variables, images,
                             deterministic=deterministic, rngs=rngs)
        loss = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, labels))
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(
            jnp.float32))
        self.log("train_acc", acc)
        return loss

    def validation_step(self, model, variables, batch, rng):
        images, labels = batch
        logits = model.apply(variables, images, deterministic=True)
        loss = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, labels))
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(
            jnp.float32))
        return {"val_loss": loss, "val_acc": acc}

    def test_step(self, model, variables, batch, rng):
        logs = self.validation_step(model, variables, batch, rng)
        return {"test_loss": logs["val_loss"], "test_acc": logs["val_acc"]}
