"""Pallas fused dequant-matmul: stream int8/int4 weight CODES into the
matmul kernel and kill the per-dispatch dequant pass.

PR 11's weight-only quantization cut at-rest param bytes to
0.25x/0.14x, but ``dequantize_params`` still materialized a
full-precision parameter tree at every program entry — so the
per-dispatch HBM byte stream, the thing decode is bound by, never
shrank (quantized decode honestly LOSES wall-clock on hosts without
convert-into-GEMM fusion; ``docs/performance.md`` round 11). This
kernel is the weight-side sibling of ``models/pallas_attention.py``
(which closed the same gap for KV codes): the projection matmuls
consume the quantized codes DIRECTLY —

- **codes+scales in, no dense weight anywhere** — the weight operand
  of each grid step is a ``(tile_k, tile_n)`` block of int8 codes (int4:
  nibble-packed ``(tile_k, tile_n/2)``) plus its scale block, streamed
  HBM→VMEM by the BlockSpec pipeline. Unpacking and the ``codes x
  scales`` multiply happen on the VMEM block right before the dot; the
  only full-precision weight in existence is one tile of VMEM scratch
  per grid step. The per-dispatch param byte stream drops to the
  codes+scales floor ``models/quant.py param_bytes`` already accounts.
- **in-kernel int4 nibble unpack** — arithmetic-shift sign extension on
  int32 views (:func:`unpack_int4_block`, pinned value-for-value
  against ``quant.unpack_int4`` over all 16 codes), low nibble first,
  exactly the ``pack_int4`` layout.
- **per-output-channel / per-group scales on the block** — int8 scales
  broadcast along the tile's contraction rows; int4 group scales apply
  on the ``(rows, tile/group_size, group_size)`` grouped view. Scales
  are never folded into the activations: the dequantized block is the
  same element-wise ``codes x scale`` product the XLA path computes,
  which is what makes the identity contract below possible.
- **both weight orientations** — ``transpose=False`` contracts the
  stored leaf's axis 0 (every Dense/DenseGeneral kernel: qkv, out,
  mlp up/down, the untied lm_head); ``transpose=True`` contracts the
  stored last axis (the tied LM head, ``wte.attend``'s ``x @ E.T`` —
  the same codes the embedding LOOKUP gathers row-wise).

Identity contract (the ``models/pallas_attention.py`` precedent): at
the default tiling — full K per grid step, output tiled over (M, N) —
the kernel's dot has the dequantize-then-XLA-matmul path's exact
per-element reduction, and under **interpret mode** on the CPU tier it
is bitwise that path (pinned by ``tests/test_pallas_matmul.py``; the
engine suites ENFORCE greedy token identity at 0 mismatches on top).
``tile_k < K`` splits the contraction into f32-accumulated partial
dots — the TPU occupancy lever, where Mosaic tile scheduling reorders
reductions anyway and the documented fallback is the PR 11
teacher-forced-agreement contract (``docs/serving.md``).

Engines select this path with ``ServeEngine/ServeClient(...,
matmul_kernel="pallas")`` (requires ``weight_dtype=``; the cfg field
``TransformerConfig.matmul_kernel`` is the source of truth the layers
dispatch on, so supervisor rebuilds and fleet replicas re-select
identical programs). ``quant.materialize_for_program`` then skips the
program-entry dequant and the codes flow through jit as pytree leaves.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_lightning_tpu.models.pallas_attention import interpret_default
from ray_lightning_tpu.models.quant import QTensor, matmul_view

__all__ = ["quantized_matmul", "unpack_int4_block", "kernel_calls"]

#: default caps for the derived output tile (largest divisor of the
#: axis at or under the cap) — one (tile_m, tile_n) f32 out block plus
#: the K-long code and x panels stay far under the ~16 MB VMEM budget.
#: tile_m needs the cap too: M is the FLATTENED token count, and a
#: prefill/verify dispatch's (M, K) x panel would otherwise ride into
#: one grid step whole (decode steps sit far below it either way).
#: Output tiling never touches an element's reduction order, so the
#: caps are invisible to the bitwise identity contract.
DEFAULT_TILE_N = 512
DEFAULT_TILE_M = 256

#: trace-time counter of kernel instantiations — the bench's witness
#: that a "fused" leg actually armed the kernel (a cached program does
#: not retrace, so snapshot it before the first compile of the leg)
_KERNEL_CALLS = 0


def kernel_calls() -> int:
    """How many times :func:`quantized_matmul` has traced a kernel this
    process (compile-time count, not per-dispatch)."""
    return _KERNEL_CALLS


def unpack_int4_block(packed: jax.Array) -> jax.Array:
    """In-kernel sibling of ``quant.unpack_int4``: sign-extend both
    nibbles of each byte and re-interleave to the doubled last axis —
    value-for-value identical (pinned over all 16 codes), but shifted
    in int32 (int8 shifts are a Mosaic lowering gap; interpret mode
    computes the same values either way)."""
    p = packed.astype(jnp.int32)
    lo = jnp.right_shift(jnp.left_shift(p, 28), 28)  # arithmetic
    hi = jnp.right_shift(p, 4)   # p is sign-extended: == int8 >> 4
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(*packed.shape[:-1], 2 * packed.shape[-1])


def _dequant_block(q_blk, s_blk, *, bits: int, group_size: Optional[int],
                   param_dtype, compute_dtype):
    """codes x scales -> one weight tile in compute dtype, the exact
    element-wise product chain of ``QTensor.dequantize`` followed by
    flax's promote-to-compute-dtype (so a full-K dot over this block is
    bitwise the dequantize-then-XLA path)."""
    if bits == 8:
        w = q_blk.astype(jnp.float32) * s_blk          # s (1, cols)
    else:
        codes = unpack_int4_block(q_blk).astype(jnp.float32)
        rows = codes.shape[0]
        grouped = codes.reshape(rows, -1, group_size)
        w = (grouped * s_blk[:, :, None]).reshape(codes.shape)
    return w.astype(param_dtype).astype(compute_dtype)


def _kernel(x_ref, q_ref, s_ref, o_ref, *acc, bits, group_size,
            dims, nk, param_dtype, compute_dtype):
    """One (m, n, k) grid step. ``nk == 1`` (the default and the
    identity contract): ONE dot over the full contraction, no
    ``preferred_element_type`` override — the exact dot the XLA path
    runs on the promoted operands, and no scratch exists. ``nk > 1``:
    f32-accumulated partial dots in VMEM scratch (TPU tiling mode; fp
    reordering documented)."""
    w = _dequant_block(q_ref[...], s_ref[...], bits=bits,
                       group_size=group_size, param_dtype=param_dtype,
                       compute_dtype=compute_dtype)
    if nk == 1:
        o_ref[...] = jax.lax.dot_general(x_ref[...], w, dims)
        return
    acc_ref = acc[0]
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], w, dims, preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _emit():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _largest_divisor(n: int, cap: int, align: int) -> int:
    """Largest divisor of ``n`` that is <= cap and a multiple of
    ``align`` (falls back to ``n`` itself — ``align`` always divides
    ``n`` for the layouts quantize_params produces)."""
    for d in range(min(cap, n), 0, -1):
        if n % d == 0 and d % align == 0:
            return d
    return n


def quantized_matmul(x: jax.Array, qt: QTensor, *,
                     transpose: bool = False,
                     tile_m: Optional[int] = None,
                     tile_n: Optional[int] = None,
                     tile_k: Optional[int] = None,
                     interpret: Optional[bool] = None) -> jax.Array:
    """``x (..., K) @ dequantize(qt) -> (..., N)`` with the dequant
    fused into the matmul kernel — no dense weight materializes.

    ``transpose=False`` contracts ``qt``'s stored axis 0 and flattens
    the remaining axes to ``N`` (the caller reshapes to its feature
    dims); ``transpose=True`` contracts the stored LAST axis (the tied
    LM head's ``x @ E.T``). Output dtype is ``x.dtype`` — callers
    promote to compute dtype first, exactly like flax's Dense.

    Tiling: ``tile_k`` defaults to the full contraction (the bitwise
    mode); ``tile_m``/``tile_n`` default to the largest divisor of
    their axis at or under :data:`DEFAULT_TILE_M` /
    :data:`DEFAULT_TILE_N` (group-aligned for int4 — output tiling is
    invisible to the identity contract). Every tile must
    divide its axis exactly — a ragged final tile raises (the compiled
    fixed-shape serve programs must never mask a partial block
    silently) — and int4 group boundaries must not split across tiles:
    ``group_size`` must divide ``tile_n`` (dense orientation) or
    ``tile_k`` (transpose orientation, where the groups ride the
    contraction axis).
    """
    codes, scales, K, N = matmul_view(qt, transpose)
    if x.shape[-1] != K:
        raise ValueError(
            f"quantized_matmul contraction mismatch: x has "
            f"{x.shape[-1]} features, the quantized leaf contracts "
            f"over {K}")
    lead = x.shape[:-1]
    x2d = x.reshape(-1, K)
    M = x2d.shape[0]
    gs = qt.group_size if qt.bits == 4 else 1
    if tile_m is None:
        tile_m = _largest_divisor(M, DEFAULT_TILE_M, 1)
    tile_k = K if tile_k is None else tile_k
    if tile_n is None:
        tile_n = _largest_divisor(
            N, DEFAULT_TILE_N, gs if not transpose else 1)
        # divisor-poor N (an unpadded 50257-class vocab on the LM
        # head: 50257 = 29 x 1733, no divisor in (29, 512]) would
        # otherwise degrade to sliver tiles — thousands of grid steps
        # of lane-misaligned blocks Mosaic can't lower. Fall back to
        # ONE full-width tile: bitwise-identical (output tiling never
        # touches a reduction), fine under interpret mode; on a real
        # TPU pad the vocab to a friendly multiple instead (standard
        # practice) or pass tile_n explicitly.
        if tile_n < min(N, 128):
            tile_n = N
    for name, tile, dim in (("tile_m", tile_m, M), ("tile_n", tile_n, N),
                            ("tile_k", tile_k, K)):
        if tile < 1 or dim % tile:
            raise ValueError(
                f"{name}={tile} does not divide its axis ({dim}): the "
                "kernel's fixed-shape grid would leave a ragged final "
                "tile — pick a tile that divides the axis exactly")
    if qt.bits == 4:
        group_axis, tile_g = (("tile_k", tile_k) if transpose
                              else ("tile_n", tile_n))
        if tile_g % qt.group_size:
            raise ValueError(
                f"group_size ({qt.group_size}) must divide {group_axis} "
                f"({tile_g}): int4 scale groups ride the "
                f"{'contraction' if transpose else 'output'} axis and "
                "a tile boundary must not split a group")
    if interpret is None:
        interpret = interpret_default()

    nm, nn, nk = M // tile_m, N // tile_n, K // tile_k
    pack = 2 if qt.bits == 4 else 1

    if transpose:
        # codes (N, K/pack): rows = output tile, cols = contraction
        q_spec = pl.BlockSpec((tile_n, tile_k // pack),
                              lambda i, j, kk: (j, kk))
        if qt.bits == 8:
            s_spec = pl.BlockSpec((1, tile_k), lambda i, j, kk: (0, kk))
        else:
            s_spec = pl.BlockSpec((tile_n, tile_k // gs),
                                  lambda i, j, kk: (j, kk))
        dims = (((1,), (1,)), ((), ()))
    else:
        # codes (K, N/pack): rows = contraction, cols = output tile
        q_spec = pl.BlockSpec((tile_k, tile_n // pack),
                              lambda i, j, kk: (kk, j))
        if qt.bits == 8:
            s_spec = pl.BlockSpec((1, tile_n), lambda i, j, kk: (0, j))
        else:
            s_spec = pl.BlockSpec((tile_k, tile_n // gs),
                                  lambda i, j, kk: (kk, j))
        dims = (((1,), (0,)), ((), ()))

    kernel = functools.partial(
        _kernel, bits=qt.bits, group_size=qt.group_size, dims=dims,
        nk=nk, param_dtype=qt.dtype, compute_dtype=x.dtype)
    global _KERNEL_CALLS
    _KERNEL_CALLS += 1
    out = pl.pallas_call(
        kernel,
        grid=(nm, nn, nk),
        in_specs=[pl.BlockSpec((tile_m, tile_k), lambda i, j, kk: (i, kk)),
                  q_spec, s_spec],
        out_specs=pl.BlockSpec((tile_m, tile_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        # f32 partial-dot accumulator — only the nk > 1 tiling needs it
        scratch_shapes=(
            [pltpu.VMEM((tile_m, tile_n), jnp.float32)] if nk > 1
            else []),
        interpret=interpret,
    )(x2d, codes, scales)
    return out.reshape(*lead, N)
