"""Causal LM whose transformer blocks run as pipeline stages over ``pp``.

The Trainer-integrated pipeline-parallel path: blocks' parameters live in
one stacked subtree (leading layers dim, param name ``blocks``) created by
vmapping :class:`TransformerBlock`'s own init — the block *math* is reused
verbatim, only the parameter layout changes. The stack is applied through
:func:`~ray_lightning_tpu.parallel.pipeline.pipelined_stack`, which runs
the GPipe microbatch schedule whenever the strategy's mesh has a ``pp``
axis (registered by the trainer, same pattern as ring attention) and falls
back to a serial scan otherwise — so the SAME model trains on a plain dp
mesh or a dp×pp mesh with identical numerics (asserted in
``tests/test_pipeline.py``).

Pair with::

    MeshStrategy(axes={"pp": 4, "dp": 2},
                 param_rule=pipeline_parallel_rule)

so the stacked blocks (and their optimizer moments) are placed on their
stages up front.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.data.loader import ArrayDataset, DataLoader
from ray_lightning_tpu.models.gpt import synthetic_tokens
from ray_lightning_tpu.models.transformer import (TransformerBlock,
                                                  TransformerConfig)
from ray_lightning_tpu.parallel.pipeline import pipelined_stack


class PipelinedTransformerLM(nn.Module):
    """GPT-style causal LM with a pipeline-ready stacked block subtree."""
    cfg: TransformerConfig
    n_microbatches: Optional[int] = None

    @nn.compact
    def __call__(self, tokens, deterministic: bool = True):
        cfg = self.cfg
        if cfg.dropout > 0.0:
            # the functional block.apply inside the pipeline carries no
            # PRNG streams; silently training without the configured
            # dropout would be worse than refusing
            raise NotImplementedError(
                "PipelinedTransformerLM does not support dropout (no PRNG "
                "threading through pipeline stages yet); set dropout=0.0.")
        B, T = tokens.shape
        block = TransformerBlock(cfg)

        def init_blocks(rng):
            dummy = jnp.zeros((1, 1, cfg.d_model), cfg.dtype)
            return jax.vmap(
                lambda r: block.init(r, dummy)["params"])(
                    jax.random.split(rng, cfg.n_layers))

        stacked = self.param("blocks", init_blocks)

        wte = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wte")
        x = wte(tokens)
        pos = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
        x = x + nn.Embed(cfg.max_seq_len, cfg.d_model, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="wpe")(pos)

        def layer_fn(p, h):
            return block.apply({"params": p}, h,
                               deterministic=deterministic)

        x = pipelined_stack(layer_fn, stacked, x,
                            n_microbatches=self.n_microbatches)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        return wte.attend(x).astype(jnp.float32)


class PipelinedLMModule(TpuModule):
    """Training module for :class:`PipelinedTransformerLM`."""

    def __init__(self, config: Optional[TransformerConfig] = None,
                 n_layers: int = 4, d_model: int = 64, n_heads: int = 2,
                 batch_size: int = 8, seq_len: int = 64,
                 num_samples: int = 256, lr: float = 1e-3,
                 vocab_size: int = 256,
                 n_microbatches: Optional[int] = None):
        super().__init__()
        if config is None:
            config = TransformerConfig(
                vocab_size=vocab_size, max_seq_len=seq_len,
                d_model=d_model, n_heads=n_heads, n_layers=n_layers,
                d_ff=4 * d_model, causal=True, scan_layers=False)
        self.cfg = config
        self.batch_size = batch_size
        self.seq_len = min(seq_len, config.max_seq_len)
        self.num_samples = num_samples
        self.lr = lr
        self.n_microbatches = n_microbatches

    def configure_model(self):
        return PipelinedTransformerLM(self.cfg,
                                      n_microbatches=self.n_microbatches)

    def configure_optimizers(self):
        return optax.adamw(self.lr, weight_decay=0.01)

    def _loader(self, seed: int, shuffle: bool = False):
        toks = synthetic_tokens(self.num_samples, self.seq_len + 1,
                                self.cfg.vocab_size, seed=seed)
        return DataLoader(ArrayDataset((toks[:, :-1], toks[:, 1:])),
                          batch_size=self.batch_size, shuffle=shuffle)

    def train_dataloader(self):
        return self._loader(0, shuffle=True)

    def val_dataloader(self):
        return self._loader(1)

    def init_variables(self, model, rng, batch):
        return model.init(rng, batch[0])

    def training_step(self, model, variables, batch, rng):
        inputs, targets = batch
        logits = model.apply(variables, inputs)
        loss = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, targets))
        self.log("train_ppl", jnp.exp(loss))
        return loss

    def validation_step(self, model, variables, batch, rng):
        inputs, targets = batch
        logits = model.apply(variables, inputs)
        loss = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, targets))
        return {"val_loss": loss}
